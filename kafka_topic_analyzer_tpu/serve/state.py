"""Lock-consistent report snapshots for the service HTTP surface.

The snapshot-consistency rule (DESIGN.md §18): the follow drive loop
assembles and serializes a full report document at each poll boundary and
*publishes* it here; the ``/report.json`` handler (obs/exporters.py) only
ever *reads* the latest published bytes.  The lock below guards a single
reference swap on publish and a single reference read on serve — both
O(1) — so a scrape returns in microseconds and can never block folding,
and a publish can never block on a slow client.  Handlers must not reach
any deeper: ``report_bytes``/``snapshot``/``entry`` are the ONLY
sanctioned accessors (tools/lint.sh rule 9 rejects handler code that
calls into the drive loop or takes any other fold-state lock).

Since the read-path PR (DESIGN.md §26) a publish produces one immutable
``PublishedReport`` — ``(raw bytes, gzipped bytes, ETag, seq)`` encoded
ONCE on the publishing side — so conditional requests (`If-None-Match`)
and `Accept-Encoding: gzip` responses cost the handler O(headers): no
per-request ``json.dumps``, no per-request ``gzip.compress``, and no way
for a reader racing a publish to observe a torn triple (body, encoding,
and validator always belong to the same seq, because they live on the
same object and the swap is one reference assignment).

The monotone ``seq`` (one counter across the main slot and every fleet
topic slot) is the cache validator AND the SSE event id: each publish is
also offered to the session's SSE publisher (serve/push.py) so `/events`
subscribers learn about new snapshots without polling.

Module-level ``active()``/``set_active()`` mirror obs/flight.py: the CLI
registers the running service's state for the session so the exporter —
which predates this package and must not import it eagerly — can look it
up per request.
"""

from __future__ import annotations

import gzip as _gzip
import json
import threading
import time
from typing import Callable, Optional

from kafka_topic_analyzer_tpu.config import DEFAULT_SERVE
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

#: Gzip level for publish-time encoding (config.ServeConfig): 6 is the
#: classic wire default — ~10× on report JSON, a low-single-digit-ms
#: cost paid once per poll boundary, never per request.
GZIP_LEVEL = DEFAULT_SERVE.gzip_level

#: Bodies smaller than this are not worth a gzip member's overhead; the
#: publish stores no gzip variant and every client gets identity (the
#: fallback is visible in kta_serve_bytes_total{encoding="identity"}).
MIN_GZIP_BYTES = DEFAULT_SERVE.gzip_min_bytes


class PublishedReport:
    """One published snapshot: the atomic (raw, gzipped, etag) triple.

    Immutable after construction — handlers hold a reference and can
    serve from it long after a newer seq replaced it in the slot, which
    is exactly what makes the torn-triple race impossible: there is no
    moment where the body belongs to one publish and the validator or
    encoding to another.
    """

    __slots__ = (
        "seq", "doc", "body", "gzipped", "etag", "etag_gzip",
        "published_at", "topic", "summary",
    )

    def __init__(
        self,
        seq: int,
        doc: dict,
        body: bytes,
        gzipped: "Optional[bytes]",
        published_at: float,
        topic: "Optional[str]",
        summary: dict,
    ):
        self.seq = seq
        self.doc = doc
        self.body = body
        self.gzipped = gzipped
        #: Strong validators.  The representation rule (RFC 9110 §8.8.3):
        #: the gzip representation carries its own ETag so a cache can
        #: never conflate the two encodings of one seq.
        self.etag = f'"r{seq}"'
        self.etag_gzip = f'"r{seq}+gzip"'
        self.published_at = published_at
        self.topic = topic
        #: Compact delta summary for the SSE event (serve/push.py):
        #: seq + topic + sizes + whatever the drive loop passed along
        #: (records folded, lag, pass count) — NOT the document itself.
        self.summary = summary


class ServiceState:
    """Latest published report document, pre-serialized AND pre-encoded.

    Serialization and compression happen on the PUBLISHING side (the
    drive loop, once per poll boundary) — never per scrape — so N
    dashboard scrapes cost N reference reads, not N ``json.dumps`` (or
    N ``gzip.compress``) of a large document.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        instance: "Optional[str]" = None,
        gzip_enabled: bool = True,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        #: Analyzer instance id stamped on every published document
        #: (fleet federation, DESIGN §23) — None keeps solo documents
        #: byte-identical to pre-fleet output.
        self._instance = instance
        #: Publish-time gzip toggle (``--serve-gzip off`` disables the
        #: stored variant; handlers then serve identity to everyone).
        self._gzip_enabled = bool(gzip_enabled)
        #: Monotone publish counter — ONE sequence across the main slot
        #: and every fleet topic slot, so each publish anywhere gets a
        #: process-unique strong validator and SSE event id.
        self._seq = 0
        self._entry: "Optional[PublishedReport]" = None
        #: Fleet mode: topic -> PublishedReport per-topic documents,
        #: published by the fleet service after each topic's pass and
        #: served at ``/report.json?topic=<name>``.  The main slot above
        #: is then the cluster ROLLUP.  Same locking discipline:
        #: per-topic publishes swap one dict entry; reads are one lookup.
        self._topic_entries: "dict[str, PublishedReport]" = {}

    def publish(
        self,
        doc: dict,
        topic: "Optional[str]" = None,
        summary: "Optional[dict]" = None,
    ) -> PublishedReport:
        """Swap in a new point-in-time report document (drive-loop side).
        The document is stamped (``report_ts``, ``seq``), serialized,
        and gzip-encoded here, then installed under the lock in one
        assignment.  With ``topic`` set, the document lands in that
        topic's fleet slot instead of the main (single-topic report /
        fleet rollup) slot.  ``summary`` rides the SSE event as the
        compact delta block dashboards read without fetching the body."""
        doc = dict(doc)
        doc["report_ts"] = round(self._clock(), 3)
        if self._instance is not None:
            doc["instance"] = self._instance
        with self._lock:
            self._seq += 1
            seq = self._seq
        doc["seq"] = seq
        # Encode OUTSIDE the lock: a reader's reference read never waits
        # on json/gzip of a large document — only on the swap below.
        body = json.dumps(doc).encode()
        gz: "Optional[bytes]" = None
        if self._gzip_enabled and len(body) >= MIN_GZIP_BYTES:
            # mtime=0 keeps the member deterministic: one seq, one exact
            # gzip byte string, so validators and bodies can be compared
            # across retries in tests and caches.
            gz = _gzip.compress(body, GZIP_LEVEL, mtime=0)
            if len(gz) >= len(body):
                gz = None  # incompressible: serve identity to everyone
        event = {
            "seq": seq,
            "topic": topic,
            "report_ts": doc["report_ts"],
            "bytes": len(body),
        }
        if self._instance is not None:
            event["instance"] = self._instance
        if summary:
            event.update(summary)
        entry = PublishedReport(
            seq, doc, body, gz, doc["report_ts"], topic, event
        )
        with self._lock:
            if topic is not None:
                self._topic_entries[topic] = entry
            else:
                self._entry = entry
        obs_metrics.REPORT_SNAPSHOTS.inc()
        # Poll-boundary SSE feed: hand the entry to the session's push
        # publisher (if one runs).  offer() is a bounded O(1) enqueue on
        # the publisher's intake — fan-out to subscriber queues happens
        # on the publisher's own thread, never the drive loop.
        from kafka_topic_analyzer_tpu.serve import push as _push

        pub = _push.active()
        if pub is not None:
            pub.offer(entry)
        return entry

    def entry(
        self, topic: "Optional[str]" = None
    ) -> "Optional[PublishedReport]":
        """The latest published triple (HTTP-handler side), or None
        before the first publish.  One lock acquire, one reference read.
        With ``topic`` set: that topic's latest fleet entry (None for an
        unknown/not-yet-published topic)."""
        with self._lock:
            if topic is not None:
                return self._topic_entries.get(topic)
            return self._entry

    def report_bytes(self, topic: "Optional[str]" = None) -> "Optional[bytes]":
        """The latest serialized report, or None before the first
        publish (back-compat accessor; ``entry`` carries the triple)."""
        e = self.entry(topic)
        return e.body if e is not None else None

    def snapshot(self, topic: "Optional[str]" = None) -> "Optional[dict]":
        """The latest report document (test/introspection side)."""
        e = self.entry(topic)
        return e.doc if e is not None else None

    def topics(self) -> "list[str]":
        """Topic names with a published fleet document (sorted)."""
        with self._lock:
            return sorted(self._topic_entries)

    @property
    def seq(self) -> int:
        """Highest seq published so far (0 before the first publish)."""
        with self._lock:
            return self._seq

    @property
    def published_at(self) -> "Optional[float]":
        e = self.entry()
        return e.published_at if e is not None else None


_active: "Optional[ServiceState]" = None


def set_active(state: "Optional[ServiceState]") -> None:
    global _active
    _active = state


def active() -> "Optional[ServiceState]":
    return _active
