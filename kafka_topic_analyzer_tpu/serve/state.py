"""Lock-consistent report snapshots for the service HTTP surface.

The snapshot-consistency rule (DESIGN.md §18): the follow drive loop
assembles and serializes a full report document at each poll boundary and
*publishes* it here; the ``/report.json`` handler (obs/exporters.py) only
ever *reads* the latest published bytes.  The lock below guards a single
reference swap on publish and a single reference read on serve — both
O(1) — so a scrape returns in microseconds and can never block folding,
and a publish can never block on a slow client.  Handlers must not reach
any deeper: ``report_bytes``/``snapshot`` are the ONLY sanctioned
accessors (tools/lint.sh rule 9 rejects handler code that calls into the
drive loop or takes any other fold-state lock).

Module-level ``active()``/``set_active()`` mirror obs/flight.py: the CLI
registers the running service's state for the session so the exporter —
which predates this package and must not import it eagerly — can look it
up per request.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics


class ServiceState:
    """Latest published report document, pre-serialized.

    Serialization happens on the PUBLISHING side (the drive loop, once
    per poll boundary) — never per scrape — so N dashboard scrapes cost N
    reference reads, not N ``json.dumps`` of a large document.
    """

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._clock = clock
        self._doc: "Optional[dict]" = None
        self._bytes: "Optional[bytes]" = None
        self._published_at: "Optional[float]" = None

    def publish(self, doc: dict) -> None:
        """Swap in a new point-in-time report document (drive-loop side).
        The document is stamped (``report_ts``) and serialized here, then
        installed under the lock in one assignment."""
        doc = dict(doc)
        doc["report_ts"] = round(self._clock(), 3)
        body = json.dumps(doc).encode()
        with self._lock:
            self._doc = doc
            self._bytes = body
            self._published_at = doc["report_ts"]
        obs_metrics.REPORT_SNAPSHOTS.inc()

    def report_bytes(self) -> "Optional[bytes]":
        """The latest serialized report (HTTP-handler side), or None
        before the first publish.  One lock acquire, one reference read."""
        with self._lock:
            return self._bytes

    def snapshot(self) -> "Optional[dict]":
        """The latest report document (test/introspection side)."""
        with self._lock:
            return self._doc

    @property
    def published_at(self) -> "Optional[float]":
        with self._lock:
            return self._published_at


_active: "Optional[ServiceState]" = None


def set_active(state: "Optional[ServiceState]") -> None:
    global _active
    _active = state


def active() -> "Optional[ServiceState]":
    return _active
