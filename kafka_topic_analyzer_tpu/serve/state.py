"""Lock-consistent report snapshots for the service HTTP surface.

The snapshot-consistency rule (DESIGN.md §18): the follow drive loop
assembles and serializes a full report document at each poll boundary and
*publishes* it here; the ``/report.json`` handler (obs/exporters.py) only
ever *reads* the latest published bytes.  The lock below guards a single
reference swap on publish and a single reference read on serve — both
O(1) — so a scrape returns in microseconds and can never block folding,
and a publish can never block on a slow client.  Handlers must not reach
any deeper: ``report_bytes``/``snapshot`` are the ONLY sanctioned
accessors (tools/lint.sh rule 9 rejects handler code that calls into the
drive loop or takes any other fold-state lock).

Module-level ``active()``/``set_active()`` mirror obs/flight.py: the CLI
registers the running service's state for the session so the exporter —
which predates this package and must not import it eagerly — can look it
up per request.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics


class ServiceState:
    """Latest published report document, pre-serialized.

    Serialization happens on the PUBLISHING side (the drive loop, once
    per poll boundary) — never per scrape — so N dashboard scrapes cost N
    reference reads, not N ``json.dumps`` of a large document.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.time,
        instance: "Optional[str]" = None,
    ):
        self._lock = threading.Lock()
        self._clock = clock
        #: Analyzer instance id stamped on every published document
        #: (fleet federation, DESIGN §23) — None keeps solo documents
        #: byte-identical to pre-fleet output.
        self._instance = instance
        self._doc: "Optional[dict]" = None
        self._bytes: "Optional[bytes]" = None
        self._published_at: "Optional[float]" = None
        #: Fleet mode: topic -> (doc, bytes) per-topic documents, published
        #: by the fleet service after each topic's pass and served at
        #: ``/report.json?topic=<name>``.  The main document slot above is
        #: then the cluster ROLLUP.  Same locking discipline: per-topic
        #: publishes swap one dict entry; reads are one lookup.
        self._topic_docs: "dict[str, tuple[dict, bytes]]" = {}

    def publish(self, doc: dict, topic: "Optional[str]" = None) -> None:
        """Swap in a new point-in-time report document (drive-loop side).
        The document is stamped (``report_ts``) and serialized here, then
        installed under the lock in one assignment.  With ``topic`` set,
        the document lands in that topic's fleet slot instead of the main
        (single-topic report / fleet rollup) slot."""
        doc = dict(doc)
        doc["report_ts"] = round(self._clock(), 3)
        if self._instance is not None:
            doc["instance"] = self._instance
        body = json.dumps(doc).encode()
        with self._lock:
            if topic is not None:
                self._topic_docs[topic] = (doc, body)
            else:
                self._doc = doc
                self._bytes = body
                self._published_at = doc["report_ts"]
        obs_metrics.REPORT_SNAPSHOTS.inc()

    def report_bytes(self, topic: "Optional[str]" = None) -> "Optional[bytes]":
        """The latest serialized report (HTTP-handler side), or None
        before the first publish.  One lock acquire, one reference read.
        With ``topic`` set: that topic's latest fleet document (None for
        an unknown/not-yet-published topic)."""
        with self._lock:
            if topic is not None:
                entry = self._topic_docs.get(topic)
                return entry[1] if entry is not None else None
            return self._bytes

    def snapshot(self, topic: "Optional[str]" = None) -> "Optional[dict]":
        """The latest report document (test/introspection side)."""
        with self._lock:
            if topic is not None:
                entry = self._topic_docs.get(topic)
                return entry[0] if entry is not None else None
            return self._doc

    def topics(self) -> "list[str]":
        """Topic names with a published fleet document (sorted)."""
        with self._lock:
            return sorted(self._topic_docs)

    @property
    def published_at(self) -> "Optional[float]":
        with self._lock:
            return self._published_at


_active: "Optional[ServiceState]" = None


def set_active(state: "Optional[ServiceState]") -> None:
    global _active
    _active = state


def active() -> "Optional[ServiceState]":
    return _active
