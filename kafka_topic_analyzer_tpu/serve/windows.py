"""Time-windowed folds: a ring of associatively mergeable window states.

The cumulative analyzer state (DESIGN.md §2) answers "what has this topic
ever held"; a service sitting on a live head must also answer "what
changed in the last 5 minutes" — and no cumulative fold can, because its
merges are irreversible (HLL registers max, counters only grow).  So
follow mode runs a second, deliberately small fold layer: wall-clock time
is cut into fixed windows, each window accumulates its own `WindowState`,
and the ring keeps the most recent N of them.  Every per-window fold
obeys the same associative-merge discipline as the main state —

- per-partition record/byte/tombstone counts   merge by +
- per-partition HLL key-cardinality registers  merge by elementwise max
- per-partition log2 size-distribution buckets merge by +

— so "the last K windows" is `merge` over K states in any grouping or
order, windows from different processes could union the same way, and the
merge-unit tests can check associativity/commutativity directly
(tests/test_follow.py).

Feeding: `WindowObserver` wraps the scan's RecordSource and folds every
yielded batch before passing it through untouched — the main fold never
sees a difference (byte-identity holds with windows on or off).  The
observer intentionally does not forward the fused-sink fast path: window
cardinality needs the decoded key hashes, which the fused decode→pack
pass never materializes, so the engine books the bypass on
``kta_fused_fallback_total{reason="source-unfusable"}`` — visible, never
silent — and the scan takes the chained decode path.  Observation takes
one ring lock per batch (parallel-ingest workers call ``batches()``
concurrently) and costs a few bincounts — O(B) numpy, no Python loop.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from kafka_topic_analyzer_tpu.records import RecordBatch

#: log2 size buckets: bucket b holds sizes in [2^(b-1), 2^b), bucket 0
#: holds size 0 (tombstones / null-key records count their actual bytes).
SIZE_BUCKETS = 32


class WindowState:
    """One window's fold: fixed-shape numpy state, associative merge."""

    __slots__ = ("records", "bytes", "tombstones", "hll", "size_hist")

    def __init__(self, num_partitions: int, hll_p: int):
        p = int(num_partitions)
        self.records = np.zeros(p, dtype=np.int64)
        self.bytes = np.zeros(p, dtype=np.int64)
        self.tombstones = np.zeros(p, dtype=np.int64)
        #: Per-partition HLL registers (distinct keys seen this window).
        self.hll = np.zeros((p, 1 << hll_p), dtype=np.uint8)
        #: Per-partition log2 message-size histogram.
        self.size_hist = np.zeros((p, SIZE_BUCKETS), dtype=np.int64)

    @property
    def num_partitions(self) -> int:
        return len(self.records)

    def observe(self, rows: np.ndarray, batch: RecordBatch) -> None:
        """Fold one batch's valid records, pre-mapped to dense ``rows``."""
        p = self.num_partitions
        valid = batch.valid
        rows = rows[valid]
        if len(rows) == 0:
            return
        sizes = (batch.key_len + batch.value_len).astype(np.int64)[valid]
        self.records += np.bincount(rows, minlength=p)
        self.bytes += np.bincount(rows, weights=sizes, minlength=p).astype(
            np.int64
        )
        self.tombstones += np.bincount(
            rows[batch.value_null[valid]], minlength=p
        )
        # log2 buckets: 0 for size 0, else floor(log2(size)) + 1, capped.
        nz = sizes > 0
        buckets = np.zeros(len(sizes), dtype=np.int64)
        # Exact integer floor(log2): sizes are int64 >= 1 here, and
        # float64 represents them exactly up to 2^53 — far above any
        # record size (lengths are int32).
        buckets[nz] = (
            np.floor(np.log2(sizes[nz].astype(np.float64))).astype(np.int64)
            + 1
        )
        np.clip(buckets, 0, SIZE_BUCKETS - 1, out=buckets)
        flat = np.bincount(
            rows * SIZE_BUCKETS + buckets, minlength=p * SIZE_BUCKETS
        )
        self.size_hist += flat.reshape(p, SIZE_BUCKETS)
        # Distinct keys: the same splitmix64 bucket/rho split the scan's
        # cumulative sketch uses (packing.hll_idx_rho_numpy), scatter-max
        # into this window's per-partition registers.
        from kafka_topic_analyzer_tpu.packing import hll_idx_rho_numpy

        keyed = ~batch.key_null[valid]
        hll_p = int(np.log2(self.hll.shape[1]))
        idx, rho = hll_idx_rho_numpy(
            batch.key_hash64[valid][keyed], np.ones(int(keyed.sum()), bool),
            hll_p,
        )
        m = self.hll.shape[1]
        np.maximum.at(
            self.hll.reshape(-1),
            rows[keyed] * m + idx.astype(np.int64),
            rho,
        )

    def merge(self, other: "WindowState") -> "WindowState":
        """Associative, commutative merge — the window-ring algebra."""
        if self.hll.shape != other.hll.shape:
            raise ValueError("window states have different shapes")
        out = WindowState(self.num_partitions, int(np.log2(self.hll.shape[1])))
        out.records = self.records + other.records
        out.bytes = self.bytes + other.bytes
        out.tombstones = self.tombstones + other.tombstones
        out.hll = np.maximum(self.hll, other.hll)
        out.size_hist = self.size_hist + other.size_hist
        return out

    def cardinality(self) -> "List[float]":
        """Per-partition distinct-key estimates from this window's
        registers (ops/hll.py estimator — same math as the main sketch)."""
        from kafka_topic_analyzer_tpu.ops.hll import hll_estimate

        return [
            float(hll_estimate(self.hll[i])) if self.records[i] else 0.0
            for i in range(self.num_partitions)
        ]

    def as_dict(self, partition_ids: "List[int]", span_s: float) -> dict:
        """JSON block for one window (or a merged span of windows)."""
        total = int(self.records.sum())
        card = self.cardinality()
        return {
            "records": total,
            "bytes": int(self.bytes.sum()),
            "rate_per_s": round(total / span_s, 3) if span_s > 0 else 0.0,
            "partitions": {
                str(pid): {
                    "records": int(self.records[i]),
                    "bytes": int(self.bytes[i]),
                    "tombstones": int(self.tombstones[i]),
                    "distinct_keys_est": round(card[i], 1),
                    "size_log2_hist": _trimmed(self.size_hist[i]),
                }
                for i, pid in enumerate(partition_ids)
            },
        }


def _trimmed(hist: np.ndarray) -> "List[int]":
    """Histogram list with the all-zero tail dropped (wire thrift)."""
    nz = np.nonzero(hist)[0]
    if len(nz) == 0:
        return []
    return hist[: int(nz[-1]) + 1].astype(int).tolist()


class WindowRing:
    """The most recent N window states, rotated by wall clock.

    Bounded memory for an unbounded service: one `WindowState` per live
    window, oldest dropped as the clock advances.  ``merged(last=k)``
    answers "the last k·window_secs seconds" via the associative merge;
    ``report()`` renders the JSON block ``/report.json`` embeds.
    Thread-safe: observers fold under one lock (parallel-ingest workers
    call concurrently), readers snapshot under the same lock.
    """

    def __init__(
        self,
        partition_ids: "List[int]",
        window_secs: float = 60.0,
        window_count: int = 8,
        hll_p: int = 10,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_secs <= 0:
            raise ValueError("window_secs must be > 0")
        if window_count < 1:
            raise ValueError("window_count must be >= 1")
        self.partition_ids = sorted(int(p) for p in partition_ids)
        self._sorted = np.array(self.partition_ids, dtype=np.int64)
        self.window_secs = float(window_secs)
        self.window_count = int(window_count)
        self.hll_p = int(hll_p)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        #: window index -> state, insertion-ordered, at most window_count.
        self._states: "Dict[int, WindowState]" = {}

    def _window_of(self, now: float) -> int:
        return int((now - self._t0) // self.window_secs)

    def _prune(self, cur: int) -> None:
        """Drop states that have aged out of the ring's horizon — by
        INDEX distance from the current window, not by insertion count:
        quiet periods create no states, so an insertion-count bound would
        let a burst from hours ago linger in 'the last N windows'."""
        floor = cur - self.window_count + 1
        for wi in [w for w in self._states if w < floor]:
            del self._states[wi]

    def _state_for(self, wi: int) -> WindowState:
        st = self._states.get(wi)
        if st is None:
            st = WindowState(len(self.partition_ids), self.hll_p)
            self._states[wi] = st
            self._prune(wi)
        return st

    def observe_batch(self, batch: RecordBatch) -> None:
        rows = np.searchsorted(self._sorted, batch.partition).astype(np.int64)
        with self._lock:
            self._state_for(self._window_of(self._clock())).observe(
                rows, batch
            )

    def merged(self, last: "Optional[int]" = None) -> WindowState:
        """Associative merge of the most recent ``last`` windows (the
        whole ring horizon by default) — "what changed in the last
        last·window_secs seconds"."""
        cur = self._window_of(self._clock())
        with self._lock:
            self._prune(cur)
            floor = cur - (last or self.window_count) + 1
            states = [
                self._states[k] for k in sorted(self._states) if k >= floor
            ]
        acc = WindowState(len(self.partition_ids), self.hll_p)
        for st in states:
            acc = acc.merge(st)
        return acc

    def coverage_s(self) -> float:
        """Seconds of wall clock the ring currently spans: the horizon
        width, clamped to the ring's lifetime.  The honest denominator
        for the merged rate — it COUNTS quiet windows (they are part of
        the observed span even though they hold no state), where summing
        only the populated windows would overstate a bursty topic's rate
        by the empty fraction."""
        now = self._clock()
        return max(1e-9, min(now - self._t0,
                             self.window_count * self.window_secs))

    def report(self) -> dict:
        """The ``windows`` block of ``/report.json``: per-window summaries
        (newest last) plus the merged whole-ring view."""
        now = self._clock()
        cur = self._window_of(now)
        with self._lock:
            self._prune(cur)
            items = sorted(self._states.items())
        windows = []
        for wi, st in items:
            # The open (newest) window's rate denominator is its elapsed
            # fraction, not the full width — else a fresh window reads as
            # an artificial rate dip.
            span = self.window_secs
            if wi == cur:
                span = max(1e-9, (now - self._t0) - wi * self.window_secs)
            doc = st.as_dict(self.partition_ids, span)
            doc["window"] = wi
            doc["start_s"] = round(wi * self.window_secs, 3)
            windows.append(doc)
        merged_doc = self.merged().as_dict(self.partition_ids, self.coverage_s())
        return {
            "window_secs": self.window_secs,
            "window_count": self.window_count,
            "hll_p": self.hll_p,
            "windows": windows,
            "merged": merged_doc,
        }


class WindowObserver:
    """Source wrapper feeding a `WindowRing` from every yielded batch.

    Forwards the full RecordSource surface (watermarks, degradation,
    corruption accessors) by delegation, like io/segfile.TeeSource — but
    deliberately does NOT forward the fused-sink ``sink=`` parameter: the
    window folds need decoded key hashes (see module docstring), and the
    engine's signature check then routes the scan down the chained decode
    path and books the bypass.  Batches pass through unmodified, before
    any in-place remap, so the ring always sees true partition ids.
    """

    def __init__(self, inner, ring: WindowRing, enabled: bool = True):
        self.inner = inner
        self.ring = ring
        #: The follow service starts the observer DISABLED for the
        #: initial catch-up pass and enables it at the first poll
        #: boundary: windows answer "what changed at the live head", and
        #: streaming a year of backlog through the current wall-clock
        #: window would report all of history as having arrived "now"
        #: (rate and cardinality both nonsense until it aged out).
        self.enabled = enabled

    def __getattr__(self, name: str):
        # Everything not overridden (partitions, watermarks,
        # refresh_watermarks, degraded_partitions, corruption accessors,
        # heal_degraded, close, ...) delegates to the wrapped source —
        # including ``supports_fused_sink``, so the engine can SEE the
        # inner source's fused capability and book that this wrapper
        # dropped it (a silent capability mask would hide the bypass).
        return getattr(self.inner, name)

    def batches(
        self,
        batch_size: int,
        partitions=None,
        start_at=None,
    ) -> "Iterator[RecordBatch]":
        for batch in self.inner.batches(
            batch_size, partitions=partitions, start_at=start_at
        ):
            if self.enabled:
                self.ring.observe_batch(batch)
            yield batch
