"""Shared SIGINT/SIGTERM wiring for the long-running services.

One implementation for the follow service (serve/follow.py) and the
fleet service (fleet/service.py): first signal requests a graceful stop
at the next poll boundary (final checkpoint, final report, clean exit);
a SECOND SIGINT restores the default handler so an operator can still
hard-interrupt a stuck pass (the engine's failure path then flushes the
pending tail and writes the failure snapshot).
"""

from __future__ import annotations

from typing import Callable


def install_stop_handlers(
    request_stop: "Callable[[str], None]",
) -> "Callable[[], None]":
    """Install the graceful-stop handlers; returns a restore callable.

    ``request_stop(signal_name)`` is invoked from the handler (it must be
    thread/signal safe — both services set a threading.Event).  Install
    and restore are no-ops off the main thread (``signal.signal`` raises
    ValueError there)."""
    import signal as _signal

    prev = {}
    seen = {"n": 0}

    def handler(signum, frame):
        seen["n"] += 1
        request_stop(_signal.Signals(signum).name)
        if signum == _signal.SIGINT and seen["n"] >= 2:
            _signal.signal(_signal.SIGINT, _signal.default_int_handler)

    for sig in (_signal.SIGINT, _signal.SIGTERM):
        try:
            prev[sig] = _signal.signal(sig, handler)
        except ValueError:  # not the main thread
            pass

    def restore() -> None:
        for sig, old in prev.items():
            try:
                _signal.signal(sig, old)
            except ValueError:
                pass

    return restore
