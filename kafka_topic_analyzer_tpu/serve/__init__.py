"""Follow-mode service layer: the long-running analyzer (DESIGN.md §18).

The batch CLI scans earliest→latest and exits; this package keeps the
scan alive at the head and turns the process into a service:

- ``serve.follow``  — the tail loop: re-poll watermarks, fold new records
  incrementally through the existing engine (superbatch, parallel
  ingest, and the sharded mesh all compose unchanged), checkpoint on an
  interval, stop cleanly on SIGINT/SIGTERM;
- ``serve.windows`` — the time-windowed folds: a ring of associatively
  mergeable window states (per-window record rate, per-partition
  cardinality, size distribution) answering "what changed in the last
  5 minutes", which no cumulative fold can;
- ``serve.state``   — the lock-consistent report snapshot the HTTP layer
  serves at ``/report.json``: the drive loop PUBLISHES pre-serialized
  documents, handlers only ever READ the latest — a slow scrape can
  never stall ingest (tools/lint.sh rule 9 enforces the split).
"""

from kafka_topic_analyzer_tpu.serve.follow import FollowService  # noqa: F401
from kafka_topic_analyzer_tpu.serve.state import (  # noqa: F401
    ServiceState,
    active,
    set_active,
)
