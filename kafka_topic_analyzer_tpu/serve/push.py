"""Server-Sent-Events push channel for report publishes (DESIGN.md §26).

Polling dashboards pay one request per second forever to learn "nothing
changed".  This module inverts the flow: every ``ServiceState.publish``
(one per poll boundary) is offered here, and ``/events`` subscribers
receive one compact SSE frame per publish — snapshot seq, topic, byte
size, and the drive loop's delta summary — so a dashboard polls zero
times and fetches a body only when the seq actually moved (and then
usually gets a 304-free gzip body one conditional GET later).

Backpressure contract (the part that keeps rule 9's spirit intact):

- **The drive loop never blocks on a subscriber.**  ``offer()`` is an
  O(1) intake append + notify; formatting (``json.dumps`` of the
  summary) and fan-out writes happen on THIS module's dedicated
  publisher thread, never the drive loop and never a handler.
- **Bounded per-subscriber queues, eviction over blocking.**  Each
  subscriber owns a bounded queue of pre-formatted frames.  A slow
  client whose queue is full is EVICTED — its stream is closed and the
  drop is booked (``kta_serve_sse_dropped_total{reason="slow-client"}``,
  never silent) — because one stalled socket must not delay the frames
  every healthy subscriber is owed.
- **Catch-up on (re)connect.**  The latest frame is re-delivered to
  every new subscriber, so an evicted client that reconnects learns the
  current seq immediately instead of waiting for the next publish.

The handler side (obs/exporters.py) only calls ``subscribe`` /
``unsubscribe`` and blocking-reads frames off its own queue — it takes
no locks of its own and serializes nothing, so the extended rule 9
(no json/gzip in handlers) holds for the streaming route too.
"""

from __future__ import annotations

import json
import queue
import threading
from collections import deque
from typing import Deque, List, Optional

from kafka_topic_analyzer_tpu.config import DEFAULT_SERVE
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

#: Default bound on a subscriber's frame queue (config.ServeConfig).
#: Publishes happen once per poll boundary (~1/s), so 64 outstanding
#: frames is already a minute of a client reading nothing — past that,
#: eviction.
DEFAULT_QUEUE_LEN = DEFAULT_SERVE.sse_queue_len

#: Sentinel closing a subscriber's stream (eviction or shutdown).
CLOSE = None


class SseSubscriber:
    """One ``/events`` connection's frame queue (handler side holds it)."""

    __slots__ = ("q", "closed")

    def __init__(self, queue_len: int):
        self.q: "queue.Queue" = queue.Queue(maxsize=queue_len)
        self.closed = False

    def next_frame(self, timeout: "Optional[float]" = None):
        """Next pre-formatted frame, ``CLOSE`` when the stream ended, or
        raises ``queue.Empty`` on timeout (the handler's keepalive
        boundary)."""
        return self.q.get(timeout=timeout)


class SsePublisher:
    """The session's SSE fan-out: one intake, one publisher thread, N
    bounded subscriber queues."""

    def __init__(self, queue_len: int = DEFAULT_QUEUE_LEN):
        if queue_len < 1:
            raise ValueError("SSE queue length must be >= 1")
        self.queue_len = int(queue_len)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._intake: "Deque[object]" = deque()
        self._subs: "List[SseSubscriber]" = []
        self._last_frame: "Optional[bytes]" = None
        self._stopped = False
        self._thread: "Optional[threading.Thread]" = None
        #: Publishes seen / frames fanned out (tests + bench referee).
        self.offered = 0
        self.delivered = 0

    # -- drive-loop side ------------------------------------------------------

    def offer(self, entry) -> None:
        """Hand one published snapshot to the fan-out (O(1); called from
        ``ServiceState.publish`` at poll boundaries).  ``entry`` is a
        ``serve.state.PublishedReport`` — only its ``summary`` rides the
        wire."""
        with self._lock:
            if self._stopped:
                return
            self._intake.append(entry)
            self.offered += 1
        self._wake.set()

    # -- handler side ---------------------------------------------------------

    def subscribe(self) -> SseSubscriber:
        """Register one ``/events`` connection.  The latest frame (if
        any) is pre-queued — the catch-up contract."""
        sub = SseSubscriber(self.queue_len)
        with self._lock:
            if self._stopped:
                sub.closed = True
                sub.q.put_nowait(CLOSE)
                return sub
            if self._last_frame is not None:
                sub.q.put_nowait(self._last_frame)
            self._subs.append(sub)
        obs_metrics.SERVE_SSE_SUBSCRIBERS.inc(1)
        return sub

    def unsubscribe(self, sub: SseSubscriber) -> None:
        """Drop one connection (handler teardown; idempotent with
        eviction — whoever removes the subscriber decrements)."""
        with self._lock:
            if sub.closed or sub not in self._subs:
                return
            self._subs.remove(sub)
            sub.closed = True
        obs_metrics.SERVE_SSE_SUBSCRIBERS.inc(-1)

    @property
    def subscribers(self) -> int:
        with self._lock:
            return len(self._subs)

    # -- the publisher thread -------------------------------------------------

    def start(self) -> "SsePublisher":
        if self._thread is not None:
            raise RuntimeError("SSE publisher already started")
        self._thread = threading.Thread(
            target=self._run, name="kta-sse-publisher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Close every stream (booked ``reason="shutdown"``) and join the
        publisher thread (idempotent)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            subs, self._subs = self._subs, []
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        for sub in subs:
            sub.closed = True
            self._close_queue(sub)
            obs_metrics.SERVE_SSE_SUBSCRIBERS.inc(-1)
            obs_metrics.SERVE_SSE_DROPPED.labels(reason="shutdown").inc()

    @staticmethod
    def _close_queue(sub: SseSubscriber) -> None:
        """Make room if needed and enqueue the CLOSE sentinel so a
        blocked handler wakes up promptly."""
        try:
            sub.q.put_nowait(CLOSE)
        except queue.Full:
            try:
                sub.q.get_nowait()
            except queue.Empty:
                pass
            try:
                sub.q.put_nowait(CLOSE)
            except queue.Full:
                pass  # another closer already made the queue terminal

    def _format(self, entry) -> bytes:
        """One SSE frame: event name, seq as the event id (clients
        resume with Last-Event-ID semantics), compact JSON summary."""
        data = json.dumps(entry.summary, separators=(",", ":"))
        return (
            f"event: publish\nid: {entry.seq}\ndata: {data}\n\n"
        ).encode()

    def _run(self) -> None:
        while True:
            self._wake.wait()
            with self._lock:
                if self._stopped and not self._intake:
                    return
                batch = list(self._intake)
                self._intake.clear()
                self._wake.clear()
            for entry in batch:
                frame = self._format(entry)
                with self._lock:
                    self._last_frame = frame
                    subs = list(self._subs)
                evicted: "List[SseSubscriber]" = []
                for sub in subs:
                    try:
                        sub.q.put_nowait(frame)
                        self.delivered += 1
                    except queue.Full:
                        evicted.append(sub)
                for sub in evicted:
                    # Slow-client eviction: booked, never silent.  The
                    # handler sees CLOSE and ends the response; the
                    # client's reconnect gets catch-up.
                    with self._lock:
                        if sub in self._subs:
                            self._subs.remove(sub)
                            sub.closed = True
                        else:
                            continue
                    obs_metrics.SERVE_SSE_SUBSCRIBERS.inc(-1)
                    obs_metrics.SERVE_SSE_DROPPED.labels(
                        reason="slow-client"
                    ).inc()
                    self._close_queue(sub)


_active: "Optional[SsePublisher]" = None


def set_active(pub: "Optional[SsePublisher]") -> None:
    global _active
    _active = pub


def active() -> "Optional[SsePublisher]":
    return _active
