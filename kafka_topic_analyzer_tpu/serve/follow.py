"""The follow-mode drive loop: a batch scan that never has to end.

``--follow`` turns one invocation into a service (DESIGN.md §18): after
the initial earliest→latest pass, the loop re-polls watermarks, tails
whatever arrived, and folds it incrementally — by re-entering the SAME
``engine.run_scan`` on the SAME backend with the cursor as ``start_at``.
That is the whole trick: every fold in the analyzer is associative and
per-partition offset-ordered (DESIGN.md §2), so a chain of passes over
``[cursor, head)`` windows folds to byte-identical state as one batch
scan stopped at the same offsets — and every composition the engine
already knows (superbatch dispatch, parallel ingest fan-ins, the sharded
mesh, wire-v5 combiner rows) rides along untouched, because the service
never re-implements the drive loop, it just re-enters it.

Pass mechanics (the engine's follow hooks, engine.run_scan docstring):
one shared heartbeat rate limiter spans passes, per-pass lifecycle events
are suppressed (the service emits ONE scan_start/scan_end pair), and the
pending superbatch tail is flushed at every pass end — a poll boundary is
always a superbatch boundary, so lag stays bounded and checkpoints/
reports are always fold-consistent.

Durability: periodic checkpoints ride the engine's snapshot machinery —
within a long pass on its timer, across short passes forced at the first
poll boundary past ``--checkpoint-interval`` — and SIGINT/SIGTERM request
a stop that lands at the next boundary: final checkpoint, final report,
clean exit code.  A killed service resumes from its last periodic
checkpoint (batch or follow — the fingerprint doesn't know the
difference) with no loss and no double-count.

Reporting: after every pass the service assembles the full ``--json``
document (plus the ``follow`` and ``windows`` blocks) and publishes it to
`serve.state.ServiceState` — the lock-consistent snapshot ``/report.json``
serves without ever touching this loop.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional

from kafka_topic_analyzer_tpu.config import FollowConfig, TransportRetryConfig
from kafka_topic_analyzer_tpu.engine import ScanResult, run_scan
from kafka_topic_analyzer_tpu.io.retry import Backoff
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import health as obs_health
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.serve import state as serve_state
from kafka_topic_analyzer_tpu.serve.windows import WindowObserver, WindowRing
from kafka_topic_analyzer_tpu.utils.progress import Spinner

log = logging.getLogger(__name__)


class FollowService:
    """Own one topic's follow loop: initial pass, tail passes, shutdown.

    Construction wires the window ring (when enabled) around the source;
    ``run()`` blocks until a stop is requested — by a signal handler
    (``install_signal_handlers``), by ``request_stop`` from any thread, or
    by the ``idle_exit_s`` drain timer — and returns the final composed
    `ScanResult`, which the CLI reports exactly like a batch scan's.

    ``clock`` is injectable like Spinner/Backoff so tests pace polls
    without real sleeping; waiting always goes through the stop event, so
    a stop request interrupts any idle backoff immediately.
    """

    def __init__(
        self,
        topic: str,
        source,
        backend,
        batch_size: int,
        follow: "FollowConfig | None" = None,
        *,
        spinner: "Optional[Spinner]" = None,
        snapshot_dir: "Optional[str]" = None,
        resume: bool = False,
        start_at: "Optional[Dict[int, int]]" = None,
        prefetch_depth: int = 2,
        ingest_workers=1,
        heartbeat_every_s: float = 10.0,
        publish_reports: bool = True,
        serve_gzip: bool = True,
        health: "Optional[obs_health.HealthEngine]" = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        # Multi-CONTROLLER meshes are refused up front: the poll loop's
        # per-process decisions (new data? idle-exit? stop?) would have to
        # become lockstep collectives before each process's pass entry, or
        # one controller enters a collective pass its peers never start.
        # Single-controller meshes (all data rows local) compose fully;
        # the fleet service is ROADMAP item 2's scheduler.
        local_rows = getattr(backend, "local_rows", None)
        if (
            getattr(backend, "global_any", None) is not None
            and local_rows is not None
            and len(list(local_rows)) < backend.config.data_shards
        ):
            raise ValueError(
                "--follow does not support multi-controller meshes yet "
                "(pass entry would need per-poll lockstep agreement); "
                "run the service single-controller"
            )
        self.topic = topic
        self.backend = backend
        self.batch_size = batch_size
        self.follow = follow if follow is not None else FollowConfig()
        self.spinner = spinner or Spinner(enabled=False)
        self.snapshot_dir = snapshot_dir
        self.resume = resume
        self.start_at = start_at
        self.prefetch_depth = prefetch_depth
        self.ingest_workers = ingest_workers
        self._clock = clock
        self.heartbeat_every_s = heartbeat_every_s
        #: Assemble + publish /report.json documents at poll boundaries.
        #: The CLI turns this off when no --metrics-port server exists to
        #: serve them — a full per-partition document serialized per
        #: productive poll that nothing can ever read is pure waste.
        self.publish_reports = publish_reports
        self._heartbeat = obs_events.Heartbeat(heartbeat_every_s)
        self.ring: "Optional[WindowRing]" = None
        self._observer: "Optional[WindowObserver]" = None
        if self.follow.window_count > 0:
            self.ring = WindowRing(
                source.partitions(),
                window_secs=self.follow.window_secs,
                window_count=self.follow.window_count,
                hll_p=self.follow.window_hll_p,
                clock=clock,
            )
            # Disabled through the initial catch-up: windows describe the
            # LIVE head, and folding the historical backlog into the
            # current wall-clock window would report all of history as
            # "the last N minutes" (see WindowObserver.enabled).
            self._observer = WindowObserver(source, self.ring, enabled=False)
            self.source = self._observer
        else:
            self.source = source
        #: The alert engine this service evaluates at every poll
        #: boundary (obs/health.py): an explicit one wins (tests inject
        #: clock-driven engines), else whatever the telemetry session
        #: installed, else none — alerting is opt-in observability and
        #: the loop must not pay for an engine nobody reads.
        self.health = health if health is not None else obs_health.active()
        #: The lock-consistent /report.json snapshot (serve/state.py) —
        #: publish-time gzip encoding rides the ``--serve-gzip`` knob.
        self.state = serve_state.ServiceState(gzip_enabled=serve_gzip)
        self._stop = threading.Event()
        self._stop_reason: "Optional[str]" = None
        # Idle pacing: poll_interval floor, exponential backoff to the
        # ceiling over consecutive empty polls (io/retry.Backoff — the
        # delay schedule only; idle waits are not transport retries, so
        # they are not booked on the backoff counters).
        self._idle_backoff = Backoff(
            TransportRetryConfig(
                backoff_ms=max(1, int(self.follow.poll_interval_s * 1000)),
                backoff_max_ms=max(
                    max(1, int(self.follow.poll_interval_s * 1000)),
                    int(self.follow.idle_backoff_max_s * 1000),
                ),
            )
        )
        # Cross-pass accounting.
        self.polls = 0
        self.passes = 0
        self.cursor: "Dict[int, int]" = {}
        self._seq_total = 0
        self._service_start_offsets: "Optional[Dict[int, int]]" = None
        self._last_end: "Dict[int, int]" = {}
        #: Partitions whose regressed end watermark was held for one poll
        #: (a second consecutive regression is adopted as truncation).
        self._regress_held: "Dict[int, bool]" = {}
        self._t0 = clock()  # re-anchored at run() start
        self._last_ckpt = clock()
        self._wire_bytes = 0
        self._wire_records = 0

    # -- stopping -------------------------------------------------------------

    def request_stop(self, reason: str = "stop") -> None:
        """Ask the loop to stop at the next poll boundary (thread-safe;
        signal handlers and test drivers both land here)."""
        if not self._stop.is_set():
            self._stop_reason = reason
        self._stop.set()

    def install_signal_handlers(self):
        """SIGINT/SIGTERM → graceful stop at the next boundary; a SECOND
        SIGINT restores the default handler so an operator can still
        hard-interrupt a pass.  Shared wiring with the fleet service
        (serve/signals.py); returns a restore callable."""
        from kafka_topic_analyzer_tpu.serve.signals import (
            install_stop_handlers,
        )

        return install_stop_handlers(self.request_stop)

    # -- the loop -------------------------------------------------------------

    def run(self) -> ScanResult:
        serve_state.set_active(self.state)
        if self.health is not None:
            # The /healthz handler discovers the engine the same way the
            # /report.json handler discovers the state: module-level
            # registration, last service wins.
            obs_health.set_active(self.health)
        if self.resume and self.snapshot_dir is not None:
            # Operator banner: where will this service pick up?  Metadata
            # only — the engine's resume path pays the state load.
            from kafka_topic_analyzer_tpu.checkpoint import snapshot_info

            info = snapshot_info(
                self.snapshot_dir,
                getattr(self.backend, "snapshot_scope", None),
            )
            if info is not None:
                log.info(
                    "follow: resuming %s from a snapshot at "
                    "records_seen=%s (batch- and follow-written snapshots "
                    "are interchangeable)",
                    self.topic, info.get("records_seen"),
                )
        obs_events.emit(
            "scan_start",
            topic=self.topic,
            partitions=len(self.source.partitions()),
            batch_size=self.batch_size,
            follow=True,
        )
        self._t0 = self._clock()
        idle_streak = 0
        idle_since: "Optional[float]" = None
        # Initial catch-up: earliest→latest (or resume / --from-timestamp
        # start), exactly the batch scan this mode generalizes.
        result = self._run_pass(first=True)
        if self._observer is not None:
            # Caught up: from here every fold is live tail, which is what
            # the window ring describes.
            self._observer.enabled = True
        self._after_pass(result)
        while not self._stop.is_set():
            # Pace the metadata polls: the poll interval after progress,
            # the backed-off schedule after consecutive empty polls.  The
            # wait rides the stop event, so shutdown never waits it out.
            delay = (
                self.follow.poll_interval_s
                if idle_streak == 0
                else self._idle_backoff.delay_ms(idle_streak) / 1000.0
            )
            if idle_since is not None and self.follow.idle_exit_s is not None:
                remaining = self.follow.idle_exit_s - (
                    self._clock() - idle_since
                )
                delay = max(0.0, min(delay, remaining))
            if self._stop.wait(delay):
                break
            lag_total = self._poll()
            if self._stop.is_set():
                break
            if lag_total > 0:
                idle_streak = 0
                idle_since = None
                obs_events.emit(
                    "follow_poll",
                    poll=self.polls,
                    new_records=lag_total,
                    lag_total=lag_total,
                )
                result = self._run_pass()
                self._after_pass(result)
            else:
                idle_streak += 1
                now = self._clock()
                if idle_since is None:
                    idle_since = now
                if (
                    self.follow.idle_exit_s is not None
                    and now - idle_since >= self.follow.idle_exit_s
                ):
                    self.request_stop("idle")
                    break
                self.spinner.set_message(
                    f"[following {self.topic} | at head | "
                    f"Sq: {self._seq_total} | polls: {self.polls}]"
                )
        # Shutdown boundary: one final (usually empty) pass commits the
        # final checkpoint at a superbatch boundary and finalizes the
        # state for the closing report.
        result = self._run_pass(final=True)
        self._after_pass(result)
        obs_events.emit(
            "follow_stop",
            reason=self._stop_reason or "stop",
            polls=self.polls,
            passes=self.passes,
        )
        obs_events.emit(
            "scan_end",
            topic=self.topic,
            records=self._seq_total,
            duration_secs=result.duration_secs,
            degraded=sum(1 for p in result.degraded_partitions if p >= 0),
            corrupt_frames=sum(
                d.get("frames", 0)
                for p, d in result.corrupt_partitions.items()
                if p >= 0
            ),
        )
        # Closing heartbeat: the engine's own forced closer is suppressed
        # on follow passes (emit_lifecycle=False), so the service emits
        # it — a sub-interval run must still record one heartbeat, and
        # the drained ETA gauges must not stay stale at mid-scan values.
        rate = (
            self._seq_total / result.duration_secs
            if result.duration_secs > 0 else 0.0
        )
        for p in self._last_end:
            obs_metrics.PARTITION_ETA_SECONDS.labels(partition=p).set(0.0)
        obs_events.emit(
            "heartbeat",
            seq=self._seq_total,
            records_per_sec=round(rate, 1),
            lag_total=int(obs_metrics.FOLLOW_LAG.value),
        )
        self._heartbeat.force()
        self.spinner.finish_with_message("done")
        return result

    # -- internals ------------------------------------------------------------

    def _poll(self) -> int:
        """Refresh watermarks (through the source's retry budget) and
        recompute every lag gauge against the MOVING end offsets — the
        follow-aware replacement for the batch scan's start-snapshot lag.
        Returns the total new-record lag behind the head."""
        start_w, end_w = self.source.refresh_watermarks()
        self.polls += 1
        obs_metrics.FOLLOW_POLLS.inc()
        # End-watermark REGRESSION (stale replica answering the re-poll,
        # or a truncation the epoch fence hasn't classified yet): hold
        # the previous head for one poll instead of scanning backwards.
        # A transient stale answer recovers by the next refresh; a
        # regression that PERSISTS is the log's new truth (truncation),
        # so the second poll adopts it — the follow cursor never rewinds,
        # so an adopted shorter head drains the partition rather than
        # re-reading offsets (no double-count), and the fetch path's
        # epoch fence owns the loss accounting.  Booked
        # (kta_log_watermark_regressions_total) + emitted, never silent.
        for p, end in list(end_w.items()):
            prev = self._last_end.get(p)
            if prev is not None and end < prev:
                held = not self._regress_held.get(p, False)
                obs_metrics.LOG_WATERMARK_REGRESSIONS.inc()
                obs_events.emit(
                    "watermark_regression",
                    partition=int(p),
                    previous_end=int(prev),
                    answered_end=int(end),
                    held=bool(held),
                )
                if held:
                    self._regress_held[p] = True
                    end_w[p] = prev
                else:
                    self._regress_held.pop(p, None)
            else:
                self._regress_held.pop(p, None)
        self._last_end = dict(end_w)
        lag_total = 0
        for p, end in end_w.items():
            lag = max(0, end - self.cursor.get(p, start_w.get(p, 0)))
            lag_total += lag
            obs_metrics.PARTITION_LAG.labels(partition=p).set(lag)
        obs_metrics.FOLLOW_LAG.set(lag_total)
        self._evaluate_health()
        return lag_total

    def _evaluate_health(self) -> None:
        """One alert-engine pass at a poll boundary (DESIGN.md §22): a
        /healthz flip lands within one poll of the fault, which is the
        acceptance bar for the lag-divergence scenario."""
        if self.health is not None:
            self.health.evaluate()

    def _checkpoint_due(self) -> bool:
        if self.snapshot_dir is None:
            return False
        return (
            self._clock() - self._last_ckpt >= self.follow.checkpoint_every_s
        )

    def _run_pass(self, first: bool = False, final: bool = False) -> ScanResult:
        """One engine pass over [cursor, current watermark snapshot)."""
        force_ckpt = self.snapshot_dir is not None and (
            final or self._checkpoint_due()
        )
        result = run_scan(
            self.topic,
            self.source,
            self.backend,
            batch_size=self.batch_size,
            spinner=self.spinner,
            snapshot_dir=self.snapshot_dir,
            snapshot_every_s=self.follow.checkpoint_every_s,
            resume=self.resume and first,
            prefetch_depth=self.prefetch_depth,
            start_at=self.start_at if first else dict(self.cursor),
            heartbeat=self._heartbeat,
            ingest_workers=self.ingest_workers,
            initial_seq=self._seq_total,
            emit_lifecycle=False,
            book_once=first,
            final_snapshot=force_ckpt,
        )
        if force_ckpt:
            self._last_ckpt = self._clock()
        self.passes += 1
        obs_metrics.FOLLOW_PASSES.inc()
        self.cursor = dict(result.next_offsets)
        # The cumulative fold count doubles as the next pass's seq seed:
        # overall_count counts exactly the records every pass (and any
        # resumed snapshot) folded.
        self._seq_total = result.metrics.overall_count
        if self._service_start_offsets is None:
            self._service_start_offsets = dict(result.start_offsets)
        if result.wire is not None:
            self._wire_bytes += result.wire.bytes_total
            self._wire_records += result.wire.records
        return result

    def _after_pass(self, result: ScanResult) -> None:
        """Publish the poll-boundary report snapshot and heal partitions
        that caught back up to the head."""
        # Re-settle the lag gauges against the freshest known head: the
        # pass just moved the cursor, and leaving the pre-pass values in
        # place would report the service permanently behind (the inverse
        # of the fixed-end-offset bug this layer exists to fix).
        lag_total = 0
        for p, end in self._last_end.items():
            lag = max(0, end - self.cursor.get(p, end))
            lag_total += lag
            obs_metrics.PARTITION_LAG.labels(partition=p).set(lag)
        obs_metrics.FOLLOW_LAG.set(lag_total)
        healed = [
            p
            for p in result.degraded_partitions
            if p >= 0
            and p in self._last_end
            and self.cursor.get(p, 0) >= self._last_end[p]
        ]
        if healed and hasattr(self.source, "heal_degraded"):
            self.source.heal_degraded(healed)
            for p in healed:
                result.degraded_partitions.pop(p, None)
        # Re-anchor the per-pass result to the SERVICE view before anyone
        # reads it: cumulative duration (a pass's own wall time is
        # meaningless to a dashboard), the first pass's start offsets, and
        # the run's cumulative wire accounting — so a published snapshot
        # and the final --json can never disagree about totals.
        result.duration_secs = int(self._clock() - self._t0)
        if self._service_start_offsets is not None:
            result.start_offsets = self._service_start_offsets
        if result.wire is not None:
            result.wire.bytes_total = self._wire_bytes
            result.wire.records = self._wire_records
        # Post-pass health boundary: the lag gauges just settled against
        # the freshest head, so a pass that healed (or worsened) the
        # divergence is reflected before the report publishes.
        self._evaluate_health()
        if not self.publish_reports:
            return
        from kafka_topic_analyzer_tpu.obs.doctor import diagnose_scan
        from kafka_topic_analyzer_tpu.report import build_json_doc

        doc = build_json_doc(
            self.topic,
            result,
            diagnosis=diagnose_scan(result),
            follow=self.follow_block(result),
            windows=self.ring.report() if self.ring is not None else None,
            health=(
                self.health.alerts_block()
                if self.health is not None
                else None
            ),
        )
        # The compact delta block /events subscribers get instead of a
        # body: enough to decide whether (and what) to fetch.
        self.state.publish(
            doc,
            summary={
                "records": int(self._seq_total),
                "lag": int(obs_metrics.FOLLOW_LAG.value),
                "polls": self.polls,
                "passes": self.passes,
            },
        )

    def follow_block(self, result: "Optional[ScanResult]" = None) -> dict:
        """The ``follow`` block of the report document: service counters
        plus the exact resume cursor."""
        block = {
            "polls": self.polls,
            "passes": self.passes,
            "lag_records": int(obs_metrics.FOLLOW_LAG.value),
            "watermark_refresh_failures": int(
                obs_metrics.WATERMARK_REFRESH_FAILURES.value
            ),
            "next_offsets": {
                str(p): int(o) for p, o in sorted(self.cursor.items())
            },
        }
        return block

    def windows_report(self) -> "Optional[dict]":
        return self.ring.report() if self.ring is not None else None
