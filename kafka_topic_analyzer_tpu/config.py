"""Analyzer configuration.

The reference has no config system beyond four CLI flags and a ``--librdkafka``
key=value escape hatch (``src/main.rs:32-67``, SURVEY.md §5.6).  The TPU build
needs a few more knobs (batch shape, sketch precisions, mesh layout); they all
live here as one frozen dataclass so every layer — CLI, backends, parallel —
shares a single source of truth and jitted functions can treat it as static.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class TransportRetryConfig:
    """Transport-fault tolerance knobs for the live Kafka scan.

    Deliberately NOT part of `AnalyzerConfig`: retry pacing changes neither
    state shapes nor fold semantics, and folding it into the analyzer config
    would churn the checkpoint fingerprint (checkpoint.py) for a setting
    that has no effect on the numbers.  Mapped from the librdkafka-style
    ``--librdkafka`` overrides table in io/kafka_wire.py.
    """

    #: First delay after a transport failure (librdkafka ``retry.backoff.ms``;
    #: ``reconnect.backoff.ms`` raises it too when set higher).  Doubles per
    #: consecutive failure.
    backoff_ms: int = 100
    #: Backoff ceiling (librdkafka ``reconnect.backoff.max.ms``).
    backoff_max_ms: int = 10_000
    #: Consecutive transport failures a partition survives before it is
    #: marked *degraded* (scan continues without it) instead of retrying
    #: forever.  Non-librdkafka knob: ``transport.retry.budget``.
    retry_budget: int = 8
    #: Fractional jitter applied to every delay (librdkafka applies ±20%):
    #: a delay d is drawn uniformly from [d·(1-j), d·(1+j)].
    jitter: float = 0.2

    def __post_init__(self) -> None:
        if self.backoff_ms < 1:
            raise ValueError("retry.backoff.ms must be >= 1")
        if self.backoff_max_ms < self.backoff_ms:
            raise ValueError(
                "reconnect.backoff.max.ms must be >= retry.backoff.ms"
            )
        if self.retry_budget < 1:
            raise ValueError("transport.retry.budget must be >= 1")
        if not (0.0 <= self.jitter < 1.0):
            raise ValueError("retry jitter must be in [0, 1)")

    @classmethod
    def from_overrides(cls, overrides: dict) -> "TransportRetryConfig":
        """Pop the retry-related librdkafka-style properties out of an
        overrides dict (mutating it, like the other knob parsers in
        io/kafka_wire.py) and build the config."""
        base = int(overrides.pop("retry.backoff.ms", 100))
        # librdkafka paces reconnect attempts separately; this client runs
        # one schedule, so an explicitly higher reconnect floor wins.
        base = max(base, int(overrides.pop("reconnect.backoff.ms", base)))
        return cls(
            backoff_ms=base,
            backoff_max_ms=max(
                base, int(overrides.pop("reconnect.backoff.max.ms", 10_000))
            ),
            retry_budget=int(overrides.pop("transport.retry.budget", 8)),
        )


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Single-scan parallel-ingest sizing (``--ingest-workers``).

    Like `TransportRetryConfig`, deliberately NOT part of `AnalyzerConfig`:
    how many host threads feed the device changes neither state shapes nor
    fold semantics (the fan-in merge is exact — DESIGN.md §11), so it must
    not churn the checkpoint fingerprint.  A snapshot taken by an N-worker
    scan resumes under any other worker count.
    """

    #: ``1`` = the sequential path (today's default), ``N`` = that many
    #: partition-sharded ingest workers, ``"auto"`` = size from the host:
    #: min(cores - 1, partitions), keeping one core for the merge loop +
    #: device dispatch.  On a sharded mesh the count resolves PER
    #: CONTROLLER: ``resolve`` is called with that controller's shard
    #: partition count, and the result splits across its data rows
    #: (parallel/ingest.py::allocate_row_workers) — so the same CLI line
    #: sizes every host of a heterogeneous fleet correctly (DESIGN.md §14).
    workers: "int | str" = 1

    def __post_init__(self) -> None:
        if isinstance(self.workers, str):
            if self.workers != "auto":
                raise ValueError(
                    f"ingest workers {self.workers!r} invalid "
                    "(a positive integer, or 'auto')"
                )
        elif self.workers < 1:
            raise ValueError("ingest workers must be >= 1")

    @classmethod
    def parse(cls, text: str) -> "IngestConfig":
        """CLI spelling: a positive integer or ``auto``."""
        if text.strip().lower() == "auto":
            return cls(workers="auto")
        try:
            n = int(text)
        except ValueError:
            raise ValueError(
                f"bad --ingest-workers {text!r}: expected a positive "
                "integer or 'auto'"
            ) from None
        return cls(workers=n)

    def resolve(self, num_partitions: int) -> int:
        """Concrete worker count for a topic with ``num_partitions``
        partitions (workers beyond the partition count would sit idle —
        each partition lives in exactly one worker)."""
        import os

        if self.workers == "auto":
            # Cores this process may actually RUN on: in a cgroup/affinity
            # -limited container os.cpu_count() reports the host's cores,
            # and sizing from it would oversubscribe badly.
            try:
                cores = len(os.sched_getaffinity(0))
            except AttributeError:  # non-Linux
                cores = os.cpu_count() or 1
            want = max(1, cores - 1)
        else:
            want = int(self.workers)
        return max(1, min(want, num_partitions))


@dataclasses.dataclass(frozen=True)
class DispatchConfig:
    """Superbatch device-dispatch sizing (``--superbatch``/``--dispatch-depth``).

    Like `IngestConfig`, deliberately NOT part of `AnalyzerConfig`: how many
    packed batches ride one jitted dispatch (and how many superbatches may
    be in flight) changes neither state shapes nor fold semantics — the
    scan-folded superstep applies the K batches in exactly the order the
    sequential path would (backends/step.py::superbatch_fold), so results
    stay byte-identical and it must not churn the checkpoint fingerprint.
    A snapshot taken by a K-superbatch scan resumes under any other K or D
    (snapshots land only at superbatch boundaries — engine.py).
    """

    #: Packed batches stacked into one ``uint8[K, N]`` host array and
    #: folded by a single jitted ``lax.scan`` dispatch (state donated once
    #: per superbatch).  ``1`` = today's one-dispatch-per-batch path;
    #: ``"auto"`` restores the proven-good 2^20 records per dispatch:
    #: max(1, min(16, 2^20 // batch_size)).
    superbatch: "int | str" = 1
    #: Bound on superbatches staged/transferring while the device folds
    #: (the in-flight dispatch queue, backends/base.py::DispatchQueue).
    #: 2 = transfer of superbatch i+1 overlaps the fold of i; higher
    #: values deepen the pipeline at the cost of host+device memory for
    #: the extra staged buffers.
    depth: int = 2
    #: Guardrail on ``auto``'s fold size: at most this many records per
    #: scanned dispatch.  BENCH round 7 measured the failure mode auto must
    #: avoid — K=16 × B=2^16 (2^20 records ≈ a multi-hundred-ms synchronous
    #: fold on a host-CPU jit) regressed e2e to 0.63× because the drive
    #: thread disappears into one fold long enough to starve the ingest
    #: overlap, while K=4 at the same B measured 1.02×.  2^18 records caps
    #: the estimated fold wall at ~30-130 ms across measured rigs (~0.12
    #: µs/record host-CPU fold, ~0.04 device) — long enough to amortize
    #: dispatch overhead, short enough that backpressure stays responsive.
    #: Explicit K is never capped: an operator who asks for 16 gets 16.
    auto_fold_cap_records: int = 1 << 18

    def __post_init__(self) -> None:
        if isinstance(self.superbatch, str):
            if self.superbatch != "auto":
                raise ValueError(
                    f"superbatch {self.superbatch!r} invalid "
                    "(a positive integer, or 'auto')"
                )
        elif self.superbatch < 1:
            raise ValueError("superbatch must be >= 1")
        if self.depth < 1:
            raise ValueError("dispatch depth must be >= 1")
        if self.auto_fold_cap_records < 1:
            raise ValueError("auto fold cap must be >= 1 record")

    @classmethod
    def parse(cls, superbatch: str, depth: int = 2) -> "DispatchConfig":
        """CLI spelling: ``--superbatch K|auto`` + ``--dispatch-depth D``."""
        text = superbatch.strip().lower()
        if text == "auto":
            return cls(superbatch="auto", depth=depth)
        try:
            k = int(text)
        except ValueError:
            raise ValueError(
                f"bad --superbatch {superbatch!r}: expected a positive "
                "integer or 'auto'"
            ) from None
        return cls(superbatch=k, depth=depth)

    def resolve(self, batch_size: int) -> int:
        """Concrete K for a given batch size.  ``auto`` targets the
        proven-good 2^20 records per device dispatch (BENCH_NOTES round 2
        established 2^20 as the default batch; the axon-relay wedge forced
        B=2^16, multiplying per-dispatch overhead 16x — auto wins that
        amortization back without touching the per-batch packed layout),
        capped at 16 stacked buffers of host staging AND at
        ``auto_fold_cap_records`` per dispatch — the round-7 guardrail
        against pushing a multi-hundred-ms synchronous fold onto the drive
        thread (K=16 at B=2^16 regressed e2e to 0.63×; DESIGN.md §12)."""
        if self.superbatch == "auto":
            k = max(1, min(16, (1 << 20) // max(1, batch_size)))
            fold_cap = max(1, self.auto_fold_cap_records // max(1, batch_size))
            return min(k, fold_cap)
        return int(self.superbatch)


@dataclasses.dataclass(frozen=True)
class FollowConfig:
    """Follow-mode service knobs (``--follow`` and friends; serve/follow.py).

    Like `IngestConfig`, deliberately NOT part of `AnalyzerConfig`: how
    often the service re-polls watermarks, checkpoints, or rotates report
    windows changes neither state shapes nor fold semantics — a follow
    run's cumulative metrics are byte-identical to a batch scan stopped at
    the same offsets (DESIGN.md §18) — so none of it may churn the
    checkpoint fingerprint.  A snapshot taken by a batch scan resumes
    under ``--follow`` and vice versa.
    """

    #: Watermark re-poll cadence at the head (seconds).  Also the FLOOR of
    #: the idle backoff schedule: consecutive empty polls back off
    #: exponentially from here up to ``idle_backoff_max_s`` (reusing
    #: io/retry.Backoff), so a quiet topic costs metadata queries, not
    #: fetch spin.
    poll_interval_s: float = 1.0
    #: Idle backoff ceiling (seconds) — the longest the service sleeps
    #: between polls of a quiet topic.  Any new data resets the schedule
    #: to ``poll_interval_s``.
    idle_backoff_max_s: float = 10.0
    #: Checkpoint cadence (seconds, ``--checkpoint-interval``).  Commits
    #: happen only at superbatch boundaries (the engine's long-standing
    #: fold-consistency rule), so this is a floor, not an exact period.
    checkpoint_every_s: float = 60.0
    #: Exit cleanly after this long at the head with no new records
    #: (``--follow-idle-exit``); None = follow forever.  The "drain and
    #: stop" mode: catch up, wait out the idle window, report, exit 0.
    idle_exit_s: "float | None" = None
    #: Width of one report window (seconds) for the time-windowed folds
    #: served at /report.json (serve/windows.py).
    window_secs: float = 60.0
    #: Number of window states kept in the ring (0 disables windowed
    #: folds).  "What changed in the last 5 minutes" is the associative
    #: merge of the last ceil(300/window_secs) states.
    window_count: int = 8
    #: HLL precision for the per-window per-partition cardinality fold
    #: (2^p one-byte registers per partition per window — deliberately
    #: smaller than the scan's cumulative sketch: window memory is
    #: P * 2^p * window_count bytes).
    window_hll_p: int = 10

    def __post_init__(self) -> None:
        if self.poll_interval_s <= 0:
            raise ValueError("--poll-interval must be > 0 seconds")
        if self.idle_backoff_max_s < self.poll_interval_s:
            raise ValueError(
                "idle backoff ceiling must be >= the poll interval"
            )
        if self.checkpoint_every_s < 0:
            raise ValueError("--checkpoint-interval must be >= 0 seconds")
        if self.idle_exit_s is not None and self.idle_exit_s < 0:
            raise ValueError("--follow-idle-exit must be >= 0 seconds")
        if self.window_secs <= 0:
            raise ValueError("--window-secs must be > 0 seconds")
        if self.window_count < 0:
            raise ValueError("--window-count must be >= 0")
        if not (4 <= self.window_hll_p <= 16):
            raise ValueError("window hll precision must be in [4, 16]")


#: Valid --lease-store selections: ``auto`` derives the store from the
#: run (the object store when --segment-store is remote, else lease
#: files in the checkpoint dir), the other two pin it.
LEASE_STORES = ("auto", "file", "object")


@dataclasses.dataclass(frozen=True)
class LeaseConfig:
    """Multi-instance fleet lease knobs (``--instance-id``/``--lease-ttl``;
    fleet/lease.py, DESIGN.md §23).

    Like `FollowConfig`, deliberately NOT part of `AnalyzerConfig`: who
    owns a topic (and for how long before failover) changes neither
    state shapes nor fold semantics — a fleet of N instances produces
    per-topic reports byte-identical to one instance scanning the same
    offsets — so none of it may churn the checkpoint fingerprint.  The
    lease EPOCH does ride snapshot metadata, but as a fencing stamp
    outside the fingerprint: any instance resumes any topic's snapshot,
    provided its own epoch is current.
    """

    #: This analyzer's identity on every lease record, booked metric,
    #: and published document.  Empty = leases disabled (the solo
    #: single-owner fleet, exactly the PR-13 behavior).
    instance_id: str = ""
    #: Lease lifetime in seconds: the failover bound (a crashed owner's
    #: topics are up for grabs this long after its last renewal) AND the
    #: zombie window the epoch fence must cover.  Renewals ride every
    #: poll boundary, so this must comfortably exceed the poll interval.
    ttl_s: float = 30.0
    #: Where lease records live (``LEASE_STORES``).
    store: str = "auto"

    def __post_init__(self) -> None:
        if self.ttl_s <= 0:
            raise ValueError("--lease-ttl must be > 0 seconds")
        if self.store not in LEASE_STORES:
            raise ValueError(
                f"lease store {self.store!r} invalid "
                f"({', '.join(LEASE_STORES)})"
            )

    @property
    def enabled(self) -> bool:
        return bool(self.instance_id)


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Alert-engine knobs (obs/health.py; DESIGN.md §22).

    Like `FollowConfig`, deliberately NOT part of `AnalyzerConfig`: how
    often health is evaluated (and what thresholds page) changes neither
    state shapes nor fold semantics — the engine only READS registry
    snapshots and observed windows — so none of it may churn the
    checkpoint fingerprint, and a scan is byte-identical with alerting
    on or off (tests/test_health.py pins it).
    """

    #: Floor between evaluations on the rate-limited ``maybe_evaluate``
    #: path (the engine heartbeat hook).  Poll-boundary evaluations from
    #: the follow/fleet services are not limited by it — a poll boundary
    #: IS an evaluation point, which is what makes the /healthz flip
    #: land within one interval of the fault (the acceptance bar).
    eval_interval_s: float = 5.0
    #: Default for-duration: a rule's condition must hold this long
    #: before the alert fires (blip suppression).
    for_s: float = 10.0
    #: Default resolve hysteresis: the condition must stay clear this
    #: long before a firing alert resolves (flap suppression).
    resolve_s: float = 15.0
    #: Lag-growth window: lag must exceed its value this far back (by at
    #: least ``lag_min_growth`` records) to count as diverging.
    lag_window_s: float = 30.0
    lag_min_growth: int = 1
    #: Corruption-storm window and the frames-per-window threshold.
    storm_window_s: float = 60.0
    corrupt_frames_threshold: float = 1.0
    #: Watermark-refresh-outage window (any budget-exhausted re-poll
    #: inside it keeps the condition true).
    outage_window_s: float = 60.0
    #: Throughput regression: recent window vs the trailing baseline
    #: window; fires when recent < drop_fraction x baseline while lag
    #: remains, and never below ``min_baseline_rate`` records/s (an
    #: idle or tiny scan has no baseline worth defending).
    throughput_window_s: float = 30.0
    throughput_baseline_s: float = 180.0
    throughput_drop_fraction: float = 0.5
    min_baseline_rate: float = 1.0
    #: Observed-series retention (must cover the longest rule window).
    retention_s: float = 900.0

    def __post_init__(self) -> None:
        if self.eval_interval_s <= 0:
            raise ValueError("health eval interval must be > 0 seconds")
        if self.for_s < 0 or self.resolve_s < 0:
            raise ValueError("for/resolve durations must be >= 0")
        if self.lag_window_s <= 0 or self.storm_window_s <= 0:
            raise ValueError("rule windows must be > 0 seconds")
        if self.outage_window_s <= 0 or self.throughput_window_s <= 0:
            raise ValueError("rule windows must be > 0 seconds")
        if self.throughput_baseline_s <= self.throughput_window_s:
            raise ValueError(
                "throughput baseline window must exceed the recent window"
            )
        if not (0.0 < self.throughput_drop_fraction < 1.0):
            raise ValueError("throughput drop fraction must be in (0, 1)")
        if self.retention_s < max(
            self.lag_window_s,
            self.storm_window_s,
            self.outage_window_s,
            self.throughput_baseline_s,
        ):
            raise ValueError(
                "health retention must cover the longest rule window"
            )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Read-path knobs (serve/state.py + serve/push.py; DESIGN.md §26).

    Like `HealthConfig`, deliberately NOT part of `AnalyzerConfig`: how
    reports are encoded and pushed changes neither state shapes nor fold
    semantics — the serving plane only READS published snapshots — so
    none of it may churn the checkpoint fingerprint, and a scan is
    byte-identical with the serving stack on or off
    (tests/test_serve_plane.py pins it).
    """

    #: Compress /report.json bodies once at publish time (``gzip_level``
    #: below); ``--no-serve-gzip`` clears it.
    gzip: bool = True
    #: Gzip level for publish-time encoding: 6 is the classic wire
    #: default — ~10× on report JSON, a low-single-digit-ms cost paid
    #: once per poll boundary, never per request.
    gzip_level: int = 6
    #: Bodies smaller than this are not worth a gzip member's overhead;
    #: the publish stores no gzip variant and every client gets identity.
    gzip_min_bytes: int = 256
    #: Per-subscriber SSE frame queue bound — a subscriber this far
    #: behind the publish rate is evicted, never blocked on
    #: (kta_serve_sse_dropped_total{reason="slow-client"}).
    sse_queue_len: int = 64
    #: SSE keepalive-comment cadence while no publish arrives, keeping
    #: intermediaries from idling out the stream.
    sse_keepalive_s: float = 15.0

    def __post_init__(self) -> None:
        if not (1 <= self.gzip_level <= 9):
            raise ValueError("serve gzip level must be in 1..9")
        if self.gzip_min_bytes < 0:
            raise ValueError("serve gzip floor must be >= 0 bytes")
        if self.sse_queue_len < 1:
            raise ValueError("SSE queue length must be >= 1")
        if self.sse_keepalive_s <= 0:
            raise ValueError("SSE keepalive must be > 0 seconds")


#: The one shared default — the serve modules read their constants from
#: here so a knob has exactly one home.
DEFAULT_SERVE = ServeConfig()


@dataclasses.dataclass(frozen=True)
class SegmentFetchConfig:
    """Remote-segment-tier knobs (``--segment-readahead``/``--segment-cache``;
    io/objstore.py + io/segstore.py, DESIGN.md §21).

    Like `IngestConfig`, deliberately NOT part of `AnalyzerConfig`: how a
    chunk's bytes ARRIVE (read ahead over the network, served from a local
    cache, or memory-mapped) changes neither state shapes nor fold
    semantics — a remote scan is byte-identical to the local-directory
    scan of the same chunks — so none of it may churn the checkpoint
    fingerprint.  A snapshot taken against one store resumes against any
    other store holding the same segments (cross-store resume).
    """

    #: Chunks kept in flight ahead of each consuming ingest stream (the
    #: per-stream WINDOW; the process-wide fetch scheduler in
    #: io/fetchsched.py supplies the workers).  In-flight chunk memory
    #: stays bounded by streams × (readahead + 1) chunks.  ``"auto"``
    #: resolves per store: 0 for local directories (the memmap faults
    #: pages in for free) and 4 for remote stores (enough speculation in
    #: flight to hide tens of ms of per-GET latency behind the fused
    #: decode→pack pass).  0 disables speculation: every chunk is a
    #: demand fetch at first touch — still admitted through the
    #: scheduler.
    readahead: "int | str" = "auto"
    #: Worker count of the ONE process-wide fetch scheduler
    #: (``--fetch-concurrency N|auto``) — the single admission point for
    #: every remote byte.  Sized once per process, NOT per stream: total
    #: connection count no longer multiplies with ingest workers.
    #: ``"auto"`` sizes from the host (min(16, max(4, cpu_count))) and
    #: grows with the resolved ingest-stream count; an explicit N pins it.
    fetch_concurrency: "int | str" = "auto"
    #: Local chunk-cache directory (``--segment-cache``); None disables.
    #: Remote stores only — caching a local directory would just copy it.
    cache_dir: "str | None" = None
    #: Cache size bound in bytes (``--segment-cache-bytes``): inserts
    #: evict least-recently-used entries past it.
    cache_max_bytes: int = 1 << 30
    #: Per-request socket timeout (connect and read) in seconds.  A stall
    #: past it is a transient transport failure: backoff, retry, budget.
    timeout_s: float = 30.0
    #: Transport retry pacing + per-partition budget — the SAME recovery
    #: substrate the live wire scan runs (io/retry.py): a partition whose
    #: chunks stay unreachable past the budget is degraded, not fatal.
    retry: TransportRetryConfig = dataclasses.field(
        default_factory=TransportRetryConfig
    )

    def __post_init__(self) -> None:
        if isinstance(self.readahead, str):
            if self.readahead != "auto":
                raise ValueError(
                    f"segment readahead {self.readahead!r} invalid "
                    "(an integer >= 0, or 'auto')"
                )
        elif self.readahead < 0:
            raise ValueError("segment readahead must be >= 0")
        if isinstance(self.fetch_concurrency, str):
            if self.fetch_concurrency != "auto":
                raise ValueError(
                    f"fetch concurrency {self.fetch_concurrency!r} invalid "
                    "(an integer >= 1, or 'auto')"
                )
        elif self.fetch_concurrency < 1:
            raise ValueError("fetch concurrency must be >= 1")
        if self.cache_max_bytes < 1:
            raise ValueError("--segment-cache-bytes must be >= 1")
        if self.timeout_s <= 0:
            raise ValueError("segment fetch timeout must be > 0 seconds")

    @classmethod
    def parse(
        cls,
        readahead: str = "auto",
        cache_dir: "str | None" = None,
        cache_max_bytes: int = 1 << 30,
        fetch_concurrency: str = "auto",
    ) -> "SegmentFetchConfig":
        """CLI spelling: ``--segment-readahead N|auto``,
        ``--fetch-concurrency N|auto``, + cache flags."""
        text = str(readahead).strip().lower()
        if text == "auto":
            ra: "int | str" = "auto"
        else:
            try:
                ra = int(text)
            except ValueError:
                raise ValueError(
                    f"bad --segment-readahead {readahead!r}: expected an "
                    "integer >= 0 or 'auto'"
                ) from None
        fc_text = str(fetch_concurrency).strip().lower()
        if fc_text == "auto":
            fc: "int | str" = "auto"
        else:
            try:
                fc = int(fc_text)
            except ValueError:
                raise ValueError(
                    f"bad --fetch-concurrency {fetch_concurrency!r}: "
                    "expected an integer >= 1 or 'auto'"
                ) from None
        return cls(
            readahead=ra, cache_dir=cache_dir, cache_max_bytes=cache_max_bytes,
            fetch_concurrency=fc,
        )

    def resolve_readahead(self, remote: bool) -> int:
        """Concrete per-stream read-ahead depth: ``auto`` = 4 for remote
        stores (hide per-GET wire latency behind the running decode→pack
        pass), 0 for local directories (nothing to hide — page faults)."""
        if self.readahead == "auto":
            return 4 if remote else 0
        return int(self.readahead)

    def resolve_concurrency(self) -> "int | None":
        """Concrete scheduler pool size, or None for ``auto`` (the
        scheduler sizes itself from the host and the engine's resolved
        ingest-stream count — io/fetchsched.py)."""
        if self.fetch_concurrency == "auto":
            return None
        return int(self.fetch_concurrency)


#: Valid --on-corruption policies, in escalation order.
CORRUPTION_POLICIES = ("fail", "skip", "quarantine")


@dataclasses.dataclass(frozen=True)
class CorruptionConfig:
    """Poison-frame policy for the live Kafka scan (io/kafka_wire.py).

    Like `TransportRetryConfig`, deliberately NOT part of `AnalyzerConfig`:
    how the scan reacts to corrupt frames changes neither state shapes nor
    fold semantics, so it must not churn the checkpoint fingerprint.

    Policies (applied only after a re-fetch reproduced the identical
    failure — a one-shot in-flight bit flip is retried, not classified):

    - ``fail``: abort the scan with the classified error (the default —
      exactly the pre-corruption-layer behavior);
    - ``skip``: skip exactly the poisoned frame, account for it
      per-partition, finish the scan, exit `cli.EXIT_CORRUPT`;
    - ``quarantine``: like skip, plus the raw frame bytes are spooled to
      ``quarantine_dir`` with a JSON sidecar (io/quarantine.py) so the
      evidence survives for offline analysis.
    """

    policy: str = "fail"
    quarantine_dir: "str | None" = None

    def __post_init__(self) -> None:
        if self.policy not in CORRUPTION_POLICIES:
            raise ValueError(
                f"on-corruption policy {self.policy!r} invalid "
                f"({', '.join(CORRUPTION_POLICIES)})"
            )
        if self.policy == "quarantine" and not self.quarantine_dir:
            raise ValueError(
                "--on-corruption=quarantine requires --quarantine-dir"
            )
        if self.quarantine_dir and self.policy != "quarantine":
            raise ValueError(
                "--quarantine-dir only applies with --on-corruption=quarantine"
            )


#: Valid --on-data-loss policies, in escalation order.
DATA_LOSS_POLICIES = ("fail", "report", "ignore")


@dataclasses.dataclass(frozen=True)
class DataLossConfig:
    """Log-mutation policy for the live Kafka scan (io/kafka_wire.py):
    what to do when the log MOVES under the scanner and records in
    ``[cursor, new_log_start)`` are unreachable (retention race), or a
    leader-epoch divergence proves the log was truncated below the
    cursor (unclean election).

    Like `CorruptionConfig`, deliberately NOT part of `AnalyzerConfig`:
    the reaction policy changes neither state shapes nor fold semantics,
    so it must not churn the checkpoint fingerprint.  Whatever the
    policy, every lost record is BOOKED (kta_log_lost_*) and spanned —
    the policy only governs whether the scan continues and how the exit
    code reflects the loss:

    - ``fail``: abort the scan with the classified error; the engine's
      failure path still writes a fold-consistent checkpoint, so a
      resume continues from committed state;
    - ``report``: keep scanning the surviving records, surface the loss
      as a DATA-LOSS report block / ``data_loss`` JSON map, exit
      `cli.EXIT_DATA_LOSS` (the default — a long-running follow service
      must not die to ordinary retention);
    - ``ignore``: keep scanning and exit 0 — for logs where retention
      churn is expected; the metrics and report blocks still name the
      loss (never-silent is not policy-dependent).
    """

    policy: str = "report"

    def __post_init__(self) -> None:
        if self.policy not in DATA_LOSS_POLICIES:
            raise ValueError(
                f"on-data-loss policy {self.policy!r} invalid "
                f"({', '.join(DATA_LOSS_POLICIES)})"
            )


@dataclasses.dataclass(frozen=True)
class AnalyzerConfig:
    """Static configuration for one analysis run.

    Anything that changes the compiled XLA program (shapes, enabled sketches,
    precisions) belongs here; runtime data (offsets, records) does not.
    """

    # --- topology -----------------------------------------------------------
    #: Number of Kafka partitions in the topic (P).  Static: it fixes the
    #: shape of the per-partition counter matrix (reference keeps HashMaps
    #: keyed by partition id instead, src/metric.rs:12-19).
    num_partitions: int = 1
    #: Records per device step (B).  The last batch is padded with
    #: ``valid=False`` records (XLA static shapes; SURVEY.md §7 hard part (b)).
    batch_size: int = 1 << 16

    # --- feature toggles (each adds state + kernels to the jitted update) ---
    #: Reference-compatible alive-key bitmap (``-c`` flag; src/metric.rs:262-305).
    count_alive_keys: bool = False
    #: log2 of the bitmap slot space.  The reference's fnv32 hash gives 2^32
    #: slots (512 MiB of packed bits); smaller values trade memory for extra
    #: collisions.  Hashes are masked to this width.
    alive_bitmap_bits: int = 32
    #: HyperLogLog distinct-key sketch (new capability; replaces the bitmap's
    #: O(2^bits) memory with O(2^hll_p) at ~1.04/sqrt(2^hll_p) rel. error).
    enable_hll: bool = False
    #: HLL precision p (m = 2^p registers). p=16 → 0.41% standard error,
    #: holding BASELINE.md's ≤1% budget at >2σ (p=14's 0.81% rode the edge:
    #: r3 recorded a 1.6% draw on config 3).  Capped at 16, the widest p
    #: whose bucket indices (0..2^p-1) fit the packed transfer's u16
    #: section; inactive records ship idx 0 with rho 0 (a scatter-max
    #: no-op), so no out-of-range sentinel index is needed.
    hll_p: int = 16
    #: One register file per partition instead of a single global one
    #: (implies enable_hll).  The global estimate stays exact HLL semantics:
    #: rows union by elementwise max.
    distinct_keys_per_partition: bool = False
    #: DDSketch message-size quantiles (new capability).
    enable_quantiles: bool = False
    #: Track one sketch row per partition instead of a single global one
    #: (BASELINE.json config 2: per-partition size histograms).  Global
    #: quantiles remain exact — DDSketch rows merge by addition.
    quantiles_per_partition: bool = False
    #: DDSketch relative accuracy alpha (gamma = (1+a)/(1-a)).
    quantile_alpha: float = 0.005
    #: Number of log-gamma buckets (covers sizes up to gamma^nbuckets).
    quantile_buckets: int = 2560

    #: Use the Pallas MXU one-hot-matmul kernel for the per-partition counter
    #: reduction (ops/pallas_counters.py) instead of the XLA scatter-add.
    #: Requires batch_size a multiple of 1024 (validated in __post_init__)
    #: and value lengths < 16 MiB (pack time); partitions beyond 128 tile
    #: the kernel grid.  Off by default until benchmarked faster on the
    #: target hardware.  (Under wire v5 the counter fold arrives as a
    #: pre-reduced table and this flag routes the merge through
    #: ops/pallas_counters.pallas_counters_merge instead.)
    use_pallas_counters: bool = False

    #: Host-side alive-pair compaction (``--alive-compaction``; DESIGN §19):
    #: ``auto`` (the default) compacts the last-writer-wins (slot, alive)
    #: pairs out of the per-batch wire rows into ONE bounded per-dispatch
    #: pair table — per-batch at K=1, per-SUPERBATCH at --superbatch K>1 —
    #: that the device merges once per dispatch instead of running the
    #: O(B) pair scatter (and its O(W) mask apply) inside every scan step.
    #: LWW compaction is itself LWW-associative, so results are
    #: byte-identical to the uncompacted fold.  Resolves ON only under the
    #: v5 combiner format with the alive bitmap enabled; ``off`` (or the
    #: ``KTA_DISABLE_COMPACTION`` env kill switch) keeps the v5 per-row
    #: pair sections — the bypass is booked on
    #: ``kta_alive_compaction_off_total{reason}``, never silent.  Pure
    #: execution strategy: byte-identical results, outside the checkpoint
    #: fingerprint (checkpoint.py), snapshots resume across the setting.
    alive_compaction: str = "auto"

    #: Packed host→device wire format (packing.py): ``0`` = auto (resolved
    #: at construction — v5 unless the ``KTA_WIRE_V4`` kill switch is set),
    #: ``4`` = per-record columns + host-pre-reduced extreme/alive/HLL
    #: sections, ``5`` = the combiner format: the remaining per-record
    #: columns are replaced by per-partition partial-fold tables (counter
    #: deltas, DDSketch bucket counts), so the device merges O(P·H) table
    #: rows instead of scattering O(B) records.  Results are byte-identical
    #: across formats (every fold is an associative integer reduction —
    #: DESIGN.md §2/§16), so this is pure execution strategy: it is
    #: excluded from the checkpoint fingerprint and snapshots resume across
    #: formats (checkpoint.py).
    wire_format: int = 0

    # --- host→device transfer ----------------------------------------------
    #: Pre-reduce bitmap updates on the host: last-writer-wins dedupe of
    #: (slot, alive) pairs per batch (C++ shim or numpy), so the device does
    #: two scatter-adds instead of a 1M-element sort.  The device-sort path
    #: remains available for reference (packing always dedupes on host; the
    #: sort kernel is exercised by its own unit tests).
    # --- parallelism --------------------------------------------------------
    #: Device mesh shape (data, space).  'data' shards record batches by
    #: partition; 'space' shards BOTH the alive-bitmap slot space and the
    #: record stream: each data row's batch is split into space_shards
    #: contiguous chunks (one per space shard, batch_size/space_shards
    #: records each), so host→device bytes and per-device reduction work
    #: scale down with the space axis; bitmap updates are redistributed
    #: on-device over ICI (all_gather + in-source-order application —
    #: backends/step.py).  (1, 1) runs single-device.
    #: See kafka_topic_analyzer_tpu/parallel/.
    mesh_shape: Tuple[int, int] = (1, 1)

    def __post_init__(self) -> None:
        if self.quantiles_per_partition and not self.enable_quantiles:
            # Per-partition sketches imply the feature (frozen dataclass, so
            # normalize via object.__setattr__).
            object.__setattr__(self, "enable_quantiles", True)
        if self.distinct_keys_per_partition and not self.enable_hll:
            object.__setattr__(self, "enable_hll", True)
        if self.num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if not (0 < self.alive_bitmap_bits <= 32):
            raise ValueError("alive_bitmap_bits must be in (0, 32]")
        if not (4 <= self.hll_p <= 16):
            raise ValueError("hll_p must be in [4, 16]")
        if self.quantile_buckets < 8:
            raise ValueError("quantile_buckets must be >= 8")
        if self.wire_format == 0:
            import os

            # Resolved (and the reason recorded) ONCE, here: the booking
            # property below must describe how this config actually chose
            # v4, not whatever the env says when the engine reads it.
            forced = bool(os.environ.get("KTA_WIRE_V4"))
            object.__setattr__(self, "wire_format", 4 if forced else 5)
            object.__setattr__(
                self, "_wire_v4_reason", "env-kill-switch" if forced else None
            )
        elif self.wire_format in (4, 5):
            object.__setattr__(
                self,
                "_wire_v4_reason",
                "explicit" if self.wire_format == 4 else None,
            )
        else:
            raise ValueError(
                f"wire_format {self.wire_format!r} invalid (0=auto, 4, or 5)"
            )
        if self.alive_compaction not in ("auto", "off"):
            raise ValueError(
                f"alive_compaction {self.alive_compaction!r} invalid "
                "(auto or off)"
            )
        # Resolve alive-pair compaction ONCE, here, with the reason it is
        # off recorded at resolution time (same discipline as the wire-v4
        # reason above: the engine's fallback booking must describe the
        # decision actually taken, not whatever the env says later).
        compact = False
        off_reason = None
        if self.count_alive_keys:
            import os

            if self.alive_compaction == "off":
                off_reason = "explicit"
            elif os.environ.get("KTA_DISABLE_COMPACTION"):
                off_reason = "env-kill-switch"
            elif self.wire_format != 5:
                # The compacted pair table is a v5 combiner section; the
                # v4 layout keeps its per-row pairs.
                off_reason = "wire-v4"
            else:
                compact = True
        object.__setattr__(self, "_compact_alive", compact)
        object.__setattr__(self, "_alive_compaction_off_reason", off_reason)
        if (
            self.use_pallas_counters
            and self.wire_format == 4
            and self.batch_size % 1024
        ):
            # A constraint of the v4 MXU one-hot-matmul kernel's 1024-record
            # blocks only: under wire v5 the counter fold arrives as a
            # pre-reduced table and pallas_counters_merge pads any shape.
            raise ValueError(
                "use_pallas_counters requires batch_size % 1024 == 0"
            )

    @property
    def hll_m(self) -> int:
        return 1 << self.hll_p

    @property
    def wire_v4_reason(self) -> "str | None":
        """Why this config runs wire v4 (None when it runs v5):
        ``env-kill-switch`` (KTA_WIRE_V4 forced the fallback at
        construction) or ``explicit`` (the caller pinned v4).  Recorded
        AT RESOLUTION TIME in ``__post_init__`` — not re-read from the
        env — so the engine's ``kta_wire_v4_fallback_total`` booking
        describes the decision actually taken (a bypassed combiner format
        is never silent, same discipline as ``kta_fused_fallback_total``;
        a ``dataclasses.replace`` of an env-forced config re-labels as
        ``explicit``, which is what the copy's pinned field now is)."""
        return self._wire_v4_reason

    @property
    def compact_alive(self) -> bool:
        """True when this config ships alive pairs as a compacted
        per-dispatch pair table instead of per-row sections (resolved in
        ``__post_init__`` — see ``alive_compaction``)."""
        return self._compact_alive

    @property
    def alive_compaction_off_reason(self) -> "str | None":
        """Why an alive-key scan runs WITHOUT pair compaction (None when
        compaction is on, or when the config has no alive bitmap to
        compact): ``explicit`` (--alive-compaction off),
        ``env-kill-switch`` (KTA_DISABLE_COMPACTION), or ``wire-v4``.
        Recorded at resolution time like ``wire_v4_reason`` so the
        ``kta_alive_compaction_off_total`` booking can never drift from
        the decision taken."""
        return self._alive_compaction_off_reason

    @property
    def quantile_gamma(self) -> float:
        a = self.quantile_alpha
        return (1.0 + a) / (1.0 - a)

    @property
    def data_shards(self) -> int:
        return self.mesh_shape[0]

    @property
    def space_shards(self) -> int:
        return self.mesh_shape[1]

    @property
    def chunk_size(self) -> int:
        """Records per (data, space) device per step: each data row's batch
        is split contiguously across the space axis."""
        return self.batch_size // self.space_shards
