"""CPU-exact oracle backend (vectorized numpy).

Reproduces the reference's accumulator semantics exactly (src/metric.rs:
207-252 per-message update; src/metric.rs:262-305 alive-key bitset including
fnv32 collision behavior) but over batches.  This backend is the referee for
every TPU claim: counters must match it bit-for-bit, sketches within their
error budget (SURVEY.md §4).

It deliberately shares no array code with the TPU backend — an independent
implementation is what makes parity tests meaningful.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from kafka_topic_analyzer_tpu.backends.base import MetricBackend, instrument_steps
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import (
    COUNTER_CHANNELS,
    QUANTILE_PROBS,
    QuantileSummary,
    TopicMetrics,
    finalize_extremes,
)
from kafka_topic_analyzer_tpu.utils.timefmt import utc_now_seconds


def _exact_quantiles(sizes: np.ndarray, counts: np.ndarray) -> QuantileSummary:
    """Exact quantiles of a (size -> count) histogram (sizes sorted)."""
    if len(sizes) == 0:
        return QuantileSummary(
            list(QUANTILE_PROBS), [float("nan")] * len(QUANTILE_PROBS)
        )
    order = np.argsort(sizes)
    sizes = sizes[order]
    counts = counts[order]
    cum = np.cumsum(counts)
    total = int(cum[-1])
    vals = []
    for q in QUANTILE_PROBS:
        rank = max(0, min(total - 1, int(np.ceil(q * total)) - 1))
        vals.append(float(sizes[int(np.searchsorted(cum, rank + 1))]))
    return QuantileSummary(list(QUANTILE_PROBS), vals)


@instrument_steps
class CpuExactBackend(MetricBackend):
    def __init__(self, config: AnalyzerConfig, init_now_s: "int | None" = None):
        super().__init__(config)
        p = config.num_partitions
        self.per_partition = np.zeros((p, len(COUNTER_CHANNELS)), dtype=np.int64)
        # Reference init values: earliest=now, latest=epoch, smallest=u64::MAX,
        # largest=0 (src/metric.rs:40-43).  We keep "unset" sentinels (per
        # partition, matching the TPU state layout) and apply the now/epoch
        # clamps at finalize.
        self.init_now_s = utc_now_seconds() if init_now_s is None else init_now_s
        i64 = np.iinfo(np.int64)
        self.earliest_s = np.full(p, i64.max, dtype=np.int64)
        self.latest_s = np.full(p, i64.min, dtype=np.int64)
        self.smallest = np.full(p, i64.max, dtype=np.int64)
        self.largest = np.zeros(p, dtype=np.int64)
        self.overall_size = 0
        self.overall_count = 0
        # Alive-key bitmap over fnv32 slots, packed bits (reference: BitSet).
        self._alive_words: "np.ndarray | None" = None
        if config.count_alive_keys:
            nwords = 1 << max(config.alive_bitmap_bits - 5, 0)
            self._alive_words = np.zeros(nwords, dtype=np.uint32)
        # Exact distinct-key tracking by 64-bit hash identity, one set per
        # partition (referee for the HLL sketch and its per-partition rows;
        # collision probability ~2^-64).  Global distinct = |union| — the
        # same key CAN appear in several partitions in arbitrary streams.
        self._seen_keys: "list[set[int]]" = [set() for _ in range(p)]
        # Exact message sizes histogram referee for quantiles, keyed by
        # (partition << 32 | size) so per-partition summaries are exact too.
        self._size_counts: Dict[int, int] = {}

    # -- update --------------------------------------------------------------

    def update(self, batch: RecordBatch) -> None:
        valid = batch.valid
        if not valid.any():
            return
        part = batch.partition
        kn = valid & ~batch.key_null
        vn = valid & ~batch.value_null
        tomb = valid & batch.value_null
        knull = valid & batch.key_null
        k_bytes = np.where(kn, batch.key_len, 0).astype(np.int64)
        v_bytes = np.where(vn, batch.value_len, 0).astype(np.int64)

        p = self.config.num_partitions
        contrib = np.stack(
            [
                valid.astype(np.int64),
                tomb.astype(np.int64),
                vn.astype(np.int64),
                knull.astype(np.int64),
                kn.astype(np.int64),
                k_bytes,
                v_bytes,
            ],
            axis=1,
        )
        np.add.at(self.per_partition, part[valid], contrib[valid])

        self.overall_count += int(valid.sum())
        self.overall_size += int(k_bytes.sum() + v_bytes.sum())

        msg_size = k_bytes + v_bytes
        sized = vn  # min/max excludes tombstones (src/metric.rs:249-251)
        if sized.any():
            np.minimum.at(self.smallest, part[sized], msg_size[sized])
            np.maximum.at(self.largest, part[sized], msg_size[sized])
        np.minimum.at(self.earliest_s, part[valid], batch.ts_s[valid])
        np.maximum.at(self.latest_s, part[valid], batch.ts_s[valid])

        keyed = valid & ~batch.key_null
        if keyed.any():
            for pid in np.unique(part[keyed]):
                sel = keyed & (part == pid)
                self._seen_keys[int(pid)].update(batch.key_hash64[sel].tolist())
            if self._alive_words is not None:
                self._update_alive_bitmap(
                    batch.key_hash32[keyed], vn[keyed]
                )
        if self.config.enable_quantiles:
            keys = (part[sized].astype(np.int64) << 32) | msg_size[sized]
            uniq, counts = np.unique(keys, return_counts=True)
            for k, c in zip(uniq.tolist(), counts.tolist()):
                self._size_counts[k] = self._size_counts.get(k, 0) + c

    def _update_alive_bitmap(self, h32: np.ndarray, alive: np.ndarray) -> None:
        """Last-writer-wins per slot within the batch, then packed-bit RMW.

        Semantics identical to replaying ``insert``/``remove`` in record order
        (src/metric.rs:273-280): for each slot only its last record matters.
        """
        bits = self.config.alive_bitmap_bits
        slot = (h32.astype(np.uint64) & np.uint64((1 << bits) - 1)).astype(np.int64)
        # Last occurrence per slot: np.unique returns first occurrences, so
        # scan the reversed array.
        rev_slot = slot[::-1]
        rev_alive = alive[::-1]
        uniq, first_rev = np.unique(rev_slot, return_index=True)
        final_alive = rev_alive[first_rev]
        word = (uniq >> 5).astype(np.int64)
        bit = (np.uint32(1) << (uniq & 31).astype(np.uint32)).astype(np.uint32)
        set_w = word[final_alive]
        set_b = bit[final_alive]
        clr_w = word[~final_alive]
        clr_b = bit[~final_alive]
        np.bitwise_and.at(self._alive_words, clr_w, ~clr_b)
        np.bitwise_or.at(self._alive_words, set_w, set_b)

    # -- finalize ------------------------------------------------------------

    def finalize(self) -> TopicMetrics:
        earliest, latest, smallest = finalize_extremes(
            int(self.earliest_s.min()),
            int(self.latest_s.max()),
            int(self.smallest.min()),
            self.init_now_s,
        )

        alive_keys = None
        if self._alive_words is not None:
            # bitwise_count avoids unpackbits' 8x temporary (4 GiB at 2^32).
            alive_keys = int(np.bitwise_count(self._alive_words).sum())
        quantiles = None
        quantiles_pp = None
        if self.config.enable_quantiles and self._size_counts:
            keys = np.array(sorted(self._size_counts), dtype=np.int64)
            kcounts = np.array(
                [self._size_counts[int(k)] for k in keys], dtype=np.int64
            )
            sizes_all = keys & 0xFFFFFFFF
            quantiles = _exact_quantiles(sizes_all, kcounts)
            if self.config.quantiles_per_partition:
                parts_of_key = keys >> 32
                quantiles_pp = []
                for p in range(self.config.num_partitions):
                    sel = parts_of_key == p
                    quantiles_pp.append(
                        _exact_quantiles(sizes_all[sel], kcounts[sel])
                    )

        return TopicMetrics(
            partitions=list(range(self.config.num_partitions)),
            per_partition=self.per_partition.copy(),
            earliest_ts_s=earliest,
            latest_ts_s=latest,
            smallest_message=smallest,
            largest_message=int(self.largest.max()),
            overall_size=self.overall_size,
            overall_count=self.overall_count,
            alive_keys=alive_keys,
            # Report the exact distinct counts only when distinct-key
            # counting was requested, so cpu/tpu reports stay line-compatible.
            distinct_keys_exact=(
                len(set().union(*self._seen_keys))
                if self.config.enable_hll
                else None
            ),
            distinct_keys_exact_per_partition=(
                [len(s) for s in self._seen_keys]
                if self.config.distinct_keys_per_partition
                else None
            ),
            quantiles=quantiles,
            quantiles_per_partition=quantiles_pp,
            per_partition_extremes=np.stack(
                [self.earliest_s, self.latest_s, self.smallest, self.largest],
                axis=1,
            ),
            init_now_s=self.init_now_s,
        )
