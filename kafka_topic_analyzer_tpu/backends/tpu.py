"""TPU backend: jitted streaming reduction with donated device state.

Design (SURVEY.md §7 M3):
- the accumulator state lives on device for the whole scan; each `update`
  dispatches one jitted step with the state buffers *donated*, so XLA updates
  them in place and the host never round-trips the state (hard part (e));
- dispatch is asynchronous — the host thread returns immediately and keeps
  feeding batches while the device works; `finalize` synchronizes once;
- batches are padded to the static batch size, so every step hits the same
  compiled executable.

Multi-device runs go through `kafka_topic_analyzer_tpu.parallel.sharded`
(same step function under ``shard_map``).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from kafka_topic_analyzer_tpu.backends.base import MetricBackend
from kafka_topic_analyzer_tpu.backends.finalize import metrics_from_state
from kafka_topic_analyzer_tpu.backends.step import analyzer_step
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.models.state import AnalyzerState
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.timefmt import utc_now_seconds

#: RecordBatch fields shipped to the device, in a fixed order.
DEVICE_FIELDS = (
    "partition",
    "key_len",
    "value_len",
    "key_null",
    "value_null",
    "ts_s",
    "key_hash32",
    "key_hash64",
    "valid",
)


def batch_to_arrays(batch: RecordBatch, batch_size: int):
    batch = batch.pad_to(batch_size)
    return {name: getattr(batch, name) for name in DEVICE_FIELDS}


class TpuBackend(MetricBackend):
    def __init__(
        self,
        config: AnalyzerConfig,
        init_now_s: "int | None" = None,
        device=None,
    ):
        super().__init__(config)
        self.init_now_s = utc_now_seconds() if init_now_s is None else init_now_s
        self.device = device if device is not None else jax.devices()[0]
        with jax.default_device(self.device):
            self.state = AnalyzerState.init(config)
        self._step = jax.jit(
            functools.partial(analyzer_step, config=config),
            donate_argnums=(0,),
        )
        self.batches_seen = 0

    def update(self, batch: RecordBatch) -> None:
        arrays = batch_to_arrays(batch, self.config.batch_size)
        arrays = {
            k: jax.device_put(v, self.device) for k, v in arrays.items()
        }
        self.state = self._step(self.state, arrays)
        self.batches_seen += 1

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)

    def finalize(self) -> TopicMetrics:
        host_state = jax.tree.map(np.asarray, jax.device_get(self.state))
        return metrics_from_state(host_state, self.config, self.init_now_s)
