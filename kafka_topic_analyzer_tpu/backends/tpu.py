"""TPU backend: jitted streaming reduction with donated device state.

Design (SURVEY.md §7 M3 + the transfer work in packing.py):
- the accumulator state lives on device for the whole scan; each `update`
  dispatches one jitted step with the state buffers *donated*, so XLA updates
  them in place and the host never round-trips the state (hard part (e));
- each batch crosses the host→device boundary as ONE packed uint8 buffer in
  wire format v3 (packing.py) — minimal bytes per record, host-side
  pre-reduction for the bitmap/HLL updates;
- dispatch is asynchronous — the host thread returns immediately and keeps
  packing the next batch while the device works; `finalize` synchronizes;
- a one-time pack→unpack self-check at init guards the bitcast layout
  against byte-order surprises on new platforms.

Multi-device runs go through `kafka_topic_analyzer_tpu.parallel.sharded`
(same packed step under ``shard_map``).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from kafka_topic_analyzer_tpu.backends.base import MetricBackend, instrument_steps
from kafka_topic_analyzer_tpu.backends.finalize import metrics_from_state
from kafka_topic_analyzer_tpu.backends.step import analyzer_step
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.models.state import AnalyzerState
from kafka_topic_analyzer_tpu.packing import pack_batch, unpack_device, unpack_numpy
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.timefmt import utc_now_seconds


def make_packed_step(config: AnalyzerConfig):
    """The jittable forward step: (state, packed uint8 buffer) → state."""

    def step(state: AnalyzerState, buf) -> AnalyzerState:
        return analyzer_step(state, unpack_device(buf, config), config)

    return step


class StagedBatch:
    """A batch already packed and launched host→device.

    Produced by ``TpuBackend.prepare`` — designed to run on a prefetch
    worker thread (engine.run_scan stages there), so the pack (native,
    GIL-released) and the async ``device_put`` transfer both overlap the
    device's current step instead of serializing in front of the next
    dispatch.  The explicit double-buffered host→device pipeline
    SURVEY.md §7 M5 calls for; prefetch depth bounds in-flight buffers.
    Deliberately just a typed buffer: all bookkeeping (counts, bytes,
    offsets) stays with the decoded batch the engine already holds.
    """

    __slots__ = ("buf",)

    def __init__(self, buf):
        self.buf = buf


def self_check_unpack(device=None) -> None:
    """One-time guard: pack a known batch on the host, unpack it on the
    device, and compare — catches any bitcast/byte-order mismatch before it
    could corrupt results."""
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    config = AnalyzerConfig(
        num_partitions=3,
        batch_size=128,
        count_alive_keys=True,
        alive_bitmap_bits=16,
        enable_hll=True,
        hll_p=8,
    )
    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=40, keys_per_partition=16, seed=11
    )
    batch = next(SyntheticSource(spec).batches(100))
    buf = pack_batch(batch, config, use_native=False)
    expected = unpack_numpy(buf, config)
    unpack = jax.jit(lambda b: unpack_device(b, config))
    got = unpack(jax.device_put(buf, device))
    for name, exp in expected.items():
        g = np.asarray(got[name])
        if not np.array_equal(g, np.asarray(exp)):
            raise RuntimeError(
                f"packed-transfer self-check failed on field {name!r}: "
                f"device unpack disagrees with host layout (byte order?)"
            )


_checked_devices: "set[str]" = set()


@instrument_steps
class TpuBackend(MetricBackend):
    def __init__(
        self,
        config: AnalyzerConfig,
        init_now_s: "int | None" = None,
        device=None,
        use_native: bool = True,
    ):
        super().__init__(config)
        self.init_now_s = utc_now_seconds() if init_now_s is None else init_now_s
        self.device = device if device is not None else jax.devices()[0]
        self.use_native = use_native
        key = str(self.device)
        if key not in _checked_devices and not os.environ.get("KTA_SKIP_SELFCHECK"):
            self_check_unpack(self.device)
            _checked_devices.add(key)
        with jax.default_device(self.device):
            self.state = AnalyzerState.init(config)
        self._step = jax.jit(make_packed_step(config), donate_argnums=(0,))

    def prepare(self, batch: RecordBatch) -> StagedBatch:
        """Pack + start the host→device transfer for a batch that will be
        fed to ``update`` later.  Safe to call from a worker thread (jax
        dispatch is thread-safe; the packers are pure numpy/C++)."""
        buf = pack_batch(batch, self.config, use_native=self.use_native)
        return StagedBatch(jax.device_put(buf, self.device))

    def update(self, batch: "RecordBatch | StagedBatch") -> None:
        if isinstance(batch, StagedBatch):
            self.state = self._step(self.state, batch.buf)
            return
        buf = pack_batch(batch, self.config, use_native=self.use_native)
        self.state = self._step(self.state, jax.device_put(buf, self.device))

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.state)

    # -- snapshot/resume (checkpoint.py) -------------------------------------

    def get_state(self) -> AnalyzerState:
        return self.state

    def set_state(self, host_state: AnalyzerState) -> None:
        self.state = jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), self.device), host_state
        )

    def finalize(self) -> TopicMetrics:
        host_state = jax.tree.map(np.asarray, jax.device_get(self.state))
        return metrics_from_state(host_state, self.config, self.init_now_s)
