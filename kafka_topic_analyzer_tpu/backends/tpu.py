"""TPU backend: jitted streaming reduction with donated device state.

Design (SURVEY.md §7 M3 + the transfer work in packing.py):
- the accumulator state lives on device for the whole scan; each `update`
  dispatches one jitted step with the state buffers *donated*, so XLA updates
  them in place and the host never round-trips the state (hard part (e));
- each batch crosses the host→device boundary as ONE packed uint8 buffer in
  wire format v4 (packing.py's module docstring is the layout contract) —
  minimal bytes per record, host-side pre-reduction for the bitmap/HLL
  updates;
- dispatch is asynchronous — the host thread returns immediately and keeps
  packing the next batch while the device works; `finalize` synchronizes;
- at ``--superbatch K`` > 1, K packed buffers stack into one contiguous
  ``uint8[K, N]`` host array folded by a single jitted ``lax.scan`` dispatch
  (state donated once per superbatch, one large transfer), with up to
  ``--dispatch-depth`` superbatches in flight (bounded by DispatchQueue);
- a one-time pack→unpack self-check at init guards the bitcast layout
  against byte-order surprises on new platforms.

Multi-device runs go through `kafka_topic_analyzer_tpu.parallel.sharded`
(same packed step under ``shard_map``).
"""

from __future__ import annotations

import os

import jax
import numpy as np

from kafka_topic_analyzer_tpu.backends.base import (
    DispatchQueue,
    MetricBackend,
    instrument_steps,
)
from kafka_topic_analyzer_tpu.backends.finalize import metrics_from_state
from kafka_topic_analyzer_tpu.backends.step import (
    analyzer_step,
    apply_pair_table,
    superbatch_fold,
)
from kafka_topic_analyzer_tpu.config import AnalyzerConfig, DispatchConfig
from kafka_topic_analyzer_tpu.models.state import AnalyzerState
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.packing import (
    SuperbatchStager,
    batch_alive_pairs,
    pack_batch,
    pack_pair_table,
    packed_nbytes,
    pair_table_capacity,
    unpack_device,
    unpack_numpy,
    unpack_pair_table_device,
)
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics
from kafka_topic_analyzer_tpu.utils.timefmt import utc_now_seconds


def make_packed_step(config: AnalyzerConfig):
    """The jittable forward step: (state, packed uint8 buffer) → state.

    Under alive-pair compaction (``config.compact_alive``) the step takes
    a second buffer — the batch's packed pair table — applied after the
    fold exactly like the superbatch path applies its merged table."""
    if config.compact_alive:
        cap = pair_table_capacity(config, config.batch_size, 1)

        def step_c(state: AnalyzerState, buf, pairbuf) -> AnalyzerState:
            st = analyzer_step(state, unpack_device(buf, config), config)
            return apply_pair_table(
                st, unpack_pair_table_device(pairbuf, config, cap), config
            )

        return step_c

    def step(state: AnalyzerState, buf) -> AnalyzerState:
        return analyzer_step(state, unpack_device(buf, config), config)

    return step


def make_packed_superstep(config: AnalyzerConfig, k: int = 1):
    """The jittable superbatch step: (state, uint8[K, N]) → (state, token).

    One dispatch scan-folds the K stacked packed buffers in order
    (backends/step.py::superbatch_fold), donating the state once per
    superbatch instead of once per batch.  The token (int32[K] of
    per-batch valid counts) is a small non-donated output used by the
    bounded dispatch queue as a completion marker.

    Under alive-pair compaction the superstep takes the dispatch's merged
    pair table (capacity ``pair_table_capacity(config, B, k)``) and
    applies it ONCE after the scan — this is the compaction win: the
    O(W) bitmap mask apply leaves the scan body entirely."""
    if config.compact_alive:
        cap = pair_table_capacity(config, config.batch_size, k)

        def superstep_c(state: AnalyzerState, bufs, pairbuf):
            return superbatch_fold(
                state,
                bufs,
                lambda b: unpack_device(b, config),
                config,
                pairs=unpack_pair_table_device(pairbuf, config, cap),
            )

        return superstep_c

    def superstep(state: AnalyzerState, bufs):
        return superbatch_fold(
            state, bufs, lambda b: unpack_device(b, config), config
        )

    return superstep


class StagedBatch:
    """A batch already packed for (or launched into) host→device transfer.

    Produced by ``TpuBackend.prepare`` — designed to run on a prefetch
    worker thread (engine.run_scan stages there), so the pack (native,
    GIL-released) overlaps the device's current step instead of
    serializing in front of the next dispatch.  At superbatch K=1 the
    worker also starts the async ``device_put`` (the explicit
    double-buffered host→device pipeline SURVEY.md §7 M5 calls for;
    prefetch depth bounds in-flight buffers); at K>1 ``buf`` stays a HOST
    buffer — the fan-in order decides which superbatch row it lands in,
    and the whole stack crosses in one large transfer at dispatch time.
    Deliberately just a typed buffer: all bookkeeping (counts, bytes,
    offsets) stays with the decoded batch the engine already holds.

    ``pairs`` rides the compacted alive path (DESIGN.md §19): at K=1 it
    is the batch's PACKED pair-table buffer (device-put alongside the
    row on the producing thread); at K>1 the raw ``(slot u32[n], flag
    u8[n])`` host arrays the dispatch-time merge consumes.  None when
    compaction is off.
    """

    __slots__ = ("buf", "pairs")

    def __init__(self, buf, pairs=None):
        self.buf = buf
        self.pairs = pairs


def self_check_unpack(device=None) -> None:
    """One-time guard: pack a known batch on the host, unpack it on the
    device, and compare — catches any bitcast/byte-order mismatch before it
    could corrupt results.  Runs BOTH wire formats: v4's per-record
    columns and v5's combiner tables (including the quantile section)
    cross the same bitcast boundary."""
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    spec = SyntheticSpec(
        num_partitions=3, messages_per_partition=40, keys_per_partition=16, seed=11
    )
    batch = next(SyntheticSource(spec).batches(100))
    for wire_format in (4, 5):
        config = AnalyzerConfig(
            num_partitions=3,
            batch_size=128,
            count_alive_keys=True,
            alive_bitmap_bits=16,
            enable_hll=True,
            hll_p=8,
            enable_quantiles=True,
            wire_format=wire_format,
        )
        buf = pack_batch(batch, config, use_native=False)
        expected = unpack_numpy(buf, config)
        unpack = jax.jit(lambda b, c=config: unpack_device(b, c))
        got = unpack(jax.device_put(buf, device))
        for name, exp in expected.items():
            g = np.asarray(got[name])
            if not np.array_equal(g, np.asarray(exp)):
                raise RuntimeError(
                    f"packed-transfer self-check failed on wire-v"
                    f"{wire_format} field {name!r}: device unpack disagrees "
                    f"with host layout (byte order?)"
                )


_checked_devices: "set[str]" = set()


@instrument_steps
class TpuBackend(MetricBackend):
    def __init__(
        self,
        config: AnalyzerConfig,
        init_now_s: "int | None" = None,
        device=None,
        use_native: bool = True,
        dispatch: "DispatchConfig | None" = None,
    ):
        super().__init__(config)
        self.init_now_s = utc_now_seconds() if init_now_s is None else init_now_s
        self.device = device if device is not None else jax.devices()[0]
        self.use_native = use_native
        key = str(self.device)
        if key not in _checked_devices and not os.environ.get("KTA_SKIP_SELFCHECK"):
            self_check_unpack(self.device)
            _checked_devices.add(key)
        with jax.default_device(self.device):
            self.state = AnalyzerState.init(config)
        # State donation is an accelerator-memory optimization only.  On
        # the host-CPU platform it is actively UNSAFE under the fleet's
        # concurrent per-topic scan threads: concurrent dispatches of a
        # donated-state executable race XLA:CPU's donation bookkeeping,
        # and a live state buffer can be freed while still referenced —
        # the resumed fold then reads recycled heap memory (pointer-sized
        # garbage in counts/HLL registers).  States are KBs on CPU, so
        # the extra copy per step costs nothing measurable there.
        self._donate = (0,) if self.device.platform != "cpu" else ()
        self._step = jax.jit(
            make_packed_step(config), donate_argnums=self._donate
        )
        # Superbatch dispatch layer (config.DispatchConfig): K packed
        # buffers per jitted scan dispatch, up to `depth` superbatches in
        # flight.  K=1 keeps the classic one-dispatch-per-batch path
        # (prepare launches the transfer itself) untouched.
        self.dispatch_config = dispatch if dispatch is not None else DispatchConfig()
        self.superbatch_k = self.dispatch_config.resolve(config.batch_size)
        self.dispatch_depth = self.dispatch_config.depth
        # Compacted alive path (DESIGN.md §19): per-dispatch pair-table
        # capacities for the two dispatch shapes this backend compiles.
        self._compact = config.compact_alive
        self._pair_cap1 = (
            pair_table_capacity(config, config.batch_size, 1)
            if self._compact
            else 0
        )
        if self.superbatch_k > 1:
            self._pair_cap_k = (
                pair_table_capacity(config, config.batch_size, self.superbatch_k)
                if self._compact
                else 0
            )
            self._superstep = jax.jit(
                make_packed_superstep(config, self.superbatch_k),
                donate_argnums=self._donate,
            )
            self._stager = SuperbatchStager(
                (packed_nbytes(config, config.batch_size),),
                self.superbatch_k,
                self.dispatch_depth,
            )
            self._queue = DispatchQueue(self.dispatch_depth)
            self._empty_buf: "np.ndarray | None" = None

    def set_dispatch_depth(self, depth: int) -> None:
        """Re-bound the in-flight dispatch window between passes — the
        fleet scheduler's dispatch-share grants (DESIGN.md §20) become
        real backpressure here, not just ledger rows.  Shrinks apply
        immediately (DispatchQueue.throttle reads the bound per call,
        and a live shrink just waits in-flight work below the new bound);
        grows clamp at the CONSTRUCTED depth, because the stager ring was
        sized then and a wider window would outrun its slots."""
        depth = max(1, min(int(depth), self.dispatch_config.depth))
        self.dispatch_depth = depth
        q = getattr(self, "_queue", None)
        if q is not None:
            q.depth = depth

    def _pack_pairs(self, pair_lists, cap) -> np.ndarray:
        """Merge + pack a dispatch's pair table, booking the raw→emitted
        compaction split (never silent — the --stats ratio reads these)."""
        buf, raw, emitted = pack_pair_table(
            pair_lists, self.config, cap, use_native=self.use_native
        )
        obs_metrics.ALIVE_PAIRS_RAW.inc(raw)
        obs_metrics.ALIVE_PAIRS_EMITTED.inc(emitted)
        return buf

    def prepare(self, batch: RecordBatch) -> StagedBatch:
        """Pack (and, at superbatch K=1, start the host→device transfer
        for) a batch that will be fed to ``update``/``update_superbatch``
        later.  Safe to call from a worker thread (jax dispatch is
        thread-safe; the packers are pure numpy/C++).  At K>1 the buffer
        stays on the host: it is copied into its superbatch row at fan-in
        time and crosses in the stack's single large transfer.  Compacted
        alive configs stage the batch's pairs alongside: packed + put at
        K=1 (the whole table is this batch's), raw host arrays at K>1
        (the dispatch-time merge spans the superbatch)."""
        buf = pack_batch(batch, self.config, use_native=self.use_native)
        if self.superbatch_k > 1:
            if self._compact:
                return StagedBatch(
                    buf, batch_alive_pairs(batch, self.config, self.use_native)
                )
            return StagedBatch(buf)
        if self._compact:
            pairbuf = self._pack_pairs(
                [batch_alive_pairs(batch, self.config, self.use_native)],
                self._pair_cap1,
            )
            return StagedBatch(
                jax.device_put(buf, self.device),
                jax.device_put(pairbuf, self.device),
            )
        return StagedBatch(jax.device_put(buf, self.device))

    def make_fused_sink(self, dense_of):
        """A packing.FusedPackSink staged for this backend: fused rows
        come out exactly like ``prepare``'s output (async ``device_put``
        on the producing thread at K=1; host buffer at K>1, copied into
        its superbatch stager row at fan-in time).  One sink per ingest
        stream — sinks are single-threaded state."""
        from kafka_topic_analyzer_tpu.packing import FusedPackSink

        def stage(buf, pairs=None):
            if self.superbatch_k > 1:
                return StagedBatch(buf, pairs)
            if self._compact:
                pairbuf = self._pack_pairs([pairs], self._pair_cap1)
                return StagedBatch(
                    jax.device_put(buf, self.device),
                    jax.device_put(pairbuf, self.device),
                )
            return StagedBatch(jax.device_put(buf, self.device))

        return FusedPackSink(
            self.config, self.config.batch_size, dense_of, stage=stage
        )

    def update(self, batch: "RecordBatch | StagedBatch") -> None:
        if isinstance(batch, StagedBatch):
            obs_metrics.WIRE_BYTES.inc(int(batch.buf.nbytes))
            if self._compact:
                obs_metrics.WIRE_BYTES.inc(int(batch.pairs.nbytes))
                self.state = self._step(self.state, batch.buf, batch.pairs)
            else:
                self.state = self._step(self.state, batch.buf)
            return
        buf = pack_batch(batch, self.config, use_native=self.use_native)
        obs_metrics.WIRE_BYTES.inc(int(buf.nbytes))
        if self._compact:
            pairbuf = self._pack_pairs(
                [batch_alive_pairs(batch, self.config, self.use_native)],
                self._pair_cap1,
            )
            obs_metrics.WIRE_BYTES.inc(int(pairbuf.nbytes))
            self.state = self._step(
                self.state,
                jax.device_put(buf, self.device),
                jax.device_put(pairbuf, self.device),
            )
            return
        self.state = self._step(self.state, jax.device_put(buf, self.device))

    def _empty_packed(self) -> np.ndarray:
        """Identity-fold pad for a partial superbatch tail: a packed empty
        batch (n_valid 0, n_pairs 0, identity-filled extreme tables, zero
        HLL registers) folds as a no-op, so padding the tail to K keeps
        ONE compiled superstep instead of one per tail length."""
        if self._empty_buf is None:
            self._empty_buf = pack_batch(
                RecordBatch.empty(0), self.config, use_native=self.use_native
            )
        return self._empty_buf

    def update_superbatch(self, staged: "list[StagedBatch | RecordBatch]") -> None:
        """Fold up to K batches in one scanned dispatch (in list order —
        byte-identical to K sequential ``update`` calls).  Blocks in the
        dispatch queue's throttle while ``dispatch_depth`` superbatches
        are already in flight; that blocking is the backpressure that
        keeps staged-buffer memory bounded."""
        k = self.superbatch_k
        if not staged or len(staged) > k:
            raise ValueError(f"superbatch of {len(staged)} batches (K={k})")
        self._queue.throttle()  # before staging: bounds host rows too
        rows = self._stager.next_slot()
        pair_lists = []
        for i, item in enumerate(staged):
            if isinstance(item, StagedBatch):
                np.copyto(rows[i], np.asarray(item.buf))
                if self._compact and item.pairs is not None:
                    pair_lists.append(item.pairs)
            else:
                pack_batch(
                    item, self.config, use_native=self.use_native, out=rows[i]
                )
                if self._compact:
                    pair_lists.append(
                        batch_alive_pairs(item, self.config, self.use_native)
                    )
        for i in range(len(staged), k):
            np.copyto(rows[i], self._empty_packed())
        obs_metrics.WIRE_BYTES.inc(int(rows.nbytes))
        bufs = jax.device_put(rows, self.device)
        if self._compact:
            # The compaction tentpole: LWW-merge the superbatch's pairs in
            # fold order into ONE bounded table — the device applies it
            # once after the scan instead of scattering inside every scan
            # step (identity-padded tail rows contribute no pairs).
            pairbuf = self._pack_pairs(pair_lists, self._pair_cap_k)
            obs_metrics.WIRE_BYTES.inc(int(pairbuf.nbytes))
            self.state, token = self._superstep(
                self.state, bufs, jax.device_put(pairbuf, self.device)
            )
        else:
            self.state, token = self._superstep(self.state, bufs)
        self._queue.launched(token, len(staged))

    def drain_dispatch(self) -> None:
        """Retire every in-flight superbatch dispatch without launching a
        new one — the engine's failure path calls this before the final
        snapshot so the dispatch-latency histogram and in-flight gauge
        close out and the snapshotted state is provably quiescent.  (The
        single-device twin of ShardedTpuBackend.drain_dispatch, where the
        no-new-collective property is what makes it lockstep-safe.)"""
        if self.superbatch_k > 1:
            self._queue.drain()

    def block_until_ready(self) -> None:
        self.drain_dispatch()
        jax.block_until_ready(self.state)

    # -- snapshot/resume (checkpoint.py) -------------------------------------

    def get_state(self) -> AnalyzerState:
        return self.state

    def set_state(self, host_state: AnalyzerState) -> None:
        self.state = jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), self.device), host_state
        )

    def finalize(self) -> TopicMetrics:
        # Retire every in-flight dispatch first so the latency histogram
        # is complete (device_get below syncs anyway).
        self.drain_dispatch()
        host_state = jax.tree.map(np.asarray, jax.device_get(self.state))
        return metrics_from_state(host_state, self.config, self.init_now_s)
