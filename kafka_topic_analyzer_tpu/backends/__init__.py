"""Metric backends.

The reference's single extension seam is ``trait MetricHandler`` with one
per-message callback (src/kafka.rs:18-20).  The TPU build widens that seam to
a *batched* `MetricBackend`: sources feed `RecordBatch`es, the backend folds
them into its accumulator state, and `finalize()` yields a `TopicMetrics`.
Backends: ``cpu`` (numpy, exact oracle) and ``tpu`` (jax, single-device or
sharded over a Mesh).
"""

from kafka_topic_analyzer_tpu.backends.base import MetricBackend, make_backend  # noqa: F401
