"""The pure analyzer step: fold one record batch into the analyzer state.

This is the device-side computation shared by the single-device TPU backend
(jitted directly) and the sharded backend (wrapped in ``shard_map`` —
parallel/sharded.py).  It is a pure function of (state, batch arrays) with
the config captured statically, so each feature combination compiles once.

It replaces the reference's hot loop body (src/kafka.rs:98-133 fanning out to
``handle_message`` per message) with a handful of fused batched reductions.
"""

from __future__ import annotations

from typing import Dict

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.models.compaction import AliveBitmapState, HLLState
from kafka_topic_analyzer_tpu.models.message_metrics import MessageMetricsState
from kafka_topic_analyzer_tpu.models.quantiles import DDSketchState
from kafka_topic_analyzer_tpu.models.state import AnalyzerState
from kafka_topic_analyzer_tpu.jax_support import jnp
from kafka_topic_analyzer_tpu.ops.bitmap import bitmap_apply_pairs
from kafka_topic_analyzer_tpu.ops.counters import counters_update, extremes_update
from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_update
from kafka_topic_analyzer_tpu.ops.hll import hll_apply


def apply_pair_table(
    state: AnalyzerState,
    pairs,
    config: AnalyzerConfig,
    space_index=0,
) -> AnalyzerState:
    """Apply one dispatch's compacted alive table (DESIGN.md §19).

    ``pairs`` is the `packing.unpack_pair_table_device` dict: the host
    already LWW-merged every batch of the dispatch in stream order, so
    ONE apply — after the dispatch's scan — replays exactly what the
    per-batch scatters inside the scan body would have produced (LWW
    compaction is LWW-associative), paying the bitmap update once per
    dispatch instead of once per batch.  The table's form decides the
    kernel (one rule, packing.alive_table_mode, read here via the section
    names): set/clear word MASKS merge elementwise like any other v5
    table — no scatter at all — while the bounded pair list keeps the
    scatter apply for slot spaces too large to mask.  Under a
    space-sharded mesh each shard masks/slices to its slot range
    (``space_index``); the table is replicated over the space axis by its
    input spec, so no per-step collective remains on the compacted path."""
    if state.alive is None:
        return state
    if "alive_set" in pairs:
        from kafka_topic_analyzer_tpu.ops.bitmap import bitmap_apply_masks

        words = bitmap_apply_masks(
            state.alive.words,
            pairs["alive_set"],
            pairs["alive_clear"],
            bits=config.alive_bitmap_bits,
            space_index=space_index,
            space_shards=config.space_shards,
        )
    else:
        words = bitmap_apply_pairs(
            state.alive.words,
            pairs["alive_slot"],
            pairs["alive_flag"],
            pairs["n_pairs"],
            bits=config.alive_bitmap_bits,
            space_index=space_index,
            space_shards=config.space_shards,
        )
    return AnalyzerState(
        metrics=state.metrics,
        alive=AliveBitmapState(words=words),
        hll=state.hll,
        quantiles=state.quantiles,
    )


def superbatch_fold(
    state: AnalyzerState,
    bufs,
    unpack,
    config: AnalyzerConfig,
    space_index=0,
    space_axis: "str | None" = None,
    pairs=None,
):
    """Fold a stacked superbatch — K packed buffers on a leading axis —
    into the state with a single ``lax.scan`` over that axis.

    This is the dispatch-amortization half of the superbatch layer: ONE
    jitted dispatch (state donated once) folds K batches, where the
    per-batch path paid K dispatches and K donation round-trips.  The
    scan body is exactly ``analyzer_step`` on ``unpack(bufs[k])``, applied
    k = 0..K-1 in order — the same order the sequential path dispatches —
    so every fold (including the order-sensitive last-writer-wins alive
    bitmap) produces byte-identical state.  ``unpack`` is injected (a
    closure over ``packing.unpack_device`` and the per-chunk config) so
    this module stays free of the wire-layout dependency; under a mesh it
    may use ``space_axis`` collectives — collectives inside a scan body
    run once per step, in step order, preserving the lockstep contract.

    Returns ``(state, n_valid)`` where ``n_valid`` is the int32[K] vector
    of per-batch valid counts: a small non-donated output the backends
    use as a completion token for the bounded in-flight dispatch queue
    (it cannot alias a donated state leaf, so blocking on it is safe
    after later dispatches have consumed the state).

    ``pairs`` (the compacted alive path) is the dispatch's merged pair
    table, applied ONCE after the scan — see `apply_pair_table`; order is
    preserved because the host merge already resolved per-slot last
    writers across the K batches, and every other fold is
    order-insensitive.
    """
    from kafka_topic_analyzer_tpu.jax_support import lax

    def body(st, buf):
        arrays = unpack(buf)
        return (
            analyzer_step(st, arrays, config, space_index, space_axis),
            arrays["n_valid"],
        )

    state, n_valid = lax.scan(body, state, bufs)
    if pairs is not None:
        state = apply_pair_table(state, pairs, config, space_index)
    return state, n_valid


def _apply_alive(
    alive_state,
    arrays: Dict[str, "jnp.ndarray"],
    config: AnalyzerConfig,
    space_index,
    space_axis: "str | None",
):
    """Alive-bitmap pair application shared by both wire formats (the
    pairs are already host-pre-reduced in v4 AND v5, so the step is
    identical).  Returns the new AliveBitmapState."""
    if space_axis is not None and config.space_shards > 1:
        from kafka_topic_analyzer_tpu.jax_support import lax

        # Route over ICI: gather every space shard's pair chunk, then
        # apply them in source order (chunk s holds records
        # [s*C, (s+1)*C) of the data row's batch, and all_gather
        # stacks by axis index, so gathered order == record order).
        #
        # Documented trade-off (ADVICE r2): the unrolled loop applies
        # all S chunks on EVERY space shard, so per-step bitmap work
        # (and trace size) is replicated S-fold instead of scaling
        # down with the space axis.  Acceptable at the small S this
        # targets (2-4 on one slice); if large space meshes become a
        # target, switch to a fori_loop over a stacked pair array or
        # pre-route pairs by slot range so each shard applies only
        # its own slots.
        slots = lax.all_gather(arrays["alive_slot"], space_axis)
        flags = lax.all_gather(arrays["alive_flag"], space_axis)
        counts = lax.all_gather(arrays["n_pairs"], space_axis)
        words = alive_state.words
        for s in range(config.space_shards):
            words = bitmap_apply_pairs(
                words,
                slots[s],
                flags[s],
                counts[s],
                bits=config.alive_bitmap_bits,
                space_index=space_index,
                space_shards=config.space_shards,
            )
    else:
        words = bitmap_apply_pairs(
            alive_state.words,
            arrays["alive_slot"],
            arrays["alive_flag"],
            arrays["n_pairs"],
            bits=config.alive_bitmap_bits,
            space_index=space_index,
            space_shards=config.space_shards,
        )
    return AliveBitmapState(words=words)


def _analyzer_step_v5(
    state: AnalyzerState,
    arrays: Dict[str, "jnp.ndarray"],
    config: AnalyzerConfig,
    space_index=0,
    space_axis: "str | None" = None,
) -> AnalyzerState:
    """Wire-v5 fold: the batch arrives as per-partition partial-fold
    TABLES (packing.py module docstring), so every reduction here is an
    elementwise table merge — integer adds for counters and DDSketch
    buckets, min/max for extremes, max for HLL registers — O(P·H) work
    per dispatch where the v4 step scattered O(B) records.  Associativity
    and commutativity of those integer merges (DESIGN.md §2/§16) is what
    makes the result byte-identical to the v4 fold; the superbatch scan
    and sharded chunk paths carry over untouched for the same reason."""
    m = state.metrics
    delta = arrays["counts"]  # int64[P, 7], COUNTER_CHANNELS order
    if config.use_pallas_counters:
        from kafka_topic_analyzer_tpu.ops.pallas_counters import (
            pallas_counters_merge,
        )

        per_partition = pallas_counters_merge(m.per_partition, delta)
    else:
        per_partition = m.per_partition + delta
    earliest, latest, smallest, largest = extremes_update(
        m.earliest_s,
        m.latest_s,
        m.smallest,
        m.largest,
        arrays["ts_min"],
        arrays["ts_max"],
        arrays["sz_min"],
        arrays["sz_max"],
    )
    metrics = MessageMetricsState(
        per_partition=per_partition,
        earliest_s=earliest,
        latest_s=latest,
        smallest=smallest,
        largest=largest,
        # Global sums are the column sums of the delta table: channels 5/6
        # are the key/value byte sums, channel 0 the record count.
        overall_size=m.overall_size + jnp.sum(delta[:, 5] + delta[:, 6]),
        overall_count=m.overall_count + jnp.sum(delta[:, 0]),
    )

    alive_state = state.alive
    if alive_state is not None and "alive_slot" in arrays:
        # Compacted configs ship no per-row pair sections: the dispatch's
        # merged pair table applies ONCE after the scan (apply_pair_table).
        alive_state = _apply_alive(
            alive_state, arrays, config, space_index, space_axis
        )

    hll_state = state.hll
    if hll_state is not None:
        if "hll_regs" in arrays:
            regs = jnp.maximum(
                hll_state.regs,
                arrays["hll_regs"].astype(jnp.int32).reshape(
                    -1, hll_state.regs.shape[1]
                ),
            )
        elif "hll_idx32" in arrays:
            # v5 flat pairs: the index already encodes (row << p | bucket),
            # so the scatter-max lands on the flattened register file.
            from kafka_topic_analyzer_tpu.ops.hll import hll_apply_flat

            regs = hll_apply_flat(
                hll_state.regs, arrays["hll_idx32"], arrays["hll_rho"]
            )
        else:
            regs = hll_apply(
                hll_state.regs, arrays["hll_idx"], arrays["hll_rho"],
                partition=None,
            )
        hll_state = HLLState(regs=regs)

    q_state = state.quantiles
    if q_state is not None:
        q_state = DDSketchState(counts=q_state.counts + arrays["qcounts"])

    return AnalyzerState(
        metrics=metrics, alive=alive_state, hll=hll_state, quantiles=q_state
    )


def analyzer_step(
    state: AnalyzerState,
    arrays: Dict[str, "jnp.ndarray"],
    config: AnalyzerConfig,
    space_index=0,
    space_axis: "str | None" = None,
) -> AnalyzerState:
    """Fold one batch (or, under a space-sharded mesh, one contiguous CHUNK
    of a data row's batch) into the analyzer state.

    ``space_axis`` names the mesh axis the record stream is chunked over
    (parallel/sharded.py).  When given, bitmap updates are redistributed
    on-device: every space shard all_gathers the (slot, aliveness) pair
    chunks over ICI and applies them in source-chunk order, which preserves
    exact last-writer-wins semantics even when one key's updates straddle
    chunk boundaries (host dedupe is per chunk, so cross-chunk duplicates
    are resolved here by application order).  All other reductions stay
    chunk-local; the space axis is reduced once at finalize.

    Wire-v5 buffers (the per-partition combiner tables — ``counts``
    present in ``arrays``) take the table-merge fold instead; the
    per-record path below is the v4 layout's."""
    if "counts" in arrays:
        return _analyzer_step_v5(
            state, arrays, config, space_index, space_axis
        )
    valid = arrays["valid"]
    key_null = arrays["key_null"]
    value_null = arrays["value_null"]
    key_len = arrays["key_len"]
    value_len = arrays["value_len"]

    m = state.metrics
    if config.use_pallas_counters:
        from kafka_topic_analyzer_tpu.ops.pallas_counters import (
            pallas_counters_update as counters_fn,
        )
    else:
        counters_fn = counters_update
    per_partition = counters_fn(
        m.per_partition,
        arrays["partition"],
        key_len,
        value_len,
        key_null,
        value_null,
        valid,
        config.num_partitions,
    )
    earliest, latest, smallest, largest = extremes_update(
        m.earliest_s,
        m.latest_s,
        m.smallest,
        m.largest,
        arrays["ts_min"],
        arrays["ts_max"],
        arrays["sz_min"],
        arrays["sz_max"],
    )
    kn = valid & ~key_null
    vn = valid & ~value_null
    k_bytes = jnp.where(kn, key_len, 0).astype(jnp.int64)
    v_bytes = jnp.where(vn, value_len, 0).astype(jnp.int64)
    metrics = MessageMetricsState(
        per_partition=per_partition,
        earliest_s=earliest,
        latest_s=latest,
        smallest=smallest,
        largest=largest,
        overall_size=m.overall_size + jnp.sum(k_bytes + v_bytes),
        overall_count=m.overall_count + jnp.sum(valid.astype(jnp.int64)),
    )

    alive_state = state.alive
    if alive_state is not None and "alive_slot" in arrays:
        # Compacted configs ship no per-row pair sections: the dispatch's
        # merged pair table applies ONCE after the scan (apply_pair_table).
        alive_state = _apply_alive(
            alive_state, arrays, config, space_index, space_axis
        )

    hll_state = state.hll
    if hll_state is not None:
        if "hll_regs" in arrays:
            # Table mode (wire v3): the host already reduced the batch to
            # a register table (R rows — 1 global or P per-partition) —
            # merge elementwise, no scatter on the device hot path.
            regs = jnp.maximum(
                hll_state.regs,
                arrays["hll_regs"].astype(jnp.int32).reshape(
                    -1, hll_state.regs.shape[1]
                ),
            )
        else:
            regs = hll_apply(
                hll_state.regs,
                arrays["hll_idx"],
                arrays["hll_rho"],
                partition=(
                    arrays["partition"]
                    if config.distinct_keys_per_partition
                    else None
                ),
            )
        hll_state = HLLState(regs=regs)

    q_state = state.quantiles
    if q_state is not None:
        msg_size = k_bytes + v_bytes
        counts = ddsketch_update(
            q_state.counts,
            msg_size,
            vn,  # quantiles over sized (non-tombstone) messages, like min/max
            config.quantile_gamma,
            config.quantile_buckets,
            partition=(
                arrays["partition"] if config.quantiles_per_partition else None
            ),
        )
        q_state = DDSketchState(counts=counts)

    return AnalyzerState(
        metrics=metrics, alive=alive_state, hll=hll_state, quantiles=q_state
    )
