"""Backend protocol + factory."""

from __future__ import annotations

import abc
import functools

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics


class MetricBackend(abc.ABC):
    """Batched replacement for the reference's ``MetricHandler`` seam
    (src/kafka.rs:18-20): updates fold whole record batches, results are read
    once at the end.

    Contract:
    - `update` must be called with batches whose per-partition record order
      matches offset order (records.py ordering contract);
    - `update` may be asynchronous (device dispatch); `finalize` synchronizes
      and returns host-side results.
    """

    def __init__(self, config: AnalyzerConfig):
        self.config = config

    @abc.abstractmethod
    def update(self, batch: RecordBatch) -> None:
        ...

    @abc.abstractmethod
    def finalize(self) -> TopicMetrics:
        ...


def _timed(fn, hist):
    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        with hist.time():
            return fn(self, *args, **kwargs)
    return wrapped


def instrument_steps(cls):
    """Class decorator for concrete backends: record step-dispatch and
    finalize latency into the obs histograms.  The engine's step entry
    point is ``update_shards`` when the class defines one (the sharded
    backend's ``update`` delegates to it — wrapping both would double
    count), ``update`` otherwise.  Async backends therefore book dispatch
    latency, not device time — the device side lives in the
    ``--profile-dir`` XLA trace."""
    step = "update_shards" if "update_shards" in cls.__dict__ else "update"
    setattr(cls, step, _timed(
        cls.__dict__[step], obs_metrics.BACKEND_STEP_SECONDS))
    setattr(cls, "finalize", _timed(
        cls.__dict__["finalize"], obs_metrics.BACKEND_FINALIZE_SECONDS))
    return cls


def make_backend(name: str, config: AnalyzerConfig) -> MetricBackend:
    """Factory for ``--backend {cpu,tpu}`` (default cpu per BASELINE.json)."""
    if name == "cpu":
        from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend

        return CpuExactBackend(config)
    if name == "tpu":
        from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

        return TpuBackend(config)
    raise ValueError(f"unknown backend {name!r} (expected 'cpu' or 'tpu')")
