"""Backend protocol + factory + the superbatch dispatch queue."""

from __future__ import annotations

import abc
import collections
import functools
import time

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics


class DispatchQueue:
    """Bounded in-flight superbatch dispatch tracking (``--dispatch-depth``).

    Device dispatch is asynchronous: without a bound, a fast ingest side
    could stack arbitrarily many staged superbatches (host staging rows +
    device input buffers) behind a slow device.  This queue caps the
    in-flight count at ``depth`` using per-dispatch completion tokens —
    small non-donated step outputs that become ready exactly when their
    superbatch's fold (and therefore its host→device transfer) completed.

    Contract, enforced by tools/lint.sh rule 4: all in-flight bookkeeping
    lives HERE (no other module touches an inflight container), and every
    dispatch site calls ``throttle()`` before launching + ``launched()``
    after — so no drive loop can ever hold more than ``depth`` staged
    superbatches.  Blocking inside ``throttle`` is the backpressure that
    propagates into the ingest fan-in (the engine thread stops draining
    the worker queues, which fill, which stalls the workers).
    """

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError("dispatch depth must be >= 1")
        self.depth = depth
        self._inflight: "collections.deque" = collections.deque()

    def __len__(self) -> int:
        return len(self._inflight)

    def throttle(self) -> None:
        """Block until fewer than ``depth`` dispatches are in flight —
        call BEFORE staging the device transfer of the next superbatch.

        Time spent blocked here is booked on
        ``kta_dispatch_throttle_seconds_total`` UNCONDITIONALLY (flight
        recorder on or off): this wait is the backpressure at the launch
        site, and the one signal that directly decides dispatch-bound vs
        ingest-bound (obs/doctor.py) — an unbooked stall here made every
        manual bench ledger reconstruct it from residuals."""
        self._reap()
        if len(self._inflight) < self.depth:
            return
        t0 = time.perf_counter()
        try:
            while len(self._inflight) >= self.depth:
                self._retire(block=True)
        finally:
            obs_metrics.DISPATCH_THROTTLE_SECONDS.inc(
                time.perf_counter() - t0
            )

    def launched(self, token, batches: int) -> None:
        """Record a dispatch just launched.  ``token`` must be a device
        value that completes with the dispatch and is never donated to a
        later dispatch (backends/step.py::superbatch_fold returns one)."""
        self._inflight.append((token, time.perf_counter(), batches))
        obs_metrics.DISPATCH_INFLIGHT.set(len(self._inflight))
        obs_metrics.SUPERBATCH_SIZE.observe(batches)

    def drain(self) -> None:
        """Retire every in-flight dispatch (finalize / block_until_ready)."""
        while self._inflight:
            self._retire(block=True)

    def _reap(self) -> None:
        """Opportunistically retire already-completed dispatches so the
        latency histogram and in-flight gauge stay fresh without blocking."""
        while self._inflight:
            ready = getattr(self._inflight[0][0], "is_ready", None)
            if ready is None or not ready():
                return
            self._retire(block=False)

    def _retire(self, block: bool) -> None:
        import jax

        token, t0, _batches = self._inflight[0]
        if block:
            jax.block_until_ready(token)
        self._inflight.popleft()
        obs_metrics.DISPATCH_SECONDS.observe(time.perf_counter() - t0)
        obs_metrics.DISPATCH_INFLIGHT.set(len(self._inflight))


class MetricBackend(abc.ABC):
    """Batched replacement for the reference's ``MetricHandler`` seam
    (src/kafka.rs:18-20): updates fold whole record batches, results are read
    once at the end.

    Contract:
    - `update` must be called with batches whose per-partition record order
      matches offset order (records.py ordering contract);
    - `update` may be asynchronous (device dispatch); `finalize` synchronizes
      and returns host-side results.
    """

    def __init__(self, config: AnalyzerConfig):
        self.config = config

    @abc.abstractmethod
    def update(self, batch: RecordBatch) -> None:
        ...

    @abc.abstractmethod
    def finalize(self) -> TopicMetrics:
        ...


def _timed(fn, hist):
    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        with hist.time():
            return fn(self, *args, **kwargs)
    return wrapped


def instrument_steps(cls):
    """Class decorator for concrete backends: record step-dispatch and
    finalize latency into the obs histograms.  The engine's step entry
    point is ``update_shards`` when the class defines one (the sharded
    backend's ``update`` delegates to it — wrapping both would double
    count), ``update`` otherwise.  Async backends therefore book dispatch
    latency, not device time — the device side lives in the
    ``--profile-dir`` XLA trace."""
    step = "update_shards" if "update_shards" in cls.__dict__ else "update"
    setattr(cls, step, _timed(
        cls.__dict__[step], obs_metrics.BACKEND_STEP_SECONDS))
    # Superbatch entry points are separate engine-facing steps (they do not
    # delegate to update/update_shards), so they book their own dispatch
    # latency — includes throttle blocking, i.e. real backpressure time.
    for super_step in ("update_superbatch", "update_shards_superbatch"):
        if super_step in cls.__dict__:
            setattr(cls, super_step, _timed(
                cls.__dict__[super_step], obs_metrics.BACKEND_STEP_SECONDS))
    setattr(cls, "finalize", _timed(
        cls.__dict__["finalize"], obs_metrics.BACKEND_FINALIZE_SECONDS))
    return cls


def make_backend(
    name: str, config: AnalyzerConfig, dispatch=None
) -> MetricBackend:
    """Factory for ``--backend {cpu,tpu}`` (default cpu per BASELINE.json).
    ``dispatch`` (config.DispatchConfig) sizes the tpu backend's superbatch
    layer; the cpu oracle has no device dispatch to amortize, so callers
    must not pass a K>1 dispatch config with it (cli.resolve_dispatch)."""
    if name == "cpu":
        from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend

        return CpuExactBackend(config)
    if name == "tpu":
        from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

        return TpuBackend(config, dispatch=dispatch)
    raise ValueError(f"unknown backend {name!r} (expected 'cpu' or 'tpu')")
