"""Backend protocol + factory."""

from __future__ import annotations

import abc

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import TopicMetrics


class MetricBackend(abc.ABC):
    """Batched replacement for the reference's ``MetricHandler`` seam
    (src/kafka.rs:18-20): updates fold whole record batches, results are read
    once at the end.

    Contract:
    - `update` must be called with batches whose per-partition record order
      matches offset order (records.py ordering contract);
    - `update` may be asynchronous (device dispatch); `finalize` synchronizes
      and returns host-side results.
    """

    def __init__(self, config: AnalyzerConfig):
        self.config = config

    @abc.abstractmethod
    def update(self, batch: RecordBatch) -> None:
        ...

    @abc.abstractmethod
    def finalize(self) -> TopicMetrics:
        ...


def make_backend(name: str, config: AnalyzerConfig) -> MetricBackend:
    """Factory for ``--backend {cpu,tpu}`` (default cpu per BASELINE.json)."""
    if name == "cpu":
        from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend

        return CpuExactBackend(config)
    if name == "tpu":
        from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend

        return TpuBackend(config)
    raise ValueError(f"unknown backend {name!r} (expected 'cpu' or 'tpu')")
