"""Turn a (host-fetched) AnalyzerState into TopicMetrics."""

from __future__ import annotations

import numpy as np

from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_quantiles
from kafka_topic_analyzer_tpu.ops.hll import hll_estimate
from kafka_topic_analyzer_tpu.results import (
    QUANTILE_PROBS,
    QuantileSummary,
    TopicMetrics,
    finalize_extremes,
)


def metrics_from_state(state, config: AnalyzerConfig, init_now_s: int) -> TopicMetrics:
    """``state`` is an AnalyzerState whose leaves are host numpy arrays
    (already merged across devices if the run was sharded)."""
    m = state.metrics
    # Per-partition extremes reduce to the reference's global lines.
    earliest, latest, smallest = finalize_extremes(
        int(np.min(m.earliest_s)),
        int(np.max(m.latest_s)),
        int(np.min(m.smallest)),
        init_now_s,
    )
    extremes = np.stack(
        [
            np.asarray(m.earliest_s),
            np.asarray(m.latest_s),
            np.asarray(m.smallest),
            np.asarray(m.largest),
        ],
        axis=1,
    )
    alive_keys = None
    if state.alive is not None:
        words = np.asarray(state.alive.words)
        alive_keys = int(np.bitwise_count(words).sum())
    hll = None
    hll_pp = None
    if state.hll is not None:
        regs = np.asarray(state.hll.regs)
        # Global estimate from the union of rows (elementwise max is the HLL
        # merge); per-partition estimates from each row.
        hll = hll_estimate(regs.max(axis=0))
        if config.distinct_keys_per_partition:
            hll_pp = [hll_estimate(regs[r]) for r in range(regs.shape[0])]
    quantiles = None
    quantiles_pp = None
    if state.quantiles is not None:
        counts = np.asarray(state.quantiles.counts)
        # Global quantiles from the exact sum of rows (DDSketch merge = add).
        vals = ddsketch_quantiles(
            counts.sum(axis=0), QUANTILE_PROBS, config.quantile_gamma
        )
        quantiles = QuantileSummary(list(QUANTILE_PROBS), vals)
        if config.quantiles_per_partition:
            quantiles_pp = [
                QuantileSummary(
                    list(QUANTILE_PROBS),
                    ddsketch_quantiles(
                        counts[r], QUANTILE_PROBS, config.quantile_gamma
                    ),
                )
                for r in range(counts.shape[0])
            ]
    return TopicMetrics(
        partitions=list(range(config.num_partitions)),
        per_partition=np.asarray(m.per_partition),
        earliest_ts_s=earliest,
        latest_ts_s=latest,
        smallest_message=smallest,
        largest_message=int(np.max(m.largest)),
        overall_size=int(m.overall_size),
        overall_count=int(m.overall_count),
        alive_keys=alive_keys,
        distinct_keys_hll=hll,
        distinct_keys_hll_per_partition=hll_pp,
        quantiles=quantiles,
        quantiles_per_partition=quantiles_pp,
        per_partition_extremes=extremes,
        init_now_s=init_now_s,
    )
