"""Central jax import + configuration.

Every device-side module imports jax through here so that 64-bit integer
support is enabled exactly once, before any tracing happens.  The analyzer's
accumulators are genuinely 64-bit (byte sums over billions of records exceed
2^32; the reference uses ``u64`` throughout, src/metric.rs:12-26), so we
enable ``jax_enable_x64`` globally.  Per-record *contributions* stay int32
where possible to keep the TPU hot path cheap; only the accumulator state is
64-bit.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

def force_platform(platforms: str) -> None:
    """Force the jax platform list even when a sitecustomize pinned
    JAX_PLATFORMS before we ran (e.g. axon's TPU tunnel).

    When the override excludes such a tunnel plugin, its factory is dropped
    outright — its client init runs even for non-selected platforms and
    blocks indefinitely if the tunnel is unreachable.  Must run before any
    backend is initialized.  Best-effort: relies on a private jax attribute,
    so failures are swallowed (the config update alone usually suffices).
    """
    try:
        jax.config.update("jax_platforms", platforms)
        if "axon" not in platforms:
            from jax._src import xla_bridge as _xb

            _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


# Escape hatch for CLI users (e.g. run the tpu backend on the host CPU when
# the accelerator tunnel is down): KTA_JAX_PLATFORMS=cpu.
_override = os.environ.get("KTA_JAX_PLATFORMS")
if _override:
    force_platform(_override)

import jax.numpy as jnp  # noqa: E402,F401
from jax import lax  # noqa: E402,F401
