"""Central jax import + configuration.

Every device-side module imports jax through here so that 64-bit integer
support is enabled exactly once, before any tracing happens.  The analyzer's
accumulators are genuinely 64-bit (byte sums over billions of records exceed
2^32; the reference uses ``u64`` throughout, src/metric.rs:12-26), so we
enable ``jax_enable_x64`` globally.  Per-record *contributions* stay int32
where possible to keep the TPU hot path cheap; only the accumulator state is
64-bit.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

def force_platform(platforms: str) -> None:
    """Force the jax platform list even when a sitecustomize pinned
    JAX_PLATFORMS before we ran (e.g. axon's TPU tunnel).

    When the override excludes such a tunnel plugin, its factory is dropped
    outright — its client init runs even for non-selected platforms and
    blocks indefinitely if the tunnel is unreachable.  Must run before any
    backend is initialized.  Best-effort: relies on a private jax attribute,
    so failures are swallowed (the config update alone usually suffices).
    """
    try:
        jax.config.update("jax_platforms", platforms)
        if "axon" not in platforms:
            from jax._src import xla_bridge as _xb

            _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def probe_device_platform(timeout_s: float) -> "str | None":
    """One shared device probe: run a real device op (not just client
    init — a half-up tunnel can pass init and block on the first op) in a
    killable subprocess.  Returns the default platform name on success
    ("cpu" when no accelerator exists or its plugin failed FAST and jax
    fell back to host CPU), or None on a hang/timeout/crash.

    Callers split the verdict: None means a wedged tunnel (fall back AND
    warn); "cpu" means a working CPU-only environment (proceed, but any
    benchmark must not present its numbers as chip measurements)."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.numpy.arange(4).sum().block_until_ready(); "
             "print('ok', jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s, check=False,
        )
        for line in probe.stdout.splitlines():
            if line.startswith("ok "):
                return line.split(None, 1)[1].strip()
        return None
    except subprocess.TimeoutExpired:
        return None


def probe_accelerator_alive(timeout_s: float) -> bool:
    """True iff a responsive NON-cpu device answered the probe."""
    platform = probe_device_platform(timeout_s)
    return platform is not None and platform != "cpu"


def ensure_responsive_accelerator(timeout_s: float = 240.0) -> bool:
    """Probe the default accelerator in a killable subprocess; on timeout or
    failure, force the host CPU platform so the caller cannot hang on a
    wedged device tunnel.  Returns True when the accelerator is healthy (or
    an explicit platform override / prior verdict makes probing moot).

    Used by bench.py, __graft_entry__, and the CLI's tpu backend path
    (cli.py::_make_cli_backend); KTA_ACCEL_OK=1 short-circuits so
    orchestrators (tools/bench_all.py) probe once for many children.
    """
    import sys

    if os.environ.get("KTA_JAX_PLATFORMS") or os.environ.get("KTA_ACCEL_OK"):
        return True
    try:
        timeout_s = float(os.environ.get("KTA_ACCEL_TIMEOUT") or timeout_s)
    except ValueError:
        pass  # malformed override: keep the default, like the other knobs
    platform = probe_device_platform(timeout_s)
    if platform == "cpu":
        # A working CPU-only environment (no accelerator plugin, or it
        # failed fast): nothing can hang, nothing to force, and warning
        # about an "unresponsive accelerator" would be a wrong diagnosis.
        # Callers that benchmark flag cpu-platform results themselves.
        return True
    if platform is not None:
        return True
    print(
        "WARNING: accelerator unresponsive — forcing the cpu platform; "
        "results will NOT reflect TPU performance",
        file=sys.stderr,
    )
    force_platform("cpu")
    return False


# Escape hatch for CLI users (e.g. run the tpu backend on the host CPU when
# the accelerator tunnel is down): KTA_JAX_PLATFORMS=cpu.
_override = os.environ.get("KTA_JAX_PLATFORMS")
if _override:
    force_platform(_override)

# Persistent XLA compilation cache: the analyzer compiles the same handful
# of programs every run (one step per feature combination), and first TPU
# compiles cost 20-40 s — cache them across processes.  KTA_CACHE_DIR
# overrides the location; KTA_CACHE_DIR=off disables.
_cache_dir = os.environ.get("KTA_CACHE_DIR")
if _cache_dir != "off":
    try:
        if not _cache_dir:
            _cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "kta-jax"
            )
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # (jax's default min-compile-time threshold of 1 s already skips
        # caching trivial CPU programs while catching TPU compiles.)
    except Exception:
        pass  # cache is an optimization; never fail startup over it

import jax.numpy as jnp  # noqa: E402,F401
from jax import lax  # noqa: E402,F401
