"""Central jax import + configuration.

Every device-side module imports jax through here so that 64-bit integer
support is enabled exactly once, before any tracing happens.  The analyzer's
accumulators are genuinely 64-bit (byte sums over billions of records exceed
2^32; the reference uses ``u64`` throughout, src/metric.rs:12-26), so we
enable ``jax_enable_x64`` globally.  Per-record *contributions* stay int32
where possible to keep the TPU hot path cheap; only the accumulator state is
64-bit.
"""

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402,F401
from jax import lax  # noqa: E402,F401
