"""Central jax import + configuration.

Every device-side module imports jax through here so that 64-bit integer
support is enabled exactly once, before any tracing happens.  The analyzer's
accumulators are genuinely 64-bit (byte sums over billions of records exceed
2^32; the reference uses ``u64`` throughout, src/metric.rs:12-26), so we
enable ``jax_enable_x64`` globally.  Per-record *contributions* stay int32
where possible to keep the TPU hot path cheap; only the accumulator state is
64-bit.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-compat ``shard_map``: jax >= 0.5 exposes ``jax.shard_map``
    (replication checking spelled ``check_vma``); on older releases (the
    container ships 0.4.37) the same transform lives at
    ``jax.experimental.shard_map.shard_map`` with the knob spelled
    ``check_rep``.  All device-side callers route through here so the
    sharded backend works on both."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _esm

    return _esm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def varying_mesh_axes(x) -> frozenset:
    """Mesh axes ``x`` varies over under a check_vma shard_map (its aval's
    ``vma``), or an empty frozenset on jax versions that predate the vma
    machinery (0.4.x checks replication via ``check_rep`` instead and has
    no ``jax.typeof``) — callers then skip their pvary/vma plumbing."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", None) or frozenset()


def force_platform(platforms: str) -> None:
    """Force the jax platform list even when a sitecustomize pinned
    JAX_PLATFORMS before we ran (e.g. axon's TPU tunnel).

    Factories the override excludes are dropped outright, not merely
    deselected: a tunnel plugin's registration hook may re-assert its own
    ``jax_platforms`` config after us (axon's register() hard-sets
    "axon,cpu" in every process via sitecustomize), and its client init
    blocks indefinitely if the tunnel is unreachable.  Must run before any
    backend is initialized.  Best-effort: relies on a private jax attribute,
    so failures are swallowed (the config update alone usually suffices).
    """
    try:
        jax.config.update("jax_platforms", platforms)
        selected = {p.strip() for p in platforms.split(",") if p.strip()}
        # Only out-of-tree plugins are dropped: popping a builtin factory
        # (e.g. "tpu") also removes its platform from MLIR's known set and
        # breaks unrelated lowering registration (pallas import), while
        # builtins are never init-eager for non-selected platforms anyway.
        from jax._src import xla_bridge as _xb

        for name in [
            n for n in _xb._backend_factories
            if n not in selected and n.lower() not in _BUILTIN_PLATFORMS
        ]:
            _xb._backend_factories.pop(name, None)
    except Exception:
        pass


#: Builtin jax platforms — anything else registered in the backend-factory
#: table is an out-of-tree plugin (e.g. a device tunnel) whose client init
#: may block; force_platform and the KTA_ACCEL_OK short-circuit both key
#: off this distinction.
_BUILTIN_PLATFORMS = {"cpu", "tpu", "cuda", "gpu", "rocm", "metal"}


def _plugin_platforms() -> "set[str]":
    """Names of registered NON-builtin backend factories (lowercased).
    Best-effort: empty on any failure, which callers treat as 'no tunnel
    plugin present'."""
    try:
        from jax._src import xla_bridge as _xb

        return {
            n.lower() for n in _xb._backend_factories
            if n.lower() not in _BUILTIN_PLATFORMS
        }
    except Exception:
        return set()


def probe_device_platform(timeout_s: float) -> "str | None":
    """One shared device probe: run a real device op (not just client
    init — a half-up tunnel can pass init and block on the first op) in a
    killable subprocess.  Returns the default platform name on success
    ("cpu" when no accelerator exists or its plugin failed FAST and jax
    fell back to host CPU), or None on a hang/timeout/crash.

    Callers split the verdict: None means a wedged tunnel (fall back AND
    warn); "cpu" means a working CPU-only environment (proceed, but any
    benchmark must not present its numbers as chip measurements)."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.numpy.arange(4).sum().block_until_ready(); "
             "print('ok', jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s, check=False,
        )
        for line in probe.stdout.splitlines():
            if line.startswith("ok "):
                return line.split(None, 1)[1].strip()
        return None
    except subprocess.TimeoutExpired:
        return None


def probe_accelerator_alive(timeout_s: float) -> bool:
    """True iff a responsive NON-cpu device answered the probe."""
    platform = probe_device_platform(timeout_s)
    return platform is not None and platform != "cpu"


def ensure_responsive_accelerator(timeout_s: float = 240.0) -> "bool | str":
    """Probe the default accelerator in a killable subprocess; on timeout or
    failure, force the host CPU platform so the caller cannot hang on a
    wedged device tunnel.  Returns the probed platform name when a probe
    ran ("axon", "tpu", "cpu", ... — all truthy, so boolean callers keep
    working), True when an explicit platform override / prior verdict makes
    probing moot, and False when the accelerator is unresponsive.

    Used by bench.py, __graft_entry__, and the CLI's tpu backend path
    (cli.py::_make_cli_backend); KTA_ACCEL_OK short-circuits so
    orchestrators (tools/bench_all.py) probe once for many children.  The
    short-circuit value may carry the orchestrator's probed platform
    (KTA_ACCEL_OK=cpu) instead of the legacy bare "1".
    """
    import sys

    if os.environ.get("KTA_JAX_PLATFORMS"):
        return True
    verdict = os.environ.get("KTA_ACCEL_OK")
    if verdict:
        # Skip the probe, but do NOT skip the platform forcing: the device
        # tunnel's plugin factory (registered into every process by a
        # sitecustomize hook) runs its client init even for platforms a
        # JAX_PLATFORMS override excludes, so a wedged tunnel hangs
        # `jax.devices()` unless the excluded factory is dropped outright
        # (VERDICT r2 weak #1).  Honor an ambient JAX_PLATFORMS override
        # via force_platform — a no-op when the override includes the
        # tunnel platform — and a platform-carrying verdict of "cpu".
        ambient = os.environ.get("JAX_PLATFORMS")
        ambient_set = (
            {p.strip().lower() for p in ambient.split(",")} if ambient else set()
        )
        if ambient and not (ambient_set & _plugin_platforms()):
            # Only force when the ambient override steers AWAY from every
            # registered tunnel plugin (not just axon's): when it includes
            # a tunnel platform, the sitecustomize's own config (e.g.
            # "axon,cpu") is the working arrangement — don't clobber it.
            force_platform(ambient)
        elif verdict.strip().lower() == "cpu":
            force_platform("cpu")
        return True
    try:
        timeout_s = float(os.environ.get("KTA_ACCEL_TIMEOUT") or timeout_s)
    except ValueError:
        pass  # malformed override: keep the default, like the other knobs
    platform = probe_device_platform(timeout_s)
    if platform == "cpu":
        # A working CPU-only environment (no accelerator plugin, or it
        # failed fast): nothing can hang, nothing to force, and warning
        # about an "unresponsive accelerator" would be a wrong diagnosis.
        # Callers that benchmark flag cpu-platform results themselves.
        return platform
    if platform is not None:
        return platform
    sys.stderr.write(
        "WARNING: accelerator unresponsive — forcing the cpu platform; "
        "results will NOT reflect TPU performance\n"
    )
    force_platform("cpu")
    return False


def detect_cpu_fallback() -> bool:
    """True when jax ended up on the host CPU platform without an explicit
    KTA_JAX_PLATFORMS override — a fallback (fast-failing plugin, stale
    orchestrator verdict), not a deliberate choice.  Benchmark emitters use
    this to avoid presenting host numbers as chip numbers."""
    return (
        jax.devices()[0].platform == "cpu"
        and not os.environ.get("KTA_JAX_PLATFORMS")
    )


def mark_degraded(doc: dict) -> dict:
    """Stamp a benchmark JSON doc as a host-CPU fallback run: the headline
    vs_baseline ratio would read as the result at a glance (VERDICT r2
    weak #5), so it moves to a clearly-labeled key and goes null."""
    doc["degraded_cpu_fallback"] = True
    if doc.get("vs_baseline") is not None:
        doc["vs_baseline_on_fallback_host"] = doc["vs_baseline"]
        doc["vs_baseline"] = None
    return doc


# Escape hatch for CLI users (e.g. run the tpu backend on the host CPU when
# the accelerator tunnel is down): KTA_JAX_PLATFORMS=cpu.
_override = os.environ.get("KTA_JAX_PLATFORMS")
if _override:
    force_platform(_override)

# Persistent XLA compilation cache: the analyzer compiles the same handful
# of programs every run (one step per feature combination), and first TPU
# compiles cost 20-40 s — cache them across processes.  KTA_CACHE_DIR
# overrides the location; KTA_CACHE_DIR=off disables.
_cache_dir = os.environ.get("KTA_CACHE_DIR")
if _cache_dir != "off":
    try:
        if not _cache_dir:
            _cache_dir = os.path.join(
                os.path.expanduser("~"), ".cache", "kta-jax"
            )
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        # (jax's default min-compile-time threshold of 1 s already skips
        # caching trivial CPU programs while catching TPU compiles.)
    except Exception:
        pass  # cache is an optimization; never fail startup over it

import jax.numpy as jnp  # noqa: E402,F401
from jax import lax  # noqa: E402,F401
