"""Poison-frame quarantine: spool corrupt raw frames with a JSON sidecar.

When the scan runs with ``--on-corruption=quarantine``, every frame that
fails decode *deterministically* (the wire layer re-fetched it once and got
byte-identical garbage back) is written here before being skipped — the
same evidence-preservation discipline large-scale training data loaders
apply to poison samples: the pipeline finishes, and the bad bytes survive
for offline analysis instead of evaporating with the process.

Layout: one ``<topic>.p<partition>.o<anchor>.frame.bin`` (the raw frame
bytes, exactly as fetched) plus a ``.json`` sidecar describing it:

    {"topic", "partition", "anchor", "base_offset", "offset_start",
     "offset_end", "classification", "crc_expected", "crc_actual",
     "length", "sha256", "error"}

Filenames are keyed by the frame's *anchor* (the scan position at which it
was hit), which is stable across runs — so a ``--resume`` that re-walks an
already-quarantined span is a no-op here (`spool` returns None when the
sidecar already exists) and never double-spools.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Optional, Tuple


def _safe_topic(topic: str) -> str:
    """Kafka topic names allow [a-zA-Z0-9._-] only, but quarantine paths
    must stay safe even for a hostile broker's metadata."""
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in topic)


class QuarantineStore:
    """Append-only spool of poisoned frames under one directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _paths(self, topic: str, partition: int, anchor: int) -> "Tuple[str, str]":
        stem = os.path.join(
            self.directory, f"{_safe_topic(topic)}.p{partition}.o{anchor}"
        )
        return stem + ".frame.bin", stem + ".json"

    def spool(
        self,
        *,
        topic: str,
        partition: int,
        anchor: int,
        raw: bytes,
        classification: str,
        base_offset: int = -1,
        offset_start: int = -1,
        offset_end: int = -1,
        crc_expected: Optional[int] = None,
        crc_actual: Optional[int] = None,
        error: str = "",
    ) -> Optional[str]:
        """Write the frame + sidecar; returns the sidecar path, or None
        when this span was already quarantined (resume idempotence).  The
        sidecar is renamed into place LAST, so a sidecar's existence
        guarantees its .bin is complete."""
        bin_path, sidecar = self._paths(topic, partition, anchor)
        if os.path.exists(sidecar):
            return None
        meta = {
            "topic": topic,
            "partition": partition,
            "anchor": anchor,
            "base_offset": base_offset,
            "offset_start": offset_start,
            "offset_end": offset_end,
            "classification": classification,
            "crc_expected": crc_expected,
            "crc_actual": crc_actual,
            "length": len(raw),
            "sha256": hashlib.sha256(raw).hexdigest(),
            "error": error,
        }
        for path, payload in (
            (bin_path, raw),
            (sidecar, json.dumps(meta, sort_keys=True).encode() + b"\n"),
        ):
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(payload)
                os.replace(tmp, path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        return sidecar

    def entries(self) -> "list[str]":
        """Sidecar paths of every quarantined frame, sorted."""
        return sorted(
            os.path.join(self.directory, name)
            for name in os.listdir(self.directory)
            if name.endswith(".json")
        )

    @staticmethod
    def load(sidecar_path: str) -> "Tuple[dict, bytes]":
        """Round-trip one quarantined frame: (sidecar meta, raw bytes).
        Raises ValueError when the stored bytes do not match the sidecar's
        length/sha256 (a quarantine spool must itself be trustworthy)."""
        with open(sidecar_path, "r", encoding="utf-8") as f:
            meta = json.load(f)
        bin_path = sidecar_path[: -len(".json")] + ".frame.bin"
        with open(bin_path, "rb") as f:
            raw = f.read()
        if len(raw) != meta["length"] or (
            hashlib.sha256(raw).hexdigest() != meta["sha256"]
        ):
            raise ValueError(
                f"quarantined frame {bin_path} does not match its sidecar"
            )
        return meta, raw
