"""Segment-dump file source (.ktaseg).

Implementation lands with the ingestion milestone (SURVEY.md §7 M2): a
binary on-disk record-metadata format written once and scanned at memory
bandwidth by the native C++ shim.  Until then, constructing it reports the
gap cleanly instead of a ModuleNotFoundError.
"""

from __future__ import annotations


class SegmentFileSource:  # pragma: no cover - placeholder until M2 lands
    def __init__(self, segment_dir: str, topic: str = ""):
        raise SystemExit(
            "the segment-file source is not available yet in this build — "
            "use --source synthetic"
        )
