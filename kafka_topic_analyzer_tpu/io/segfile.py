"""Segment-dump files (.ktaseg): columnar on-disk record metadata.

A cluster-free ingestion path: scan a topic once (any source — the Kafka
wire client can persist while fetching), keep only the fixed-width metadata
columns the reducers need (SURVEY.md §3.4 — never payload bytes), and re-run
analyses at memory bandwidth.  One file per partition, little-endian,
columnar so batches map straight into `RecordBatch` arrays:

    magic      8s   b"KTASEG01"
    partition  i32
    reserved   i32  (zero)
    start_off  i64  (first offset in the file)
    count      i64
    key_len    i32[count]
    value_len  i32[count]
    key_null   u8 [count]
    value_null u8 [count]
    ts_ms      i64[count]
    key_hash32 u32[count]   (fnv32 reference variant)
    key_hash64 u64[count]

Files are named ``{topic}-{partition}.ktaseg``.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.records import RecordBatch

MAGIC = b"KTASEG01"
_HEADER = struct.Struct("<8sii qq")  # magic, partition, reserved, start, count
HEADER_SIZE = _HEADER.size

#: (column name, dtype) in file order; names match RecordBatch fields except
#: ts_ms (stored at millisecond precision; RecordBatch carries seconds).
COLUMNS = (
    ("key_len", np.int32),
    ("value_len", np.int32),
    ("key_null", np.uint8),
    ("value_null", np.uint8),
    ("ts_ms", np.int64),
    ("key_hash32", np.uint32),
    ("key_hash64", np.uint64),
)


def segment_path(directory: str, topic: str, partition: int) -> str:
    return os.path.join(directory, f"{topic}-{partition}.ktaseg")


def write_segment(
    path: str,
    partition: int,
    start_offset: int,
    columns: Dict[str, np.ndarray],
) -> None:
    """Write one partition's columns to a .ktaseg file."""
    count = len(columns["key_len"])
    with open(path, "wb") as f:
        f.write(_HEADER.pack(MAGIC, partition, 0, start_offset, count))
        for name, dtype in COLUMNS:
            arr = np.ascontiguousarray(columns[name], dtype=dtype)
            if arr.shape != (count,):
                raise ValueError(f"{name}: bad shape {arr.shape}")
            f.write(arr.tobytes())


def write_segment_from_batches(
    directory: str, topic: str, partition: int, batches: "list[RecordBatch]",
    start_offset: int = 0,
) -> str:
    """Convenience writer from RecordBatches of a single partition."""
    full = RecordBatch.concat(batches)
    if not np.all(full.partition == partition):
        raise ValueError("batches contain records of other partitions")
    path = segment_path(directory, topic, partition)
    write_segment(
        path,
        partition,
        start_offset,
        {
            "key_len": full.key_len,
            "value_len": full.value_len,
            "key_null": full.key_null.astype(np.uint8),
            "value_null": full.value_null.astype(np.uint8),
            "ts_ms": full.ts_s * 1000,
            "key_hash32": full.key_hash32,
            "key_hash64": full.key_hash64,
        },
    )
    return path


class SegmentFile:
    """Memory-mapped reader of one .ktaseg file."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            header = f.read(HEADER_SIZE)
        if len(header) != HEADER_SIZE:
            raise ValueError(f"{path}: truncated header")
        magic, partition, _, start_offset, count = _HEADER.unpack(header)
        if magic != MAGIC:
            raise ValueError(f"{path}: bad magic {magic!r}")
        self.partition = partition
        self.start_offset = start_offset
        self.count = count
        self._col_offsets: Dict[str, Tuple[int, np.dtype]] = {}
        off = HEADER_SIZE
        for name, dtype in COLUMNS:
            self._col_offsets[name] = (off, np.dtype(dtype))
            off += count * np.dtype(dtype).itemsize
        expected = off
        actual = os.path.getsize(path)
        if actual != expected:
            raise ValueError(f"{path}: size {actual} != expected {expected}")
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")

    def column(self, name: str, lo: int = 0, hi: Optional[int] = None) -> np.ndarray:
        off, dtype = self._col_offsets[name]
        hi = self.count if hi is None else hi
        start = off + lo * dtype.itemsize
        stop = off + hi * dtype.itemsize
        return self._mm[start:stop].view(dtype)

    def read_batch(self, lo: int, hi: int) -> RecordBatch:
        n = hi - lo
        return RecordBatch(
            partition=np.full(n, self.partition, dtype=np.int32),
            key_len=self.column("key_len", lo, hi).copy(),
            value_len=self.column("value_len", lo, hi).copy(),
            key_null=self.column("key_null", lo, hi).astype(np.bool_),
            value_null=self.column("value_null", lo, hi).astype(np.bool_),
            ts_s=self.column("ts_ms", lo, hi) // 1000,
            key_hash32=self.column("key_hash32", lo, hi).copy(),
            key_hash64=self.column("key_hash64", lo, hi).copy(),
            valid=np.ones(n, dtype=np.bool_),
        )


class SegmentFileSource(RecordSource):
    """RecordSource over a directory of {topic}-{partition}.ktaseg files."""

    def __init__(self, segment_dir: str, topic: str):
        self.segment_dir = segment_dir
        self.topic = topic
        # Exact match on "{topic}-{int}.ktaseg": a prefix match would also
        # swallow segments of topics like "{topic}-extra".
        import re

        pattern = re.compile(rf"^{re.escape(topic)}-(\d+)\.ktaseg$")
        self.segments: Dict[int, SegmentFile] = {}
        for fname in sorted(os.listdir(segment_dir)):
            m = pattern.match(fname)
            if not m:
                continue
            seg = SegmentFile(os.path.join(segment_dir, fname))
            if seg.partition != int(m.group(1)):
                raise ValueError(
                    f"{fname}: header partition {seg.partition} does not "
                    f"match filename"
                )
            self.segments[seg.partition] = seg
        if not self.segments:
            raise SystemExit(
                f"no {topic}-*.ktaseg files in {segment_dir!r}"
            )

    def partitions(self) -> List[int]:
        return sorted(self.segments)

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        start = {p: s.start_offset for p, s in self.segments.items()}
        end = {p: s.start_offset + s.count for p, s in self.segments.items()}
        return start, end

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
    ) -> Iterator[RecordBatch]:
        parts = sorted(partitions) if partitions is not None else self.partitions()
        # Sequential per-partition chunks: fastest IO pattern, and the order
        # contract only requires per-partition offset order.
        for p in parts:
            seg = self.segments[p]
            first = 0
            if start_at and p in start_at:
                first = min(max(start_at[p] - seg.start_offset, 0), seg.count)
            for lo in range(first, seg.count, batch_size):
                yield seg.read_batch(lo, min(lo + batch_size, seg.count))
