"""Segment-dump files (.ktaseg): columnar on-disk record metadata.

A cluster-free ingestion path: scan a topic once (any source — the Kafka
wire client can persist while fetching), keep only the fixed-width metadata
columns the reducers need (SURVEY.md §3.4 — never payload bytes), and re-run
analyses at memory bandwidth.  One file per partition, little-endian,
columnar so batches map straight into `RecordBatch` arrays:

    magic      8s   b"KTASEG01"
    partition  i32
    flags      i32  (bit0: per-record offsets column present)
    start_off  i64  (first offset in the file)
    count      i64
    key_len    i32[count]
    value_len  i32[count]
    key_null   u8 [count]
    value_null u8 [count]
    ts_ms      i64[count]
    key_hash32 u32[count]   (fnv32 reference variant)
    key_hash64 u64[count]
    [offsets   i64[count]]  iff flags bit0 — set when the source's offset
                            space has gaps (log compaction), so watermarks
                            and snapshot resume stay offset-exact

Files are named ``{topic}-{partition}.ktaseg`` or, for rolled dumps of one
partition, ``{topic}-{partition}.c{chunk}.ktaseg`` — the reader orders a
partition's chunks by start offset.
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.io.kafka_codec import CorruptFrameError
from kafka_topic_analyzer_tpu.io.objstore import SegmentFetchUnavailable
from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.records import RecordBatch


class CorruptSegmentError(CorruptFrameError, ValueError):
    """A .ktaseg file whose *bytes* are wrong — the cold-path analog of the
    wire scan's corrupt-frame taxonomy (io/kafka_codec.py), so operators
    triaging a broken dump see the same classified kinds, with the file
    path in place of the fetch span.  ``ValueError`` stays in the MRO for
    callers that pre-date the classification.

    ``path`` names the damaged file; the inherited ``partition``/``span``
    context fields carry the header's claim and the damaged byte range.
    """

    kind = "corrupt-segment"

    def __init__(self, message: str, *, path: "Optional[str]" = None, **kw):
        super().__init__(message, **kw)
        self.path = path


class TruncatedSegmentError(CorruptSegmentError):
    """The file ends before its header-declared column payload (or before
    the header itself) — an interrupted dump or a partial copy."""

    kind = "truncated"


class MalformedSegmentError(CorruptSegmentError):
    """Structurally impossible header or layout: bad magic, negative
    count/partition, header↔filename disagreement, overlapping chunks."""

    kind = "malformed-header"


MAGIC = b"KTASEG01"
_HEADER = struct.Struct("<8sii qq")  # magic, partition, flags, start, count
HEADER_SIZE = _HEADER.size
FLAG_OFFSETS = 1

#: (column name, dtype) in file order; names match RecordBatch fields except
#: ts_ms (stored at millisecond precision; RecordBatch carries seconds).
COLUMNS = (
    ("key_len", np.int32),
    ("value_len", np.int32),
    ("key_null", np.uint8),
    ("value_null", np.uint8),
    ("ts_ms", np.int64),
    ("key_hash32", np.uint32),
    ("key_hash64", np.uint64),
)


def segment_path(directory: str, topic: str, partition: int) -> str:
    return os.path.join(directory, f"{topic}-{partition}.ktaseg")


def parse_segment_header(
    header: bytes, path: str
) -> "Tuple[int, int, int, int]":
    """Validate + decode one .ktaseg header → (partition, flags,
    start_offset, count).  ONE implementation for every byte source —
    local files, remotely fetched chunk bodies, and the remote catalog's
    ranged header probes — so classification can never diverge by tier."""
    if len(header) != HEADER_SIZE:
        raise TruncatedSegmentError(
            f"{path}: truncated header ({len(header)} of "
            f"{HEADER_SIZE} bytes)",
            path=path,
            span=(0, len(header)),
        )
    magic, partition, flags, start_offset, count = _HEADER.unpack(header)
    if magic != MAGIC:
        raise MalformedSegmentError(
            f"{path}: bad magic {magic!r}", path=path, span=(0, 8)
        )
    if count < 0 or partition < 0:
        raise MalformedSegmentError(
            f"{path}: impossible header (partition {partition}, "
            f"count {count})",
            path=path,
            partition=partition,
            span=(0, HEADER_SIZE),
            num_records=max(count, 0),
        )
    return partition, flags, start_offset, count


def segment_column_layout(
    count: int, flags: int
) -> "Tuple[Dict[str, Tuple[int, np.dtype]], int]":
    """(column name -> (byte offset, dtype), expected total size) for a
    chunk with the given header — the layout every reader shares."""
    col_offsets: Dict[str, Tuple[int, np.dtype]] = {}
    off = HEADER_SIZE
    cols = list(COLUMNS) + (
        [("offsets", np.int64)] if flags & FLAG_OFFSETS else []
    )
    for name, dtype in cols:
        col_offsets[name] = (off, np.dtype(dtype))
        off += count * np.dtype(dtype).itemsize
    return col_offsets, off


def check_segment_size(
    actual: int, expected: int, path: str, partition: int, count: int
) -> None:
    """Classify a chunk whose byte length disagrees with its header's
    column layout: short = truncated (interrupted dump, partial copy or
    fetch), long = malformed (trailing garbage)."""
    if actual != expected:
        kind = (
            TruncatedSegmentError if actual < expected
            else MalformedSegmentError
        )
        raise kind(
            f"{path}: size {actual} != expected {expected} for "
            f"{count} records",
            path=path,
            partition=partition,
            span=(0, actual),
            num_records=count,
        )


def write_segment(
    path: str,
    partition: int,
    start_offset: int,
    columns: Dict[str, np.ndarray],
    offsets: "np.ndarray | None" = None,
) -> None:
    """Write one partition's columns to a .ktaseg file."""
    count = len(columns["key_len"])
    flags = FLAG_OFFSETS if offsets is not None else 0
    with open(path, "wb") as f:
        f.write(_HEADER.pack(MAGIC, partition, flags, start_offset, count))
        for name, dtype in COLUMNS:
            arr = np.ascontiguousarray(columns[name], dtype=dtype)
            if arr.shape != (count,):
                raise ValueError(f"{name}: bad shape {arr.shape}")
            f.write(arr.tobytes())
        if offsets is not None:
            arr = np.ascontiguousarray(offsets, dtype=np.int64)
            if arr.shape != (count,):
                raise ValueError("offsets: bad shape")
            f.write(arr.tobytes())


def write_segment_from_batches(
    directory: str, topic: str, partition: int, batches: "list[RecordBatch]",
    start_offset: int = 0,
) -> str:
    """Convenience writer from RecordBatches of a single partition."""
    full = RecordBatch.concat(batches)
    if not np.all(full.partition == partition):
        raise ValueError("batches contain records of other partitions")
    path = segment_path(directory, topic, partition)
    write_segment(
        path,
        partition,
        start_offset,
        {
            "key_len": full.key_len,
            "value_len": full.value_len,
            "key_null": full.key_null.astype(np.uint8),
            "value_null": full.value_null.astype(np.uint8),
            "ts_ms": full.ts_s * 1000,
            "key_hash32": full.key_hash32,
            "key_hash64": full.key_hash64,
        },
    )
    return path


class SegmentFile:
    """Memory-mapped reader of one .ktaseg file."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            header = f.read(HEADER_SIZE)
        partition, flags, start_offset, count = parse_segment_header(
            header, path
        )
        self.partition = partition
        self.start_offset = start_offset
        self.count = count
        self.has_offsets = bool(flags & FLAG_OFFSETS)
        self._col_offsets, expected = segment_column_layout(count, flags)
        check_segment_size(
            os.path.getsize(path), expected, path, partition, count
        )
        self._mm = np.memmap(path, dtype=np.uint8, mode="r")
        #: Lazily-built constants for the zero-copy read path: every batch
        #: of this file shares one partition/valid array via prefix views.
        #: Sized to the LARGEST SPAN READ (bounded by the scan's batch
        #: size), not the file's record count — a year-scale chunk must
        #: not pin O(file) host RAM for two constant columns.  Marked
        #: read-only so an accidental in-place mutator downstream fails
        #: loudly instead of corrupting sibling batches (the memmap
        #: columns are mode="r" and give the same guarantee).
        self._const_partition: "Optional[np.ndarray]" = None
        self._const_valid: "Optional[np.ndarray]" = None

    @property
    def end_offset(self) -> int:
        """One past the last record's offset (offset-exact for gappy dumps)."""
        if self.has_offsets and self.count:
            return int(self.column("offsets", self.count - 1, self.count)[0]) + 1
        return self.start_offset + self.count

    def column(self, name: str, lo: int = 0, hi: Optional[int] = None) -> np.ndarray:
        off, dtype = self._col_offsets[name]
        hi = self.count if hi is None else hi
        start = off + lo * dtype.itemsize
        stop = off + hi * dtype.itemsize
        return self._mm[start:stop].view(dtype)

    def read_batch(self, lo: int, hi: int, copy: bool = False) -> RecordBatch:
        """Rows [lo, hi) as a RecordBatch — ZERO-COPY by default.

        The int/hash columns and both null flags are direct views of the
        memmap (bool and uint8 share a byte layout, so the flags reinterpret
        in place); partition and valid slice per-file read-only constants.
        The only per-batch allocation is the ms→s timestamp division — the
        one column whose stored unit differs from the batch contract.  The
        cold path packs straight from these views (wire v4 sections copy
        from the mapped pages exactly once, pack_batch ``out=``), so a
        segment scan's per-record byte traffic is file page → packed row.

        ``copy=True`` detaches every column (the pre-catalog behavior) for
        callers that must outlive or mutate the mapping.
        """
        n = hi - lo
        if self._const_partition is None or len(self._const_partition) < n:
            part = np.full(n, self.partition, dtype=np.int32)
            part.flags.writeable = False
            ones = np.ones(n, dtype=np.bool_)
            ones.flags.writeable = False
            self._const_partition, self._const_valid = part, ones
        batch = RecordBatch(
            partition=self._const_partition[:n],
            key_len=self.column("key_len", lo, hi),
            value_len=self.column("value_len", lo, hi),
            key_null=self.column("key_null", lo, hi).view(np.bool_),
            value_null=self.column("value_null", lo, hi).view(np.bool_),
            ts_s=self.column("ts_ms", lo, hi) // 1000,
            key_hash32=self.column("key_hash32", lo, hi),
            key_hash64=self.column("key_hash64", lo, hi),
            valid=self._const_valid[:n],
        )
        if self.has_offsets:
            batch.offsets = self.column("offsets", lo, hi)
        return batch.copy() if copy else batch


class RemoteSegmentFile(SegmentFile):
    """One object-store chunk, open for reading (DESIGN.md §21).

    The catalog opens it from a ranged HEADER probe alone — validation
    (header decode, size-vs-layout check against the LIST size, overlap
    ordering) never downloads a chunk body.  The body arrives lazily, the
    first time a column is touched (``ensure_body``): cache → verified
    fetch → ``np.frombuffer``, after which every inherited read path —
    ``column`` views, ``read_batch`` zero-copy semantics, the fused
    ``append_columns`` feed — works byte-for-byte like the memory-mapped
    local file, because ``_mm`` is the same uint8 array shape over the
    same bytes (a verified cache hit arrives as the cache file's memmap —
    zero-copy straight through).  ``release()`` drops the body reference
    once the stream has consumed the chunk (outstanding batch views keep
    the buffer alive through numpy's base refcount) and best-effort
    cancels a scheduler request for it that never started — degraded-skip
    paths must not pay for bytes nobody will read — bounding a stream's
    resident memory to readahead + 1 chunks.

    Acquisition failures are CACHED on the file: a scheduler worker that
    hit a deterministic failure (classified corruption, exhausted retry
    budget) must hand the consumer exactly that failure, not trigger a
    second fetch cycle.
    """

    def __init__(
        self,
        fetch_body: "Callable[[Callable[[bytes], None]], bytes]",
        name: str,
        location: str,
        size: int,
        header: bytes,
        end_offset: "Optional[int]" = None,
    ):
        # Deliberately no super().__init__: there is no local path to map.
        self.path = f"{location.rstrip('/')}/{name}"
        self.name = name
        partition, flags, start_offset, count = parse_segment_header(
            header, self.path
        )
        self.partition = partition
        self.start_offset = start_offset
        self.count = count
        self.has_offsets = bool(flags & FLAG_OFFSETS)
        self._header = header
        self._col_offsets, expected = segment_column_layout(count, flags)
        check_segment_size(size, expected, self.path, partition, count)
        self._expected_size = expected
        self._fetch_body = fetch_body
        self._end = end_offset
        self._lock = threading.Lock()
        self._data: "Optional[np.ndarray]" = None
        self._failure: "Optional[BaseException]" = None
        #: The fetch scheduler ticket covering this chunk's body, while
        #: one is queued or in flight (set by the read-ahead window so
        #: release() can cancel a fetch that never started).
        self._pending = None
        self._const_partition = None
        self._const_valid = None

    @property
    def end_offset(self) -> int:
        """Offset-exact for gappy chunks WITHOUT a body fetch: the store
        probed the trailing offsets entry (suffix range) at open time."""
        if self._end is not None:
            return self._end
        return self.start_offset + self.count

    @property
    def _mm(self) -> np.ndarray:
        return self.ensure_body()

    def ensure_body(self) -> np.ndarray:
        """The chunk's bytes, fetching (cache → store, verified) on first
        touch.  Thread-safe: a scheduler worker and the consuming stream
        serialize on the per-chunk lock, so the consumer blocks on an
        in-flight prefetch instead of fetching twice."""
        with self._lock:
            if self._failure is not None:
                raise self._failure
            if self._data is None:
                try:
                    raw = self._fetch_body(self._validate_body)
                except (CorruptSegmentError, SegmentFetchUnavailable) as e:
                    self._failure = e  # deterministic: replay, don't refetch
                    raise
                # A verified cache hit is already a uint8 memmap view —
                # keep it zero-copy; a transport body is bytes.
                self._data = (
                    raw if isinstance(raw, np.ndarray)
                    else np.frombuffer(raw, dtype=np.uint8)
                )
            return self._data

    def _validate_body(self, raw: bytes) -> None:
        """Classify FETCHED bytes with the exact local-reader taxonomy:
        short body = truncated, header bytes that no longer decode (or
        disagree with what the catalog validated) = malformed.  The store
        disambiguates in-flight vs at-rest damage around this (one
        re-fetch — io/kafka_wire.py's rule)."""
        if len(raw) < HEADER_SIZE:
            raise TruncatedSegmentError(
                f"{self.path}: fetched body holds {len(raw)} of "
                f"{HEADER_SIZE} header bytes",
                path=self.path,
                partition=self.partition,
                span=(0, len(raw)),
            )
        header = bytes(raw[:HEADER_SIZE])
        parse_segment_header(header, self.path)
        if header != self._header:
            raise MalformedSegmentError(
                f"{self.path}: fetched header disagrees with the "
                "catalog-validated header — object changed or damaged "
                "since the catalog opened it",
                path=self.path,
                partition=self.partition,
                span=(0, HEADER_SIZE),
                num_records=self.count,
            )
        check_segment_size(
            len(raw), self._expected_size, self.path, self.partition,
            self.count,
        )

    def release(self) -> None:
        """Drop the body reference (batch views already handed out keep
        the underlying buffer alive; new touches re-fetch via the cache),
        and cancel a scheduler request for this chunk that has not
        started yet (booked on ``kta_fetch_sched_cancelled_total``) —
        the degraded-skip and teardown paths must not pay for bytes
        nobody will read.

        BEST-EFFORT: ``ensure_body`` holds the per-chunk lock for the
        whole fetch (socket timeout + backoff sleeps), and release is
        called from teardown paths — the degraded-partition skip and the
        end-of-stream sweep — that must never stall tens of seconds
        behind a scheduler worker stuck in a hung request.  If the lock
        is busy, the in-flight fetch owns the body's lifetime; memory
        stays bounded by the read-ahead window either way."""
        ticket, self._pending = self._pending, None
        if ticket is not None:
            ticket.cancel()  # no-op once running/done; booked if it lands
        if self._lock.acquire(blocking=False):
            try:
                self._data = None
            finally:
                self._lock.release()


class _ScheduledReadahead:
    """One stream's read-ahead WINDOW over the process-wide fetch
    scheduler (``--segment-readahead N`` · io/fetchsched.py).

    The stream no longer owns a thread pool: it registers a `FetchStream`
    with the shared scheduler and keeps chunks [i, i+N] of its plan
    *submitted* — the head of the window (the chunk the decoder will need
    next) at DEMAND class, the rest speculative.  The scheduler's
    admission policy does the rest: demand beats speculation
    process-wide, streams round-robin within a class, and the worker
    count is ``--fetch-concurrency`` no matter how many streams run.
    Workers never surface errors: a failed prefetch parks the failure on
    its chunk (``RemoteSegmentFile.ensure_body`` — cache-aware,
    failure-caching), and the consumer re-raises it at the chunk's
    position in the stream — ordering, degradation, and corruption
    semantics are exactly the synchronous path's.

    In-flight chunk memory stays bounded at (N + 1) chunks per stream:
    only submitted-window bodies can materialize, and the consumer
    releases each chunk as it passes.
    """

    def __init__(self, depth: int):
        from kafka_topic_analyzer_tpu.io.fetchsched import get_scheduler

        self.depth = depth
        self._stream = get_scheduler().stream()
        self._tickets: "Dict[int, object]" = {}
        self._submitted: "set[int]" = set()
        self._consumed: "set[int]" = set()
        self._weighted = False

    @staticmethod
    def _prefetch(seg: "RemoteSegmentFile") -> None:
        try:
            seg.ensure_body()
        except Exception:
            pass  # parked on the segment; the consumer re-raises in order

    def schedule(self, plan, i: int, degraded: "Dict[int, str]") -> None:
        """Keep chunks [i, i+N] of the plan submitted (skipping local
        chunks and partitions already degraded this scan).  Chunk i — the
        one the consumer is about to block on — submits at DEMAND class;
        the look-ahead tail is speculative."""
        if not self._weighted:
            # Weighted admission (DESIGN §25): this stream's fair share
            # of the wire is proportional to how much it still has to
            # fetch — the plan's chunk count (≈ partitions × segments).
            # Registered once, at the first schedule, when the plan is
            # first known.
            self._weighted = True
            self._stream.set_weight(max(1.0, float(len(plan))))
        for j in range(i, min(i + self.depth + 1, len(plan))):
            if j in self._submitted:
                continue
            self._submitted.add(j)
            p, seg, _first = plan[j]
            if p in degraded or not isinstance(seg, RemoteSegmentFile):
                self._consumed.add(j)
                continue
            obs_metrics.SEGSTORE_READAHEAD.inc(1)
            ticket = self._stream.submit(
                lambda s=seg: self._prefetch(s),
                seq=j,
                speculative=(j != i),
            )
            self._tickets[j] = ticket
            seg._pending = ticket  # so release() can cancel a queued fetch

    def claim(self, i: int) -> None:
        """The consumer is blocked on chunk i NOW: promote its request to
        DEMAND if it is still queued behind speculative work (booked as a
        deadline reorder) and wait for the worker to finish it.  The
        subsequent ``ensure_body`` then finds the body — or the parked
        failure — without fetching twice (per-chunk lock)."""
        ticket = self._tickets.get(i)
        if ticket is not None:
            self._stream.demand(ticket)

    def done(self, i: int) -> None:
        """The consumer reached chunk i: it no longer counts as ahead."""
        if i in self._submitted and i not in self._consumed:
            self._consumed.add(i)
            obs_metrics.SEGSTORE_READAHEAD.inc(-1)
        self._tickets.pop(i, None)

    def close(self) -> None:
        for j in self._submitted - self._consumed:
            self._consumed.add(j)
            obs_metrics.SEGSTORE_READAHEAD.inc(-1)
        self._tickets.clear()
        # Unregisters the stream from the scheduler: queued requests are
        # cancelled (booked), in-flight ones finish on their workers.
        self._stream.close()


class SegmentDumpWriter:
    """Incrementally dump a scan's record metadata into rolled .ktaseg
    chunks (``{topic}-{p}.c{N}.ktaseg``), one writer shared by a whole scan.

    Buffers per partition and rolls a chunk to disk every
    ``records_per_chunk`` records, so memory stays bounded regardless of
    topic size.  Thread-safe across per-shard prefetch threads because each
    partition is fed by exactly one shard (records.py contract) — state is
    per partition.
    """

    def __init__(self, directory: str, topic: str, records_per_chunk: int = 1 << 18):
        os.makedirs(directory, exist_ok=True)
        # Refuse a directory that already holds this topic's segments: a
        # shorter re-dump would leave stale chunks behind, and the reader
        # would silently merge old and new records.  Same name pattern as
        # the reader's enumeration (segstore), so the staleness check can
        # never desync from what a later scan would pick up.
        from kafka_topic_analyzer_tpu.io.segstore import topic_chunk_pattern

        pattern = topic_chunk_pattern(topic)
        stale = [f for f in os.listdir(directory) if pattern.match(f)]
        if stale:
            raise ValueError(
                f"{directory!r} already contains {len(stale)} segment file(s) "
                f"for topic {topic!r} (e.g. {stale[0]}) — remove them or "
                "choose another directory"
            )
        self.directory = directory
        self.topic = topic
        self.records_per_chunk = records_per_chunk
        self._buf: Dict[int, List[RecordBatch]] = {}
        self._buffered: Dict[int, int] = {}
        self._chunk_idx: Dict[int, int] = {}
        self._written: Dict[int, int] = {}
        #: True start offsets of the source (set via set_base_offsets):
        #: offset-less (gapless) sources may still start above 0 after
        #: retention, and chunk headers must not silently rebase to 0.
        self._base: Dict[int, int] = {}

    def set_base_offsets(self, start_offsets: Dict[int, int]) -> None:
        self._base.update(start_offsets)

    def append(self, batch: RecordBatch) -> None:
        valid = batch.valid
        if not valid.all():
            batch = batch.take(np.nonzero(valid)[0])
        for p in np.unique(batch.partition):
            sub = batch.take(np.nonzero(batch.partition == p)[0])
            p = int(p)
            self._buf.setdefault(p, []).append(sub)
            self._buffered[p] = self._buffered.get(p, 0) + len(sub)
            if self._buffered[p] >= self.records_per_chunk:
                self._flush(p)

    def _flush(self, p: int) -> None:
        batches = self._buf.pop(p, [])
        self._buffered[p] = 0
        if not batches:
            return
        full = RecordBatch.concat(batches)
        idx = self._chunk_idx.get(p, 0)
        self._chunk_idx[p] = idx + 1
        path = os.path.join(self.directory, f"{self.topic}-{p}.c{idx}.ktaseg")
        # Offset-carrying sources: the first record's true offset; gapless
        # sources: the source's start offset plus records already written.
        start = (
            int(full.offsets[0])
            if full.offsets is not None
            else self._base.get(p, 0) + self._written.get(p, 0)
        )
        self._written[p] = self._written.get(p, 0) + len(full)
        write_segment(
            path,
            p,
            start,
            {
                "key_len": full.key_len,
                "value_len": full.value_len,
                "key_null": full.key_null.astype(np.uint8),
                "value_null": full.value_null.astype(np.uint8),
                "ts_ms": full.ts_s * 1000,
                "key_hash32": full.key_hash32,
                "key_hash64": full.key_hash64,
            },
            offsets=full.offsets,
        )

    def close(self) -> None:
        for p in list(self._buf):
            self._flush(p)


class TeeSource(RecordSource):
    """Wraps a source and dumps every yielded batch through a
    `SegmentDumpWriter` — scan once from Kafka, re-analyze forever from
    segments (``--dump-segments``)."""

    def __init__(self, inner: RecordSource, writer: SegmentDumpWriter):
        self.inner = inner
        self.writer = writer

    def partitions(self):
        return self.inner.partitions()

    def watermarks(self):
        return self.inner.watermarks()

    def is_empty(self):
        return self.inner.is_empty()

    def offsets_for_timestamp(self, ts_ms: int):
        return self.inner.offsets_for_timestamp(ts_ms)

    def degraded_partitions(self):
        return self.inner.degraded_partitions()

    def corruption_stats(self):
        return self.inner.corruption_stats()

    def corruption_spans(self):
        return self.inner.corruption_spans()

    def seed_corrupt_spans(self, spans):
        # The engine discovers this by hasattr; forward only when the inner
        # source actually implements it (the RecordSource base does not).
        seed = getattr(self.inner, "seed_corrupt_spans", None)
        if seed is not None:
            seed(spans)

    def batches(self, batch_size, partitions=None, start_at=None):
        self.writer.set_base_offsets(self.inner.watermarks()[0])
        for batch in self.inner.batches(batch_size, partitions, start_at):
            self.writer.append(batch)
            yield batch

    def close(self):
        self.writer.close()
        if hasattr(self.inner, "close"):
            self.inner.close()


class SegmentFileSource(RecordSource):
    """RecordSource over a catalog of {topic}-{partition}[.cN].ktaseg
    chunks in a SegmentStore (a local directory today — io/segstore.py is
    the object-store seam); a partition's chunks are ordered by start
    offset.

    This is the first-class cold path: with ``--ingest-workers N`` the
    engine shards the catalog's partitions over N decode→pack workers
    (record-count-balanced via `partition_record_counts`), each draining
    its own ``batches()`` stream — safe because distinct partitions touch
    distinct SegmentFiles, so workers never share mutable reader state,
    and exact for the same reason the wire fan-in is (DESIGN.md §11: each
    partition's records travel one worker's stream in offset order).
    """

    def __init__(self, store, topic: str, fetch=None):
        from kafka_topic_analyzer_tpu.config import SegmentFetchConfig
        from kafka_topic_analyzer_tpu.io.segstore import (
            SegmentCatalog,
            open_segment_store,
        )

        fetch = fetch if fetch is not None else SegmentFetchConfig()
        if isinstance(store, str):
            store = open_segment_store(store, fetch=fetch)
        self.store = store
        self.topic = topic
        remote = bool(getattr(store, "is_remote", False))
        if remote:
            # Size the ONE process-wide fetch scheduler before the catalog
            # fans out its header probes through it.  An explicit
            # --fetch-concurrency pins the pool; auto lets the engine's
            # resolved stream count grow it (fetchsched.note_streams).
            from kafka_topic_analyzer_tpu.io import fetchsched

            concurrency = fetch.resolve_concurrency()
            if concurrency is not None:
                fetchsched.configure(concurrency, explicit=True)
        self.catalog = SegmentCatalog(store, topic)
        self.segments: Dict[int, List[SegmentFile]] = self.catalog.segments
        #: Per-stream read-ahead WINDOW (0 = demand-only, no speculation;
        #: resolves to 0 for local stores, where there is nothing to
        #: hide).  The process-wide fetch scheduler supplies the workers.
        self.readahead = fetch.resolve_readahead(remote)
        #: partition -> reason, for partitions dropped mid-scan after their
        #: chunk fetches exhausted the transport retry budget (the PR-1
        #: degraded surface, shared across parallel-ingest worker streams).
        self._degraded: Dict[int, str] = {}
        self._degraded_lock = threading.Lock()
        if not self.segments:
            raise SystemExit(
                f"no {topic}-*.ktaseg files in {store.describe()!r}"
            )

    def partitions(self) -> List[int]:
        return sorted(self.segments)

    def close(self) -> None:
        """Release every remote chunk body this catalog still holds (and
        cancel their queued scheduler requests) — fleet teardown and
        per-topic failure paths must stop a finished source from pinning
        memory or competing for the shared fetch pool.  Local memmaps
        need nothing: pages un-fault on their own."""
        for chunks in self.segments.values():
            for seg in chunks:
                if isinstance(seg, RemoteSegmentFile):
                    seg.release()

    def degraded_partitions(self) -> Dict[int, str]:
        return dict(self._degraded)

    def _note_degraded(self, partition: int, reason: str) -> None:
        """Drop ``partition`` from the rest of the scan (its remaining
        chunks are skipped) and record why — the engine reports it and
        exits EXIT_DEGRADED, exactly like a wire partition past its
        budget.  Lock-guarded: worker streams share this map."""
        with self._degraded_lock:
            self._degraded.setdefault(partition, reason)

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        return self.catalog.watermarks()

    def partition_record_counts(self) -> Dict[int, int]:
        """Exact retained records per partition (catalog metadata) — the
        engine balances parallel-ingest workers by these instead of by
        partition count, since cold catalogs know their sizes up front."""
        return self.catalog.record_counts()

    #: Cold chunks can feed the fused decode→pack sink: the memmap column
    #: views go straight into wire-v4 rows (sink.append_columns — the
    #: ms→s divide happens inside the native appender), skipping both the
    #: RecordBatch view layer and the separate pack pass.
    supports_fused_sink = True

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
        sink=None,
    ) -> Iterator[RecordBatch]:
        parts = sorted(partitions) if partitions is not None else self.partitions()
        # Sequential per-partition chunks: fastest IO pattern, and the order
        # contract only requires per-partition offset order.  The plan is
        # materialized up front so the read-ahead pool can see (and start
        # fetching) the chunks BEHIND the one the stream is consuming.
        # (Resume into a gappy remote chunk touches its offsets column —
        # one synchronous body fetch, cache-served on a re-resume.)
        plan: "List[Tuple[int, SegmentFile, int]]" = []
        for p in parts:
            resume = start_at.get(p) if start_at else None
            for seg in self.segments[p]:
                first = 0
                if resume is not None:
                    if resume >= seg.end_offset:
                        continue  # chunk fully below the resume point
                    if resume > seg.start_offset:
                        # Only the ONE chunk straddling the resume point
                        # needs its offsets column (a synchronous body
                        # fetch on remote stores — admitted through the
                        # shared scheduler as a demand request, like
                        # every other remote byte); chunks entirely above
                        # the resume point start at record 0 — probing
                        # them too would download every remaining chunk
                        # at plan time and pin them all in memory.
                        if seg.has_offsets:
                            try:
                                if isinstance(seg, RemoteSegmentFile):
                                    from kafka_topic_analyzer_tpu.io import (
                                        fetchsched,
                                    )

                                    fetchsched.get_scheduler().run(
                                        seg.ensure_body
                                    )
                                offs = np.asarray(seg.column("offsets"))
                            except SegmentFetchUnavailable as e:
                                # Plan-time fetches degrade like consumer
                                # ones: drop the partition, keep scanning.
                                self._note_degraded(p, str(e))
                                break
                            first = int(np.searchsorted(offs, resume))
                        else:
                            first = min(
                                max(resume - seg.start_offset, 0), seg.count
                            )
                plan.append((p, seg, first))
        pool = None
        if any(isinstance(seg, RemoteSegmentFile) for _, seg, _ in plan):
            # EVERY remote plan routes through the shared scheduler —
            # readahead 0 just shrinks the window to demand-only
            # (chunk i submits at DEMAND class, nothing speculates).
            pool = _ScheduledReadahead(self.readahead)
        try:
            for i, (p, seg, first) in enumerate(plan):
                if p in self._degraded:
                    if pool is not None:
                        pool.done(i)
                    if isinstance(seg, RemoteSegmentFile):
                        # A chunk the pool prefetched before its partition
                        # degraded must not stay pinned in memory for the
                        # rest of the stream.
                        seg.release()
                    continue  # budget exhausted earlier in this stream
                if pool is not None:
                    pool.schedule(plan, i, self._degraded)
                try:
                    if isinstance(seg, RemoteSegmentFile):
                        # Materialize the body HERE, before any records are
                        # booked or appended: a chunk either enters the
                        # scan whole or degrades its partition cleanly.
                        # claim() first — if the chunk's request is still
                        # queued behind speculative work, promote it to
                        # demand class (the deadline rule) and ride the
                        # worker's fetch instead of starting a second one.
                        if pool is not None:
                            pool.claim(i)
                        seg.ensure_body()
                except SegmentFetchUnavailable as e:
                    # The transport budget for this partition ran out:
                    # drop it from the scan and keep going — the engine
                    # reports the degraded set (graceful degradation,
                    # io/retry.py), exactly like a dead wire partition.
                    self._note_degraded(p, str(e))
                    if pool is not None:
                        pool.done(i)
                    seg.release()
                    continue
                if pool is not None:
                    pool.done(i)
                if sink is not None:
                    # Fused cold path: the whole chunk's column views in
                    # one native append (chunk bytes → packed row; the sink
                    # cuts batch_size rows itself).  ts_mode=1 is the
                    # reader's ``ts_ms // 1000`` rule.  Batches book at
                    # the batch_size-cut count the chained loop below
                    # would have reported, so kta_segment_batches_total
                    # stays comparable whichever path engaged.
                    n = seg.count - first
                    if n <= 0:
                        continue
                    obs_metrics.SEGMENT_RECORDS.inc(n)
                    obs_metrics.SEGMENT_BATCHES.inc(
                        -(-n // batch_size)
                    )
                    sink.append_columns(
                        seg.partition,
                        seg.column("key_len", first),
                        seg.column("value_len", first),
                        seg.column("key_null", first),
                        seg.column("value_null", first),
                        seg.column("ts_ms", first),
                        seg.column("key_hash32", first),
                        seg.column("key_hash64", first),
                        n,
                        ts_mode=1,
                        offsets=(
                            seg.column("offsets", first)
                            if seg.has_offsets else None
                        ),
                    )
                    yield from sink.take_completed()
                else:
                    for lo in range(first, seg.count, batch_size):
                        hi = min(lo + batch_size, seg.count)
                        obs_metrics.SEGMENT_RECORDS.inc(hi - lo)
                        obs_metrics.SEGMENT_BATCHES.inc()
                        yield seg.read_batch(lo, hi)
                if isinstance(seg, RemoteSegmentFile):
                    # Consumed: drop the stream's body reference (views
                    # already yielded keep the buffer alive; memory stays
                    # bounded at readahead + 1 chunks per stream).
                    seg.release()
        finally:
            if pool is not None:
                pool.close()
                # Sweep bodies the pool prefetched but the consumer never
                # reached (early generator close, errors): best-effort —
                # a fetch still racing in a pool thread may repopulate
                # its one chunk after this, bounded by the pool depth.
                for _, seg, _ in plan:
                    if isinstance(seg, RemoteSegmentFile):
                        seg.release()
        if sink is not None:
            sink.flush()
            yield from sink.take_completed()
