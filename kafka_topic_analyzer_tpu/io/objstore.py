"""Object-store transport for the remote segment tier (DESIGN.md §21).

The cold scan path's storage seam (io/segstore.py) needs exactly three
operations against a remote store: LIST a topic's chunk objects, fetch a
byte RANGE of one (the catalog's header probe), and fetch a whole chunk
body.  This module is the S3-shaped HTTP client behind
``ObjectSegmentStore`` plus the local segment cache:

- `RetryingHttp` — THE retry-budget wrapper.  Every socket the remote tier
  touches lives inside this class (tools/lint.sh rule 11): one method pair
  does the raw request, one public ``get`` drives it through the PR-1
  recovery substrate — capped-exponential `io/retry.Backoff` between
  attempts (sleeps booked, never bare ``time.sleep``) and a
  `PartitionRetryBudget` so a partition whose chunks stay unreachable is
  DEGRADED (scan continues without it, reported) instead of retried
  forever.  Transient failures are resets/timeouts/truncated bodies/5xx;
  a 200-body whose MD5 disagrees with the response ETag is presumed
  *in-flight* damage and re-fetched — but a second fetch returning
  byte-identical data proves the mismatch persistent (SSE-KMS/SSE-C
  ETags are 32-hex yet not the content MD5; responses declaring such
  encryption skip the check up front) and the body is accepted, booked,
  and left to the downstream structural/sha256 validation.  4xx are
  deterministic and never retried; so is a server that ignores Range
  headers (the requested window is sliced out of its 200 response).
  LIST pagination follows NextContinuationToken until IsTruncated
  clears, so catalogs beyond one 1000-key page enumerate completely.
- `SegmentCache` — the content-verified local chunk cache
  (``--segment-cache DIR``): entries are keyed by the address digest
  (store + object name + size), written tmp-file → atomic rename, carry a
  sha256 sidecar recorded at fetch time, and are VERIFIED on first touch
  each process lifetime — a flipped byte in a cached entry is detected,
  booked (``kta_segstore_fallback_total{reason="cache-poisoned"}``),
  evicted, and re-fetched; it is never silently served.  Once an entry
  verifies, its digest LATCHES as trusted and later hits skip the
  re-hash (``kta_segstore_cache_verify_latched_total``) — the
  verify-amortization that closes BENCH round 14's warm-re-audit
  residual.  Eviction, re-population, and poison detection drop the
  latch, so any NEW on-disk bytes re-verify at their first touch.  Hits
  are served as read-only ``np.memmap`` views (zero-copy into
  ``pack_batch(out=)``/the fused native pass — POSIX keeps the mapping
  valid across a concurrent eviction's unlink).  The cache is a
  size-bounded LRU (hits refresh mtime; inserts evict oldest-first past
  ``max_bytes``).

Wire shape (path-style S3): ``GET {base}/?list-type=2&prefix=P`` returns
a ListBucketResult XML of Key/Size/ETag rows; ``GET {base}/{key}`` with an
optional ``Range: bytes=a-b`` header returns 200/206.  Any S3-compatible
endpoint serves this; ``tools/objstore_serve.py`` is the local
implementation the tests and benchmarks run against.
"""

from __future__ import annotations

import hashlib
import http.client
import io
import json
import os
import re
import threading

import numpy as np
from time import perf_counter as _perf_counter
from typing import Callable, Dict, List, Optional, Tuple
from xml.etree import ElementTree

from kafka_topic_analyzer_tpu.config import SegmentFetchConfig
from kafka_topic_analyzer_tpu.io.retry import Backoff, PartitionRetryBudget
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics


class ObjectStoreError(IOError):
    """A remote-store operation that failed deterministically (bad spec,
    missing object, exhausted retry budget).  ``IOError`` so the CLI's
    environment-failure path reports one clean line, not a traceback."""


class SegmentFetchUnavailable(ObjectStoreError):
    """A chunk fetch that exhausted its transport retry budget.  Carries
    the partition so the segment source can mark exactly it degraded
    (the PR-1 graceful-degradation contract) and keep scanning the rest."""

    def __init__(self, message: str, partition: "Optional[int]" = None):
        super().__init__(message)
        self.partition = partition


class _Transient(Exception):
    """Internal marker for a retryable failure (5xx, truncated body,
    ETag/MD5 disagreement): never escapes ``RetryingHttp.get``."""


def parse_object_store_spec(spec: str) -> "Tuple[bool, str, int, str]":
    """``(tls, host, port, base_path)`` for a remote store spec.

    ``http(s)://host[:port]/base`` addresses any S3-compatible endpoint
    path-style; ``s3://bucket[/prefix]`` is sugar for path-style access
    through the endpoint in ``KTA_S3_ENDPOINT`` (default
    ``https://s3.amazonaws.com`` — unauthenticated GETs, i.e. public or
    proxy-fronted buckets; signed access belongs to a fronting proxy)."""
    m = re.match(r"^(https?)://([^/:]+)(?::(\d+))?(/.*)?$", spec)
    if m:
        tls = m.group(1) == "https"
        host = m.group(2)
        port = int(m.group(3)) if m.group(3) else (443 if tls else 80)
        base = (m.group(4) or "").rstrip("/")
        return tls, host, port, base
    m = re.match(r"^s3://([^/]+)(/.*)?$", spec)
    if m:
        endpoint = os.environ.get("KTA_S3_ENDPOINT", "https://s3.amazonaws.com")
        tls, host, port, base = parse_object_store_spec(endpoint)
        return tls, host, port, f"{base}/{m.group(1)}{(m.group(2) or '').rstrip('/')}"
    raise ValueError(
        f"bad object store spec {spec!r}: expected http(s)://host[:port]/bucket"
        "[/prefix] or s3://bucket[/prefix]"
    )


class RetryingHttp:
    """The one place remote-tier bytes cross a socket (lint rule 11).

    Connections are per-thread (the read-ahead pool fetches concurrently)
    and evicted on any failure so a retry reconnects fresh.  ``get`` is
    the public surface: every attempt is paced by the shared `Backoff`
    schedule, every retry booked on ``kta_segstore_retries_total``, and
    per-partition failure streaks run through the `PartitionRetryBudget`
    so the degraded transition matches the live wire scan's semantics.
    """

    def __init__(self, spec: str, fetch: SegmentFetchConfig):
        self.spec = spec
        self.tls, self.host, self.port, self.base = parse_object_store_spec(spec)
        # Path-style S3 splits the base into BUCKET (the LIST endpoint —
        # /bucket/?list-type=2) and KEY PREFIX (folded into the prefix=
        # parameter and every object key): a /bucket/some/prefix spec
        # must never issue GET /bucket/some/prefix/?list-type=2, which
        # is an object GET, not a bucket LIST.
        parts = [p for p in self.base.split("/") if p]
        if not parts:
            # A bucketless spec would LIST against `GET /?list-type=2`
            # and GET `/name` — the user would see a confusing downstream
            # 404/XML error instead of a spec rejection.  (Validated here,
            # not in parse_object_store_spec: the s3:// branch parses a
            # bare KTA_S3_ENDPOINT with an empty base legitimately.)
            raise ValueError(
                f"bad object store spec {spec!r}: no bucket in path — "
                "expected http(s)://host[:port]/bucket[/prefix]"
            )
        self.bucket_path = f"/{parts[0]}"
        self.key_prefix = "/".join(parts[1:])
        if self.key_prefix:
            self.key_prefix += "/"
        self.timeout_s = fetch.timeout_s
        self.backoff = Backoff(fetch.retry)
        self.budget = PartitionRetryBudget(fetch.retry.retry_budget)
        #: Latched once ONE object proves (via a byte-identical re-fetch)
        #: that this store's ETags are not content MD5s: SSE and ETag
        #: policy are bucket-level, so re-learning it per chunk would
        #: download an archived year twice and sleep a backoff per chunk.
        self.etag_not_md5 = False
        #: Latched once ONE ranged GET comes back as a 200 full object:
        #: Range support is server-level, so once known the catalog
        #: fetches each chunk whole ONCE and slices its probes locally
        #: instead of downloading the full object per probe.
        self.range_ignored = False
        self._lock = threading.Lock()
        self._local = threading.local()

    def url_of(self, path: str) -> str:
        """Absolute URL of a request path, for error messages/logs."""
        scheme = "https" if self.tls else "http"
        return f"{scheme}://{self.host}:{self.port}{path}"

    # -- raw request (the only socket touch) ---------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (
                http.client.HTTPSConnection if self.tls
                else http.client.HTTPConnection
            )
            conn = cls(self.host, self.port, timeout=self.timeout_s)
            self._local.conn = conn
        return conn

    def _evict_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            self._local.conn = None

    def _one_request(
        self,
        path: str,
        rng: "Optional[Tuple[int, int]]",
        method: str = "GET",
        body: "Optional[bytes]" = None,
        extra_headers: "Optional[Dict[str, str]]" = None,
    ) -> "Tuple[int, bytes, Dict[str, str]]":
        """One request on this thread's connection: (status, body, headers).
        Raises OSError/http.client exceptions on transport failure.  This
        is the ONLY place that touches the socket (lint rule 11) — the
        lease layer's conditional PUTs ride the same connection pool,
        eviction, and timeout discipline as segment GETs."""
        headers: "Dict[str, str]" = {}
        if rng is not None:
            lo, hi = rng
            headers["Range"] = (
                f"bytes=-{hi}" if lo is None else f"bytes={lo}-{hi}"
            )
        if extra_headers:
            headers.update(extra_headers)
        conn = self._connection()
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        resp_body = resp.read()
        return (
            resp.status,
            resp_body,
            {k.lower(): v for k, v in resp.getheaders()},
        )

    # -- the retry-budget wrapper --------------------------------------------

    def get(
        self,
        path: str,
        rng: "Optional[Tuple[Optional[int], int]]" = None,
        kind: str = "body",
        partition: "Optional[int]" = None,
        expect: "Optional[int]" = None,
    ) -> bytes:
        """GET with retry/budget/integrity.  ``rng`` is an inclusive byte
        range ((None, n) = suffix range, S3 semantics); ``expect`` the
        exact body length required (a short read is a transient truncated
        stream, like the wire client's).  ``partition`` routes failure
        streaks through the shared budget: exhaustion raises
        `SegmentFetchUnavailable` (the caller degrades the partition);
        catalog-time operations with no partition fail after the same
        number of attempts."""
        if partition is not None and partition in self.budget.degraded:
            raise SegmentFetchUnavailable(
                f"{self.url_of(path)}: partition {partition} "
                f"already degraded ({self.budget.degraded[partition]})",
                partition=partition,
            )
        attempt = 0
        #: MD5 of the last body whose ETag disagreed: a SECOND fetch
        #: returning the identical bytes proves the damage is not
        #: in-flight — the ETag simply is not the content MD5 (SSE-KMS /
        #: SSE-C / composite ETags), and retrying further would burn the
        #: whole budget against a healthy encrypted archive.
        mismatched_md5: "Optional[str]" = None
        while True:
            try:
                try:
                    status, body, headers = self._one_request(path, rng)
                except (OSError, http.client.HTTPException) as e:
                    self._evict_connection()
                    raise _Transient(
                        f"{type(e).__name__}: {e}"
                    ) from e
                if status in (500, 502, 503, 504):
                    raise _Transient(f"HTTP {status}")
                if status not in (200, 206):
                    raise ObjectStoreError(
                        f"object store GET {self.url_of(path)} failed: "
                        f"HTTP {status}"
                    )
                #: What actually crossed the wire — the egress metric
                #: books this even when a range-ignored full body is
                #: sliced down to a 32-byte window below.
                transferred = len(body)
                if (
                    status == 200
                    and rng is not None
                    and (expect is None or len(body) != expect)
                ):
                    # The endpoint ignored the Range header and replied 200
                    # with the FULL object.  That is deterministic server
                    # behavior, not in-flight damage: slice the requested
                    # window out (booked — every header probe against such
                    # a server pays a whole-body download) instead of
                    # burning the retry budget on 'truncated body'.  But a
                    # 200 body CUT SHORT of its own Content-Length is
                    # in-flight truncation, not range-ignoring — still
                    # transient.
                    try:
                        declared = int(headers.get("content-length", ""))
                    except ValueError:
                        declared = None
                    if declared is not None and len(body) < declared:
                        self._evict_connection()
                        raise _Transient(
                            f"truncated body ({len(body)} of "
                            f"{declared} bytes)"
                        )
                    lo, hi = rng
                    sliced = (
                        (body[-hi:] if hi else b"") if lo is None
                        else body[lo : hi + 1]
                    )
                    if expect is not None and len(sliced) != expect:
                        if declared is None:
                            # Close-delimited response (no Content-Length)
                            # cut short: indistinguishable from in-flight
                            # truncation — retry under the budget rather
                            # than abort the scan on one network blip.
                            self._evict_connection()
                            raise _Transient(
                                f"short 200 body for ranged GET "
                                f"({len(body)} bytes, no Content-Length)"
                            )
                        raise ObjectStoreError(
                            f"object store GET {self.url_of(path)} ignored "
                            f"Range: bytes={'' if lo is None else lo}-{hi} "
                            f"and its {len(body)}-byte 200 response cannot "
                            f"satisfy it — server does not support ranged "
                            "GETs"
                        )
                    body = sliced
                    self.range_ignored = True
                    _book_fallback("range-ignored")
                elif expect is not None and len(body) != expect:
                    self._evict_connection()
                    raise _Transient(
                        f"truncated body ({len(body)} of {expect} bytes)"
                    )
                if status == 200 and rng is None:
                    # Whole-object GET: S3 ETags for SIMPLE objects are the
                    # body MD5, so a first mismatch is presumed damage in
                    # flight and re-fetched.  But 32-hex ETags that are NOT
                    # the content MD5 exist (SSE-KMS / SSE-C encrypt the
                    # stored bytes), so the check is skipped when the
                    # response declares such encryption — and a SECOND
                    # fetch returning byte-identical data proves the
                    # mismatch is persistent, not in-flight: accept the
                    # body (booked) and let the structural / sha256
                    # validation downstream judge it, rather than degrading
                    # every partition of a healthy encrypted archive.
                    etag = headers.get("etag", "").strip('"')
                    sse = headers.get(
                        "x-amz-server-side-encryption", ""
                    ).lower()
                    etag_is_md5 = (
                        not self.etag_not_md5
                        and re.fullmatch(r"[0-9a-f]{32}", etag) is not None
                        and "kms" not in sse
                        and "x-amz-server-side-encryption-customer-algorithm"
                        not in headers
                    )
                    if etag_is_md5:
                        md5 = hashlib.md5(body).hexdigest()
                        if md5 == etag:
                            pass
                        elif md5 == mismatched_md5:
                            self.etag_not_md5 = True
                            _book_fallback("etag-not-md5")
                            obs_events.emit(
                                "segstore_etag_not_md5",
                                url=self.url_of(path),
                                etag=etag,
                            )
                        else:
                            mismatched_md5 = md5
                            raise _Transient("body MD5 does not match ETag")
                obs_metrics.SEGSTORE_GETS.labels(kind=kind).inc()
                obs_metrics.SEGSTORE_BYTES.inc(transferred)
                if partition is not None:
                    with self._lock:
                        self.budget.record_success(partition)
                return body
            except _Transient as e:
                attempt += 1
                obs_metrics.SEGSTORE_RETRIES.inc()
                if partition is not None:
                    with self._lock:
                        self.budget.record_failure(partition, str(e))
                        exhausted = partition in self.budget.degraded
                    if exhausted:
                        raise SegmentFetchUnavailable(
                            f"{self.url_of(path)}: "
                            f"{self.budget.degraded[partition]}",
                            partition=partition,
                        ) from e
                elif attempt >= self.budget.budget:
                    raise ObjectStoreError(
                        f"object store GET {self.url_of(path)} failed "
                        f"after {attempt} attempts (last: {e})"
                    ) from e
                self.backoff.sleep_for(attempt)

    def list_objects(self, prefix: str) -> "List[Tuple[str, int]]":
        """LIST (name, size) under ``prefix`` — ListObjectsV2-shaped:
        ``{bucket}/?list-type=2&prefix={key_prefix}{prefix}`` returning
        ListBucketResult XML, PAGINATED: S3 caps a LIST page at 1000 keys
        and an archived year is tens of thousands of chunks, so this
        follows NextContinuationToken until IsTruncated clears — a
        truncated page that carries no token is a protocol violation and
        fails loudly (a silently short catalog would scan incomplete data
        'successfully').  Every page rides the same retry-budget ``get``.
        Keys come back as full bucket keys; the basename is the
        store-relative name, so flat and prefixed layouts enumerate
        identically."""
        from urllib.parse import quote

        out: "List[Tuple[str, int]]" = []
        token: "Optional[str]" = None
        while True:
            path = (
                f"{self.bucket_path}/?list-type=2"
                f"&prefix={quote(self.key_prefix + prefix)}"
            )
            if token:
                path += f"&continuation-token={quote(token)}"
            body = self.get(path, kind="list")
            try:
                root = ElementTree.parse(io.BytesIO(body)).getroot()
            except ElementTree.ParseError as e:
                raise ObjectStoreError(
                    f"object store LIST {self.spec} returned unparseable "
                    f"XML: {e}"
                ) from e
            # S3 proper namespaces the document; local servers may not.
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for c in root.iter(f"{ns}Contents"):
                key = c.findtext(f"{ns}Key") or ""
                size = int(c.findtext(f"{ns}Size") or 0)
                out.append((key.rsplit("/", 1)[-1], size))
            truncated = (
                (root.findtext(f"{ns}IsTruncated") or "").strip().lower()
                == "true"
            )
            if not truncated:
                return out
            next_token = root.findtext(f"{ns}NextContinuationToken")
            if not next_token:
                raise ObjectStoreError(
                    f"object store LIST {self.spec} returned a truncated "
                    "page without a NextContinuationToken — cannot "
                    "enumerate the full catalog"
                )
            if next_token == token:
                # A server that echoes the same token forever would loop
                # this walk unboundedly while duplicating keys.
                raise ObjectStoreError(
                    f"object store LIST {self.spec} repeated continuation "
                    f"token {next_token!r} — no pagination progress"
                )
            token = next_token

    # -- small-object + conditional-write surface (the lease transport) -------

    def get_small(
        self, path: str
    ) -> "Optional[Tuple[bytes, str]]":
        """GET a small control object whole: (body, etag), or None on 404.

        Unlike ``get`` this treats 404 as an ANSWER, not an error — an
        absent lease record means "nobody has ever owned this topic",
        which the lease layer must distinguish from a store outage.  No
        MD5-vs-ETag integrity pass either: the ETag here is an opaque
        fencing token for If-Match (fleet/lease.py, DESIGN §23), not a
        content checksum to verify.  Transient failures retry on the
        shared backoff; exhaustion raises ObjectStoreError (the caller
        degrades, it does not guess)."""
        attempt = 0
        while True:
            try:
                try:
                    status, body, headers = self._one_request(path, None)
                except (OSError, http.client.HTTPException) as e:
                    self._evict_connection()
                    raise _Transient(f"{type(e).__name__}: {e}") from e
                if status in (500, 502, 503, 504):
                    raise _Transient(f"HTTP {status}")
                if status == 404:
                    return None
                if status != 200:
                    raise ObjectStoreError(
                        f"object store GET {self.url_of(path)} failed: "
                        f"HTTP {status}"
                    )
                obs_metrics.SEGSTORE_GETS.labels(kind="lease").inc()
                obs_metrics.SEGSTORE_BYTES.inc(len(body))
                return body, headers.get("etag", "").strip('"')
            except _Transient as e:
                attempt += 1
                obs_metrics.SEGSTORE_RETRIES.inc()
                if attempt >= self.budget.budget:
                    raise ObjectStoreError(
                        f"object store GET {self.url_of(path)} failed "
                        f"after {attempt} attempts (last: {e})"
                    ) from e
                self.backoff.sleep_for(attempt)

    def put_conditional(
        self,
        path: str,
        body: bytes,
        if_match: "Optional[str]" = None,
        if_none_match: bool = False,
    ) -> "Optional[str]":
        """Conditional PUT: the fencing primitive (DESIGN §23).

        ``if_match`` sends ``If-Match: "<etag>"`` (replace exactly the
        version we read); ``if_none_match`` sends ``If-None-Match: *``
        (create only if absent).  Returns the NEW etag on success, or
        None on HTTP 412 — a lost compare-and-swap race, which is a
        deterministic answer and is never retried here.  Transport
        failures retry on the shared backoff, which makes a PUT
        AMBIGUOUS: the first attempt may have been applied before the
        connection died, so the retry can 412 against our own write.
        The caller (ObjectLeaseStore) resolves that by reading the
        record back and comparing owner/epoch — this layer stays a dumb
        transport and reports exactly what the server said."""
        if (if_match is None) == (not if_none_match):
            raise ValueError(
                "put_conditional requires exactly one of if_match / "
                "if_none_match — an unconditional lease write would be "
                "a fencing hole"
            )
        hdrs = {"Content-Length": str(len(body))}
        if if_match is not None:
            hdrs["If-Match"] = f'"{if_match}"'
        else:
            hdrs["If-None-Match"] = "*"
        attempt = 0
        while True:
            try:
                try:
                    status, resp_body, headers = self._one_request(
                        path, None, method="PUT", body=body,
                        extra_headers=hdrs,
                    )
                except (OSError, http.client.HTTPException) as e:
                    self._evict_connection()
                    raise _Transient(f"{type(e).__name__}: {e}") from e
                if status in (500, 502, 503, 504):
                    raise _Transient(f"HTTP {status}")
                if status == 412:
                    return None
                if status not in (200, 201, 204):
                    raise ObjectStoreError(
                        f"object store PUT {self.url_of(path)} failed: "
                        f"HTTP {status}"
                    )
                return headers.get("etag", "").strip('"')
            except _Transient as e:
                attempt += 1
                obs_metrics.SEGSTORE_RETRIES.inc()
                if attempt >= self.budget.budget:
                    raise ObjectStoreError(
                        f"object store PUT {self.url_of(path)} failed "
                        f"after {attempt} attempts (last: {e})"
                    ) from e
                self.backoff.sleep_for(attempt)

    def object_path(self, name: str) -> str:
        from urllib.parse import quote

        return f"{self.bucket_path}/{quote(self.key_prefix + name)}"


def _book_fallback(reason: str) -> None:
    """Every fallback-to-direct-fetch path books its reason — a cache
    bypass is never silent (lint rule 11; same discipline as the fused
    and compaction fallbacks)."""
    obs_metrics.SEGSTORE_FALLBACK.labels(reason=reason).inc()


#: The process-lifetime trust latch: address digests whose on-disk bytes
#: SOME SegmentCache instance in this process already sha256-verified.
#: Deliberately shared across instances — every scan builds its own
#: source/store/cache object over the same directory, and "verify once
#: per process" must survive that churn.  Digests bind store spec + name
#: + size, so two stores can never alias each other's trust.  Set
#: membership/add/discard are GIL-atomic; mutation happens only through
#: the SegmentCache choke points below (tools/lint.sh rule 15).
_PROCESS_TRUSTED: "set" = set()


class SegmentCache:
    """Content-verified local chunk cache with LRU size bounding.

    Entry layout: ``DIR/{digest}.seg`` (the raw chunk bytes) +
    ``DIR/{digest}.json`` sidecar ``{name, size, sha256}``, where digest =
    sha256 of the store spec + object name + size — two stores (or a
    re-dumped object of a different size) can never collide.  Writes land
    tmp-file → ``os.replace`` so a crashed writer leaves no partial entry;
    the sidecar lands LAST, so an entry is visible only once both halves
    are durable.  The FIRST hit of an entry each process lifetime
    re-hashes its bytes against the sidecar's sha256 and latches the
    digest as trusted; later hits skip the hash (amortized verify) —
    the cache serves exactly what was fetched and verified, or nothing.
    """

    def __init__(self, directory: str, max_bytes: int, store_key: str):
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        self.max_bytes = max_bytes
        self.store_key = store_key
        self._lock = threading.Lock()
        #: The process-wide trust latch (see _PROCESS_TRUSTED) — bound
        #: here so access stays confined to the
        #: _latch_trusted/_unlatch_trusted/_is_trusted choke points
        #: (tools/lint.sh rule 15) and every trust transition books.
        self._trusted: "set" = _PROCESS_TRUSTED
        #: Running resident-bytes estimate so inserts are O(1): the full
        #: directory sweep (and the estimate's re-sync) only runs when
        #: this crosses the bound — a year-scale fill must not stat the
        #: whole cache on every insert.
        self._total = sum(
            st.st_size
            for st in (
                self._stat(os.path.join(directory, f))
                for f in os.listdir(directory)
                if f.endswith(".seg")
            )
            if st is not None
        )

    @staticmethod
    def _stat(path: str):
        try:
            return os.stat(path)
        except OSError:
            return None

    def _digest(self, name: str, size: int) -> str:
        return hashlib.sha256(
            f"{self.store_key}\n{name}\n{size}".encode()
        ).hexdigest()

    def _paths(self, digest: str) -> "Tuple[str, str]":
        return (
            os.path.join(self.directory, f"{digest}.seg"),
            os.path.join(self.directory, f"{digest}.json"),
        )

    # -- the trust-latch choke points (tools/lint.sh rule 15: the ONLY
    # code allowed to touch self._trusted, so every trust transition is
    # auditable and booked) ---------------------------------------------------

    def _is_trusted(self, digest: str) -> bool:
        """Hit-side choke point: True when this process already verified
        the entry's bytes, booking the amortized hit
        (``kta_segstore_cache_verify_latched_total``)."""
        if digest in self._trusted:
            obs_metrics.SEGSTORE_CACHE_VERIFY_LATCHED.inc()
            return True
        return False

    def _latch_trusted(self, digest: str) -> None:
        """Latch an entry whose sha256 JUST verified: later hits this
        process lifetime skip the re-hash."""
        self._trusted.add(digest)

    def _unlatch_trusted(self, digest: str, reason: str) -> None:
        """Drop the trust latch — the on-disk bytes are gone or about to
        change, so the next hit must re-verify (first-touch verification
        is what keeps the never-serve-poison guarantee).  Dropping a
        LATCHED digest is rare enough to narrate."""
        if digest in self._trusted:
            self._trusted.discard(digest)
            obs_events.emit(
                "segment_cache_unlatched", digest=digest, reason=reason
            )

    def get(self, name: str, size: int) -> "Optional[np.ndarray]":
        """Verified chunk bytes for (name, size) as a read-only memmap
        view (zero-copy into the column slicer / fused native pass), or
        None (miss / poisoned — a poisoned entry is evicted and booked,
        the caller re-fetches).

        LOCK-FREE on the read+hash path: entries are immutable once
        renamed in (os.replace is atomic, the sidecar lands last), and a
        concurrent eviction's unlink leaves an already-mapped file
        readable — POSIX unlink semantics — (worst case: this read
        becomes a miss).  Holding the cache lock here would serialize
        every stream's verification hashing behind one core."""
        digest = self._digest(name, size)
        seg, meta = self._paths(digest)
        try:
            with open(meta, "rb") as f:
                sidecar = json.load(f)
            data = np.memmap(seg, dtype=np.uint8, mode="r")
        except (OSError, ValueError):
            obs_metrics.SEGSTORE_CACHE_MISSES.inc()
            return None
        if self._is_trusted(digest):
            # Verify-amortized hit: this process already hashed these
            # bytes once; serve the mapping without re-hashing (the
            # verify-seconds counter stands still, the latched counter
            # advances — BENCH round 16's warm-re-audit claim).
            pass
        else:
            # First touch this process lifetime: the verify residual,
            # booked.  Hashing the mapping faults its pages in — the
            # same IO a read would have paid, minus the copy.
            t0 = _perf_counter()
            content = hashlib.sha256(data).hexdigest()
            obs_metrics.SEGSTORE_CACHE_VERIFY_SECONDS.inc(
                _perf_counter() - t0
            )
            if content != sidecar.get("sha256"):
                # A flipped byte at rest in the CACHE: never serve it —
                # drop the entry, book the reason, fall back to a direct
                # fetch (the store itself is re-verified on that path).
                _book_fallback("cache-poisoned")
                obs_events.emit(
                    "segment_cache_poisoned", name=name, entry=seg
                )
                with self._lock:
                    self._remove(seg, meta)
                obs_metrics.SEGSTORE_CACHE_MISSES.inc()
                return None
            self._latch_trusted(digest)
        obs_metrics.SEGSTORE_CACHE_HITS.inc()
        obs_metrics.SEGSTORE_CACHE_HIT_BYTES.inc(len(data))
        now = None  # touch: mtime = now marks the entry recently used
        try:
            os.utime(seg, now)
        except OSError:
            pass
        return data

    def evict(self, name: str, size: int) -> None:
        """Drop one entry (a STALE hit: its bytes match their sidecar —
        not rot — but no longer match what the store's catalog now
        declares, e.g. the archive was re-dumped at the same size).  The
        caller books the fallback reason and re-fetches."""
        digest = self._digest(name, size)
        self._unlatch_trusted(digest, "evicted-stale")
        with self._lock:
            self._remove(*self._paths(digest))
        obs_metrics.SEGSTORE_CACHE_EVICTIONS.inc()

    def put(self, name: str, size: int, data: bytes) -> None:
        """Insert one verified chunk.  The write itself runs UNLOCKED —
        tmp names are per-thread and the double rename is atomic, so
        concurrent writers of different chunks never serialize their
        hashing/IO; only the LRU sweep takes the lock."""
        digest = self._digest(name, size)
        seg, meta = self._paths(digest)
        # Re-population replaces the on-disk bytes: whatever trust the
        # old bytes earned does not transfer — the next hit re-verifies
        # the NEW bytes at first touch (catching write-path rot too).
        self._unlatch_trusted(digest, "re-populated")
        try:
            tmp = f"{seg}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(data)
            # Re-inserting an existing digest (racing fetches of one
            # chunk, a re-put after an unreadable sidecar) REPLACES its
            # bytes: only the net growth may be added to the running
            # total, or the inflated estimate triggers premature
            # full-directory eviction sweeps.  The stat, the rename, and
            # the total update must be one atom — two racing puts of the
            # SAME digest would otherwise both stat the pre-replace state
            # and both add the full size.  (The expensive body write
            # above stays unlocked.)
            with self._lock:
                replaced = self._stat(seg)
                os.replace(tmp, seg)
                self._total += len(data) - (
                    replaced.st_size if replaced is not None else 0
                )
            mtmp = f"{meta}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(mtmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "name": name,
                        "size": size,
                        "sha256": hashlib.sha256(data).hexdigest(),
                    },
                    f,
                )
            os.replace(mtmp, meta)
        except OSError:
            # An unwritable cache must not fail the scan — the chunk
            # was already fetched and verified; book the bypass.
            _book_fallback("cache-io-error")
            return
        with self._lock:
            if self._total > self.max_bytes:
                self._evict_to_bound(keep=digest)

    def _remove(self, seg: str, meta: str) -> None:
        """Unlink one entry, keeping the resident-bytes estimate in step
        (callers hold the lock)."""
        st = self._stat(seg)
        if st is not None:
            self._total -= st.st_size
        for path in (seg, meta):
            try:
                os.remove(path)
            except OSError:
                pass

    def _evict_to_bound(self, keep: "Optional[str]" = None) -> None:
        """Drop least-recently-used entries until total bytes fit the
        bound (one full sweep, which also re-syncs the running estimate
        against reality — re-puts of an existing digest and external
        deletions drift it).  The just-inserted entry (``keep``)
        survives even when it alone exceeds the bound — a cache that
        immediately discards what it just fetched would thrash forever."""
        entries = []
        total = 0
        for fname in os.listdir(self.directory):
            if not fname.endswith(".seg"):
                continue
            st = self._stat(os.path.join(self.directory, fname))
            if st is None:
                continue
            entries.append((st.st_mtime, st.st_size, fname[: -len(".seg")]))
            total += st.st_size
        entries.sort()
        self._total = total
        for _, size, digest in entries:
            if self._total <= self.max_bytes:
                break
            if digest == keep:
                continue
            # An evicted digest may later be re-filled with fresh bytes
            # at the same path — drop its latch so that first hit
            # re-verifies.
            self._unlatch_trusted(digest, "evicted-lru")
            self._remove(*self._paths(digest))
            obs_metrics.SEGSTORE_CACHE_EVICTIONS.inc()
