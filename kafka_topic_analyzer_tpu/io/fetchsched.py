"""Process-wide fetch scheduler: ONE admission point for every remote byte.

PR 14 gave each ingest stream its own read-ahead pool, and BENCH round 14
measured the consequences: concurrency could only deepen by multiplying
pools (the round-6 thread-churn regression shape), short-RTT stores never
saturated the wire, and concurrent streams — parallel ingest workers, the
catalog's header probes, fleet topics — competed blindly for sockets.
This module replaces every one of those private pools with ONE scheduler
per process (DESIGN.md §25):

- **Single admission point.**  All remote chunk-body fetches, catalog
  header probes, and plan-time resume probes submit here; nothing else in
  ``io/segstore.py`` / ``io/objstore.py`` / ``io/segfile.py`` may
  construct a pool or thread (tools/lint.sh rule 15).  The worker pool is
  sized once per process (``--fetch-concurrency N|auto``), so total
  connection count is a process property, not ``streams × depth``.
- **Two priority classes.**  A DEMAND request is one a consumer is
  blocked on *right now* (the chunk the decoder needs next, a catalog
  probe the plan cannot proceed without); SPECULATIVE is read-ahead.
  Demand always outranks speculation — booked on
  ``kta_fetch_sched_reorders_total{reason="demand-over-speculative"}``
  when a demand request actually jumps queued speculative work, and
  ``{reason="deadline-promotion"}`` when a consumer reaches a chunk whose
  speculative request is still queued and promotes it.
- **Per-stream fairness, weighted.**  Each consumer registers a
  `FetchStream` with a WEIGHT (its lag / planned chunk count — the
  ingest read-ahead registers its segment-plan size; default 1.0), and
  selection within each priority class is smooth weighted round-robin
  across the streams that have queued work: a stream with twice the
  backlog weight is granted twice the admissions, interleaved (never
  bursted), and equal weights degrade to the exact round-robin of PR
  19 — so a stream with a deep speculative backlog cannot starve a
  sibling's first request, and a fleet topic that is 10× further behind
  drains ~10× the bytes instead of splitting the wire evenly with an
  almost-caught-up sibling.  ``FetchStream.set_weight`` retargets a
  live stream (lag moves; weights follow).
- **Cancellation.**  A queued request can be cancelled before it starts
  (``kta_fetch_sched_cancelled_total``): degraded-partition skips and
  stream teardown must not pay for bytes nobody will read.  In-flight
  fetches are never interrupted — `shutdown` drains them cleanly.

Occupancy telemetry (``kta_fetch_sched_queue_depth`` /
``_inflight`` / ``_wait_seconds_total``) feeds FlightRecorder tracks so
`obs/doctor.py` can attribute a fetch-bound scan to scheduler starvation
(queue deeper than the pool — raise ``--fetch-concurrency``) vs wire
saturation (pool busy, queue shallow — the link is the limit).
"""

from __future__ import annotations

import os
import threading
from time import perf_counter as _perf_counter
from typing import Callable, Dict, List, Optional

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

#: Priority classes.  Smaller = served first.
DEMAND = 0
SPECULATIVE = 1

#: Ticket states.
_QUEUED, _RUNNING, _DONE, _CANCELLED = range(4)

#: Hard cap on the auto-sized pool: past ~16 connections the remote tier
#: is wire-bound, not admission-bound, and more threads only churn.
_MAX_AUTO = 16


def default_concurrency() -> int:
    """``--fetch-concurrency auto``: enough workers to keep a multi-stream
    scan's demand + speculation in flight on any host, capped where more
    sockets stop helping."""
    return min(_MAX_AUTO, max(4, os.cpu_count() or 4))


class FetchTicket:
    """One scheduled fetch: the callable, its stream/sequence position,
    its priority class, and (after completion) its outcome.  Waiters
    block on ``wait``/``result``; ``cancel`` works only while queued."""

    __slots__ = (
        "_sched", "stream_id", "fn", "seq", "pclass", "ordinal", "state",
        "submitted", "value", "error", "_done",
    )

    def __init__(
        self,
        sched: "FetchScheduler",
        stream_id: int,
        fn: "Callable[[], object]",
        seq: int,
        pclass: int,
        ordinal: int,
    ):
        self._sched = sched
        self.stream_id = stream_id
        self.fn = fn
        self.seq = seq
        self.pclass = pclass
        #: Global submission order — the referee for "did a demand
        #: request actually jump queued speculative work".
        self.ordinal = ordinal
        self.state = _QUEUED
        self.submitted = _perf_counter()
        self.value: object = None
        self.error: "Optional[BaseException]" = None
        self._done = threading.Event()

    def wait(self, timeout: "Optional[float]" = None) -> bool:
        """Block until the fetch completed or was cancelled."""
        return self._done.wait(timeout)

    def result(self, timeout: "Optional[float]" = None) -> object:
        """The fetch's return value, re-raising its exception in the
        caller (the synchronous-fetch contract `run`/`run_all` build on)."""
        if not self._done.wait(timeout):
            raise TimeoutError("fetch request did not complete in time")
        if self.error is not None:
            raise self.error
        return self.value

    def cancel(self) -> bool:
        """Cancel if still queued (booked); False once started/finished."""
        return self._sched.cancel(self)

    @property
    def cancelled(self) -> bool:
        return self.state == _CANCELLED


class FetchStream:
    """One consumer's handle on the scheduler: the unit of weighted
    fairness.  Each ingest stream (and each catalog open) registers its
    own; ``close`` cancels everything of this stream still queued."""

    def __init__(self, sched: "FetchScheduler", sid: int):
        self._sched = sched
        self.sid = sid
        self._closed = False

    def set_weight(self, weight: float) -> "FetchStream":
        """Retarget this stream's fairness weight (lag / partition or
        chunk count).  Selection share within a priority class is
        proportional among streams with queued work; takes effect on
        the next admission."""
        self._sched.set_weight(self.sid, weight)
        return self

    def submit(
        self, fn: "Callable[[], object]", seq: int = 0,
        speculative: bool = True,
    ) -> FetchTicket:
        if self._closed:
            raise RuntimeError("fetch stream is closed")
        return self._sched._submit(
            self.sid, fn, seq, SPECULATIVE if speculative else DEMAND
        )

    def demand(self, ticket: FetchTicket) -> None:
        """The consumer is blocked on this request NOW: promote it past
        every speculative fetch (booked when it was still queued) and
        wait for it to finish."""
        self._sched.promote(ticket)
        ticket.wait()

    def close(self) -> None:
        """Unregister the stream; queued requests are cancelled (booked),
        in-flight ones finish on their worker."""
        if not self._closed:
            self._closed = True
            self._sched._close_stream(self.sid)


class FetchScheduler:
    """The shared worker pool + priority queue.  One instance per process
    (`get_scheduler`); tests may construct private instances."""

    def __init__(self, concurrency: "Optional[int]" = None):
        if concurrency is None:
            concurrency = default_concurrency()
        if concurrency < 1:
            raise ValueError("fetch concurrency must be >= 1")
        self._cv = threading.Condition()
        self._target = int(concurrency)
        #: stream id -> queued tickets (unordered; selection scans).
        self._queues: "Dict[int, List[FetchTicket]]" = {}
        #: Stream ids in registration order — the deterministic
        #: tie-break for weighted selection.
        self._order: "List[int]" = []
        #: Smooth weighted round-robin state (nginx SWRR): each
        #: selection credits every CANDIDATE stream (queued work in the
        #: class being served) by its weight, picks the highest credit,
        #: and debits the winner by the candidates' total — proportional
        #: shares, interleaved, deterministic, and exactly round-robin
        #: when all weights are equal.
        self._weights: "Dict[int, float]" = {}
        self._credits: "Dict[int, float]" = {}
        self._next_sid = 0
        self._ordinal = 0
        self._live = 0
        self._idle = 0
        self._spawned = 0
        self._threads: "List[threading.Thread]" = []
        self._stopped = False

    @property
    def concurrency(self) -> int:
        return self._target

    # -- streams --------------------------------------------------------------

    def stream(self, weight: float = 1.0) -> FetchStream:
        if weight <= 0:
            raise ValueError("fetch stream weight must be > 0")
        with self._cv:
            if self._stopped:
                raise RuntimeError("fetch scheduler is shut down")
            sid = self._next_sid
            self._next_sid += 1
            self._order.append(sid)
            self._queues[sid] = []
            self._weights[sid] = float(weight)
            self._credits[sid] = 0.0
        return FetchStream(self, sid)

    def set_weight(self, sid: int, weight: float) -> None:
        if weight <= 0:
            raise ValueError("fetch stream weight must be > 0")
        with self._cv:
            if sid in self._weights:
                self._weights[sid] = float(weight)

    def _close_stream(self, sid: int) -> None:
        with self._cv:
            dropped = [
                t for t in self._queues.pop(sid, [])
                if t.state == _QUEUED
            ]
            for t in dropped:
                t.state = _CANCELLED
                obs_metrics.FETCH_SCHED_QUEUE_DEPTH.inc(-1)
                obs_metrics.FETCH_SCHED_CANCELLED.inc()
            if sid in self._order:
                self._order.remove(sid)
            self._weights.pop(sid, None)
            self._credits.pop(sid, None)
        for t in dropped:
            t._done.set()

    # -- submission / cancellation / promotion --------------------------------

    def _submit(
        self, sid: int, fn: "Callable[[], object]", seq: int, pclass: int
    ) -> FetchTicket:
        with self._cv:
            if self._stopped:
                raise RuntimeError("fetch scheduler is shut down")
            ticket = FetchTicket(self, sid, fn, seq, pclass, self._ordinal)
            self._ordinal += 1
            self._queues.setdefault(sid, []).append(ticket)
            obs_metrics.FETCH_SCHED_QUEUE_DEPTH.inc(1)
            self._ensure_workers()
            self._cv.notify()
        return ticket

    def cancel(self, ticket: FetchTicket) -> bool:
        with self._cv:
            if ticket.state != _QUEUED:
                return False
            q = self._queues.get(ticket.stream_id)
            if q is not None and ticket in q:
                q.remove(ticket)
            ticket.state = _CANCELLED
            obs_metrics.FETCH_SCHED_QUEUE_DEPTH.inc(-1)
            obs_metrics.FETCH_SCHED_CANCELLED.inc()
        ticket._done.set()
        return True

    def promote(self, ticket: FetchTicket) -> bool:
        """Raise a queued speculative request to DEMAND (the deadline
        rule: the chunk a decoder needs next outranks read-ahead)."""
        with self._cv:
            if ticket.state != _QUEUED or ticket.pclass != SPECULATIVE:
                return False
            ticket.pclass = DEMAND
            obs_metrics.FETCH_SCHED_REORDERS.labels(
                reason="deadline-promotion"
            ).inc()
            self._cv.notify()
        return True

    # -- synchronous conveniences ---------------------------------------------

    def run(self, fn: "Callable[[], object]") -> object:
        """One demand fetch through the pool, result (or exception)
        re-delivered in the caller — the plan-time probe path."""
        stream = self.stream()
        try:
            return stream.submit(fn, seq=0, speculative=False).result()
        finally:
            stream.close()

    def run_all(self, fns: "List[Callable[[], object]]") -> "List[object]":
        """Demand-fetch a batch concurrently, results in submission order
        (the catalog's header-probe fan-out).  The first failure by order
        is re-raised after every request settled — a catalog either opens
        whole or fails deterministically, never half-probed."""
        stream = self.stream()
        try:
            tickets = [
                stream.submit(fn, seq=i, speculative=False)
                for i, fn in enumerate(fns)
            ]
            for t in tickets:
                t.wait()
            for t in tickets:
                if t.error is not None:
                    raise t.error
            return [t.value for t in tickets]
        finally:
            stream.close()

    # -- selection (the admission policy) --------------------------------------

    def _pick_stream(self, pclass: int) -> "Optional[int]":
        """Smooth weighted round-robin over the streams with queued work
        in ``pclass`` (callers hold the lock).  Idle streams accrue no
        credit, so a stream that sat quiet cannot burst later; ties
        break by registration order, keeping selection deterministic."""
        candidates = [
            sid
            for sid in self._order
            if any(t.pclass == pclass for t in self._queues.get(sid, ()))
        ]
        if not candidates:
            return None
        total = 0.0
        best_sid: "Optional[int]" = None
        for sid in candidates:
            w = self._weights.get(sid, 1.0)
            total += w
            self._credits[sid] = self._credits.get(sid, 0.0) + w
            if best_sid is None or self._credits[sid] > self._credits[best_sid]:
                best_sid = sid
        self._credits[best_sid] -= total
        return best_sid

    def _select(self) -> "Optional[FetchTicket]":
        """Pick the next request (callers hold the lock): DEMAND before
        SPECULATIVE, weighted round-robin across streams within a class
        (`_pick_stream`), lowest (seq, ordinal) within a stream —
        deterministic given the queue and the weights."""
        for pclass in (DEMAND, SPECULATIVE):
            sid = self._pick_stream(pclass)
            if sid is None:
                continue
            q = self._queues[sid]
            best: "Optional[FetchTicket]" = None
            for t in q:
                if t.pclass != pclass:
                    continue
                if best is None or (t.seq, t.ordinal) < (
                    best.seq, best.ordinal
                ):
                    best = t
            q.remove(best)
            if pclass == DEMAND and any(
                t.pclass == SPECULATIVE and t.ordinal < best.ordinal
                for queue in self._queues.values()
                for t in queue
            ):
                # This demand request jumped speculative work that was
                # submitted before it — the deadline rule reordering
                # the wire, made visible.
                obs_metrics.FETCH_SCHED_REORDERS.labels(
                    reason="demand-over-speculative"
                ).inc()
            best.state = _RUNNING
            obs_metrics.FETCH_SCHED_QUEUE_DEPTH.inc(-1)
            return best
        return None

    # -- the worker pool -------------------------------------------------------

    def _ensure_workers(self) -> None:
        """Spawn workers lazily up to the target while queued work exceeds
        idle capacity (callers hold the lock).  Threads are daemons: the
        pool never blocks interpreter exit."""
        backlog = sum(len(q) for q in self._queues.values())
        while (
            self._live < self._target
            and backlog > self._idle
            and not self._stopped
        ):
            self._live += 1
            self._spawned += 1
            th = threading.Thread(
                target=self._worker,
                name=f"kta-fetch-sched-{self._spawned}",
                daemon=True,
            )
            self._threads.append(th)
            th.start()
            backlog -= 1

    def _worker(self) -> None:
        while True:
            with self._cv:
                req: "Optional[FetchTicket]" = None
                while req is None:
                    if self._stopped or self._live > self._target:
                        self._live -= 1
                        return
                    req = self._select()
                    if req is None:
                        self._idle += 1
                        self._cv.wait()
                        self._idle -= 1
            obs_metrics.FETCH_SCHED_WAIT_SECONDS.inc(
                max(0.0, _perf_counter() - req.submitted)
            )
            obs_metrics.FETCH_SCHED_INFLIGHT.inc(1)
            try:
                req.value = req.fn()
            except BaseException as e:  # noqa: BLE001 — delivered to waiter
                req.error = e
            finally:
                obs_metrics.FETCH_SCHED_INFLIGHT.inc(-1)
                with self._cv:
                    req.state = _DONE
                req._done.set()

    def resize(self, concurrency: int) -> None:
        """Retarget the pool.  Growth spawns on the next submissions;
        excess workers exit as they finish their current fetch."""
        if concurrency < 1:
            raise ValueError("fetch concurrency must be >= 1")
        with self._cv:
            self._target = int(concurrency)
            self._ensure_workers()
            self._cv.notify_all()

    def shutdown(self, wait: bool = True) -> None:
        """Clean shutdown mid-fetch: queued requests are cancelled
        (booked), in-flight fetches complete on their workers, workers
        exit.  ``wait=True`` joins them."""
        with self._cv:
            self._stopped = True
            dropped = [
                t
                for q in self._queues.values()
                for t in q
                if t.state == _QUEUED
            ]
            for t in dropped:
                t.state = _CANCELLED
                obs_metrics.FETCH_SCHED_QUEUE_DEPTH.inc(-1)
                obs_metrics.FETCH_SCHED_CANCELLED.inc()
            self._queues.clear()
            self._cv.notify_all()
        for t in dropped:
            t._done.set()
        if wait:
            for th in self._threads:
                th.join(timeout=30)


# -- the process singleton -----------------------------------------------------

_lock = threading.Lock()
_singleton: "Optional[FetchScheduler]" = None
#: Last configured size + whether it came from an explicit flag value
#: (explicit beats auto: a later auto hint never shrinks or overrides
#: what the operator asked for).
_configured: "Optional[int]" = None
_explicit = False


def configure(concurrency: int, explicit: bool = True) -> None:
    """Size the process-wide pool (``--fetch-concurrency``).  Safe to
    call repeatedly — e.g. once per fleet topic source sharing one
    process: the LAST explicit value wins; auto hints only apply while
    no explicit size was ever given."""
    global _configured, _explicit
    if concurrency < 1:
        raise ValueError("fetch concurrency must be >= 1")
    with _lock:
        if not explicit and _explicit:
            return
        _configured = int(concurrency)
        _explicit = _explicit or explicit
        if _singleton is not None:
            _singleton.resize(_configured)


def note_streams(streams: int) -> None:
    """Engine hint: ``streams`` ingest streams are about to drain
    concurrently.  Under auto sizing, grow the pool so every stream can
    hold a demand fetch plus some speculation without starving siblings;
    an explicit ``--fetch-concurrency`` is never overridden."""
    want = min(_MAX_AUTO, max(default_concurrency(), streams + 2))
    with _lock:
        if _explicit:
            return
        global _configured
        if _configured is None or want > _configured:
            _configured = want
            if _singleton is not None:
                _singleton.resize(want)


def get_scheduler() -> FetchScheduler:
    """THE process-wide scheduler, created on first use at the configured
    (or auto) size."""
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = FetchScheduler(
                _configured if _configured is not None
                else default_concurrency()
            )
        return _singleton


def _reset_for_tests() -> None:
    """Tear down the singleton (tests only): shut the pool, forget the
    configuration."""
    global _singleton, _configured, _explicit
    with _lock:
        sched, _singleton = _singleton, None
        _configured = None
        _explicit = False
    if sched is not None:
        sched.shutdown(wait=True)
