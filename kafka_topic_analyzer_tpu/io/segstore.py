"""Segment catalog/fetch layer: where .ktaseg chunks live, how to find them.

The cold scan path (``--source segfile``) is tiered-storage-shaped: a
topic's retained history is a set of immutable segment chunks in *some*
store — a local directory today, an object store (S3/GCS) bucket later —
and the scan needs exactly two operations against it: enumerate a topic's
chunks and open one for reading.  This module is that seam:

- `SegmentStore` — the two-method fetch interface (`list_refs`, `open`).
  `DirectorySegmentStore` is the local implementation; an object-store
  client plugs in here without touching the reader, the catalog, or the
  engine (`open_segment_store` is the factory that will learn its URL
  schemes).
- `SegmentCatalog` — a validated view of one topic's chunks: header↔name
  consistency, per-partition chunk ordering by start offset, overlap
  rejection, and the per-partition record counts the parallel cold path
  uses to balance its workers (segments are disjoint offset ranges, so
  sharding *by partition* keeps the PR-4 determinism argument — each
  partition's chunks live in exactly one worker, in offset order).

Opening a catalog books the ``kta_segment_*`` telemetry (files opened,
bytes mapped) so the ``--stats``/``--json`` cold-path digest can report
what the scan actually touched.
"""

from __future__ import annotations

import abc
import dataclasses
import os
import re
from typing import TYPE_CHECKING, Dict, List, Tuple

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

if TYPE_CHECKING:  # circular at runtime: segfile imports this module
    from kafka_topic_analyzer_tpu.io.segfile import SegmentFile


def topic_chunk_pattern(topic: str) -> "re.Pattern[str]":
    """Exact match on ``{topic}-{int}[.c{int}].ktaseg``: a prefix match
    would also swallow segments of topics like ``{topic}-extra``."""
    return re.compile(rf"^{re.escape(topic)}-(\d+)(?:\.c\d+)?\.ktaseg$")


@dataclasses.dataclass(frozen=True)
class SegmentRef:
    """One enumerable chunk in a store, before it is opened."""

    #: Store-relative name, e.g. ``orders-3.c12.ktaseg``.
    name: str
    #: Partition id parsed from the name (the catalog cross-checks it
    #: against the opened header).
    partition: int
    #: Chunk size in bytes (telemetry + the reader's truncation check).
    size: int


class SegmentStore(abc.ABC):
    """Minimal fetch interface over a collection of .ktaseg chunks."""

    @abc.abstractmethod
    def list_refs(self, topic: str) -> List[SegmentRef]:
        """All chunks belonging to ``topic``, name-sorted."""

    @abc.abstractmethod
    def open(self, ref: SegmentRef) -> "SegmentFile":
        """Open one chunk for reading (memory-mapped for local stores)."""

    @abc.abstractmethod
    def describe(self) -> str:
        """Human-readable location, for error messages and logs."""


class DirectorySegmentStore(SegmentStore):
    """The local store: a directory of ``.ktaseg`` files (what
    ``--dump-segments`` and ``tools/make_segments`` produce)."""

    def __init__(self, directory: str):
        self.directory = directory

    def list_refs(self, topic: str) -> List[SegmentRef]:
        pattern = topic_chunk_pattern(topic)
        refs = []
        for fname in sorted(os.listdir(self.directory)):
            m = pattern.match(fname)
            if not m:
                continue
            refs.append(
                SegmentRef(
                    name=fname,
                    partition=int(m.group(1)),
                    size=os.path.getsize(os.path.join(self.directory, fname)),
                )
            )
        return refs

    def open(self, ref: SegmentRef) -> "SegmentFile":
        from kafka_topic_analyzer_tpu.io.segfile import SegmentFile

        return SegmentFile(os.path.join(self.directory, ref.name))

    def describe(self) -> str:
        return self.directory


def open_segment_store(spec: str) -> SegmentStore:
    """Store factory for ``--segment-dir``: a plain path is a local
    directory; a ``scheme://`` spec is reserved for remote stores (object
    storage) and rejected with the seam named, so the error reads as
    "not yet" rather than "never"."""
    m = re.match(r"^([a-z][a-z0-9+.-]*)://", spec)
    if m and m.group(1) != "file":
        raise ValueError(
            f"segment store scheme {m.group(1)!r} is not implemented yet "
            "(io/segstore.py SegmentStore is the plug-in seam); today only "
            "local directories are supported"
        )
    path = spec[len("file://"):] if m else spec
    if not os.path.isdir(path):
        raise ValueError(f"segment store {spec!r} is not a directory")
    return DirectorySegmentStore(path)


class SegmentCatalog:
    """One topic's validated chunk layout in a store.

    Opens every chunk (header + column map; the local store mmaps lazily —
    pages fault in only as batches read them), cross-checks the header's
    partition against the filename, orders each partition's chunks by
    start offset, and rejects overlapping chunks (stale files from an
    older dump would silently merge old and new records).
    """

    def __init__(self, store: SegmentStore, topic: str):
        from kafka_topic_analyzer_tpu.io.segfile import MalformedSegmentError

        self.store = store
        self.topic = topic
        self.segments: "Dict[int, List[SegmentFile]]" = {}
        self.num_files = 0
        self.total_bytes = 0
        for ref in store.list_refs(topic):
            seg = store.open(ref)
            if seg.partition != ref.partition:
                raise MalformedSegmentError(
                    f"{ref.name}: header partition {seg.partition} does "
                    f"not match filename",
                    path=ref.name,
                    partition=ref.partition,
                )
            self.segments.setdefault(seg.partition, []).append(seg)
            self.num_files += 1
            self.total_bytes += ref.size
        for p, chunks in self.segments.items():
            chunks.sort(key=lambda s: s.start_offset)
            for prev, nxt in zip(chunks, chunks[1:]):
                if nxt.start_offset < prev.end_offset:
                    raise MalformedSegmentError(
                        f"overlapping segment chunks for partition {p}: "
                        f"{os.path.basename(prev.path)} ends at "
                        f"{prev.end_offset} but "
                        f"{os.path.basename(nxt.path)} starts at "
                        f"{nxt.start_offset} — stale chunks from an older "
                        "dump?",
                        path=os.path.basename(nxt.path),
                        partition=p,
                    )
        obs_metrics.SEGMENT_FILES_OPENED.inc(self.num_files)
        obs_metrics.SEGMENT_BYTES_MAPPED.inc(self.total_bytes)

    def partitions(self) -> List[int]:
        return sorted(self.segments)

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        start = {p: c[0].start_offset for p, c in self.segments.items()}
        end = {p: c[-1].end_offset for p, c in self.segments.items()}
        return start, end

    def record_counts(self) -> Dict[int, int]:
        """Per-partition retained record counts — known exactly up front
        (unlike a live topic), so the parallel cold path can balance its
        workers by records instead of partition count."""
        return {
            p: sum(s.count for s in chunks)
            for p, chunks in self.segments.items()
        }
