"""Segment catalog/fetch layer: where .ktaseg chunks live, how to find them.

The cold scan path (``--source segfile``) is tiered-storage-shaped: a
topic's retained history is a set of immutable segment chunks in *some*
store — a local directory today, an object store (S3/GCS) bucket later —
and the scan needs exactly two operations against it: enumerate a topic's
chunks and open one for reading.  This module is that seam:

- `SegmentStore` — the two-method fetch interface (`list_refs`, `open`).
  `DirectorySegmentStore` is the local tier (memory-mapped files);
  `ObjectSegmentStore` is the remote tier (DESIGN.md §21): an S3-shaped
  HTTP client (LIST + ranged GET via io/objstore.py's retry-budget
  transport) whose catalog validation runs off ranged HEADER probes —
  never a chunk body — and whose chunk bodies arrive lazily through the
  process-wide fetch scheduler (io/fetchsched.py) and the sha256-verified
  local cache.
  `open_segment_store` is the factory: plain paths and ``file://`` are
  local, ``http(s)://`` / ``s3://`` are remote.
- `SegmentCatalog` — a validated view of one topic's chunks: header↔name
  consistency, per-partition chunk ordering by start offset, overlap
  rejection, and the per-partition record counts the parallel cold path
  uses to balance its workers (segments are disjoint offset ranges, so
  sharding *by partition* keeps the PR-4 determinism argument — each
  partition's chunks live in exactly one worker, in offset order).

Opening a catalog books the ``kta_segment_*`` telemetry (files opened,
bytes mapped) so the ``--stats``/``--json`` cold-path digest can report
what the scan actually touched.
"""

from __future__ import annotations

import abc
import dataclasses
import os
import re
import struct
from typing import TYPE_CHECKING, Dict, List, Tuple

from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics

if TYPE_CHECKING:  # circular at runtime: segfile imports this module
    from kafka_topic_analyzer_tpu.io.segfile import SegmentFile


def topic_chunk_pattern(topic: str) -> "re.Pattern[str]":
    """Exact match on ``{topic}-{int}[.c{int}].ktaseg``: a prefix match
    would also swallow segments of topics like ``{topic}-extra``."""
    return re.compile(rf"^{re.escape(topic)}-(\d+)(?:\.c\d+)?\.ktaseg$")


@dataclasses.dataclass(frozen=True)
class SegmentRef:
    """One enumerable chunk in a store, before it is opened."""

    #: Store-relative name, e.g. ``orders-3.c12.ktaseg``.
    name: str
    #: Partition id parsed from the name (the catalog cross-checks it
    #: against the opened header).
    partition: int
    #: Chunk size in bytes (telemetry + the reader's truncation check).
    size: int


class SegmentStore(abc.ABC):
    """Minimal fetch interface over a collection of .ktaseg chunks."""

    @abc.abstractmethod
    def list_refs(self, topic: str) -> List[SegmentRef]:
        """All chunks belonging to ``topic``, name-sorted."""

    @abc.abstractmethod
    def open(self, ref: SegmentRef) -> "SegmentFile":
        """Open one chunk for reading (memory-mapped for local stores)."""

    @abc.abstractmethod
    def describe(self) -> str:
        """Human-readable location, for error messages and logs."""


class DirectorySegmentStore(SegmentStore):
    """The local store: a directory of ``.ktaseg`` files (what
    ``--dump-segments`` and ``tools/make_segments`` produce)."""

    def __init__(self, directory: str):
        self.directory = directory

    def list_refs(self, topic: str) -> List[SegmentRef]:
        pattern = topic_chunk_pattern(topic)
        refs = []
        for fname in sorted(os.listdir(self.directory)):
            m = pattern.match(fname)
            if not m:
                continue
            refs.append(
                SegmentRef(
                    name=fname,
                    partition=int(m.group(1)),
                    size=os.path.getsize(os.path.join(self.directory, fname)),
                )
            )
        return refs

    def open(self, ref: SegmentRef) -> "SegmentFile":
        from kafka_topic_analyzer_tpu.io.segfile import SegmentFile

        return SegmentFile(os.path.join(self.directory, ref.name))

    def describe(self) -> str:
        return self.directory


class ObjectSegmentStore(SegmentStore):
    """The remote tier: chunks in an S3-shaped object store, addressed by
    ``http(s)://host[:port]/bucket[/prefix]`` or ``s3://bucket[/prefix]``.

    Enumeration is one ListObjectsV2-shaped request; opening a ref costs
    a ranged HEADER probe (plus, for gappy chunks, an 8-byte suffix probe
    for the offset-exact end watermark) — catalog validation never
    downloads a chunk body.  Bodies arrive through `fetch_chunk`:
    sha256-verified local cache first (``--segment-cache``), then a
    budget-retried GET whose bytes are classified with the local reader's
    exact corruption taxonomy; a classification failure is re-fetched
    ONCE to rule out an in-flight flip (io/kafka_wire.py's rule) before
    it counts as at-rest corruption.
    """

    #: The source resolves ``--segment-readahead auto`` against this.
    is_remote = True

    def __init__(self, spec: str, fetch=None):
        from kafka_topic_analyzer_tpu.config import SegmentFetchConfig
        from kafka_topic_analyzer_tpu.io.objstore import (
            RetryingHttp,
            SegmentCache,
        )

        fetch = fetch if fetch is not None else SegmentFetchConfig()
        self.spec = spec.rstrip("/")
        self.transport = RetryingHttp(self.spec, fetch)
        self.cache = (
            SegmentCache(fetch.cache_dir, fetch.cache_max_bytes, self.spec)
            if fetch.cache_dir
            else None
        )

    def list_refs(self, topic: str) -> List[SegmentRef]:
        pattern = topic_chunk_pattern(topic)
        refs = []
        for name, size in sorted(self.transport.list_objects(f"{topic}-")):
            m = pattern.match(name)
            if not m:
                continue
            refs.append(
                SegmentRef(name=name, partition=int(m.group(1)), size=size)
            )
        return refs

    def open(self, ref: SegmentRef) -> "SegmentFile":
        from kafka_topic_analyzer_tpu.io.segfile import (
            FLAG_OFFSETS,
            HEADER_SIZE,
            RemoteSegmentFile,
            parse_segment_header,
        )

        path = self.transport.object_path(ref.name)
        # Catalog probes deliberately carry NO partition: a store that is
        # unreachable at SETUP time fails the scan cleanly (after the
        # same attempt budget) rather than silently dropping partitions
        # from the catalog — the degraded surface only covers partitions
        # the scan actually admitted (body fetches, during batches()).
        whole = None
        if self.transport.range_ignored:
            # This server answers every ranged GET with the full object
            # (latched on first detection): issue ONE whole-object GET
            # per chunk and slice the header/tail probes locally, instead
            # of downloading the full object once per probe.  The cache
            # absorbs the cost entirely when enabled — consulted before
            # the GET (a warm catalog open downloads nothing) and seeded
            # after it (the body fetch later is a verified hit, so the
            # chunk crosses the wire once per scan, not twice).
            if self.cache is not None:
                whole = self.cache.get(ref.name, ref.size)
            if whole is None:
                whole = self.transport.get(
                    path, kind="header", expect=ref.size
                )
                if self.cache is not None:
                    self.cache.put(ref.name, ref.size, whole)
            # bytes(), not a view: a cache hit is a memmap and the header
            # is stored for bytes-equality checks downstream.
            header = bytes(whole[: min(HEADER_SIZE, ref.size)])
        else:
            header = self.transport.get(
                path,
                rng=(0, HEADER_SIZE - 1),
                kind="header",
                expect=min(HEADER_SIZE, ref.size),
            )
        _p, flags, _start, count = parse_segment_header(
            header, f"{self.spec}/{ref.name}"
        )
        end_offset = None
        if flags & FLAG_OFFSETS and count > 0:
            # Gappy chunk: the offset-exact end watermark is the LAST
            # offsets entry — an 8-byte suffix probe, not a body download.
            if whole is not None:
                tail = bytes(whole[ref.size - 8 : ref.size])
            else:
                tail = self.transport.get(
                    path,
                    rng=(ref.size - 8, ref.size - 1),
                    kind="header",
                    expect=8,
                )
            end_offset = struct.unpack("<q", tail)[0] + 1

        def fetch_body(validate):
            return self.fetch_chunk(ref, validate)

        return RemoteSegmentFile(
            fetch_body, ref.name, self.spec, ref.size, header, end_offset
        )

    def open_all(self, refs: List[SegmentRef]) -> "List[SegmentFile]":
        """Open many refs with their header probes in flight concurrently
        (order-preserving).  An archived year is tens of thousands of
        chunks; serial round-trips would put a wire RTT in front of every
        one before the scan even starts.  The probes run as DEMAND
        requests on the process-wide fetch scheduler — the catalog no
        longer brings its own pool, so its burst shares (and is bounded
        by) the same ``--fetch-concurrency`` admission as every other
        remote byte."""
        if len(refs) <= 1:
            return [self.open(r) for r in refs]
        from kafka_topic_analyzer_tpu.io.fetchsched import get_scheduler

        return get_scheduler().run_all(
            [lambda r=r: self.open(r) for r in refs]
        )

    def fetch_chunk(self, ref: SegmentRef, validate):
        """One whole verified chunk body (RemoteSegmentFile.ensure_body's
        acquisition path): cache hit (sha256-checked once per process,
        then latched; served as a zero-copy memmap view) → else a
        budget-retried GET (bytes), classified by ``validate`` with one
        disambiguating re-fetch, then written back to the cache."""
        from kafka_topic_analyzer_tpu.io.segfile import CorruptSegmentError

        if self.cache is not None:
            data = self.cache.get(ref.name, ref.size)
            if data is not None:
                try:
                    validate(data)
                    return data
                except CorruptSegmentError:
                    # The entry matches its OWN sha256 sidecar (so it is
                    # not rot) but no longer matches what the catalog
                    # validated — the archive was re-dumped at the same
                    # name and size.  A stale entry is a miss, never an
                    # abort: evict, book, fetch fresh.
                    from kafka_topic_analyzer_tpu.io.objstore import (
                        _book_fallback,
                    )

                    self.cache.evict(ref.name, ref.size)
                    _book_fallback("cache-stale")
        path = self.transport.object_path(ref.name)
        data = self.transport.get(
            path, kind="body", partition=ref.partition, expect=ref.size
        )
        try:
            validate(data)
        except CorruptSegmentError:
            # Structural classification failed.  The MD5/ETag check (when
            # the server sends one) already retried in-flight damage, but
            # not every endpoint ETags — ONE ranged re-fetch disambiguates:
            # identical bytes fail identically (at-rest corruption, the
            # classified error propagates); different bytes mean the first
            # copy was damaged in flight.  Mirrors io/kafka_wire.py's
            # one-re-fetch rule for suspect frames.
            obs_metrics.CORRUPT_REFETCHES.inc()
            data = self.transport.get(
                path, kind="refetch", partition=ref.partition,
                expect=ref.size,
            )
            validate(data)
        if self.cache is not None:
            self.cache.put(ref.name, ref.size, data)
        return data

    def describe(self) -> str:
        return self.spec


#: Schemes `open_segment_store` routes (a plain path means file://).
SUPPORTED_STORE_SCHEMES = ("file", "http", "https", "s3")


def open_segment_store(spec: str, fetch=None) -> SegmentStore:
    """Store factory for ``--segment-dir``: a plain path or ``file://``
    spec is a local directory; ``http(s)://host[:port]/bucket[/prefix]``
    and ``s3://bucket[/prefix]`` open the remote tier (`ObjectSegmentStore`
    — DESIGN.md §21).  ``fetch`` (config.SegmentFetchConfig) carries the
    read-ahead/cache/retry knobs; unknown schemes are rejected with the
    supported list and the plug-in seam named."""
    m = re.match(r"^([a-z][a-z0-9+.-]*)://", spec)
    scheme = m.group(1) if m else None
    if scheme in ("http", "https", "s3"):
        return ObjectSegmentStore(spec, fetch=fetch)
    if m and scheme != "file":
        supported = ", ".join(
            f"{s}://" for s in SUPPORTED_STORE_SCHEMES
        )
        raise ValueError(
            f"segment store scheme {scheme!r} is not supported "
            f"(supported: a plain directory path, {supported}); "
            "io/segstore.py SegmentStore is the plug-in seam for more"
        )
    if fetch is not None and fetch.cache_dir:
        raise ValueError(
            "--segment-cache only applies to remote segment stores "
            "(http://, https://, s3:// specs) — a local directory IS "
            "the cache"
        )
    path = spec[len("file://"):] if m else spec
    if not os.path.isdir(path):
        raise ValueError(f"segment store {spec!r} is not a directory")
    return DirectorySegmentStore(path)


class SegmentCatalog:
    """One topic's validated chunk layout in a store.

    Opens every chunk (header + column map; the local store mmaps lazily —
    pages fault in only as batches read them), cross-checks the header's
    partition against the filename, orders each partition's chunks by
    start offset, and rejects overlapping chunks (stale files from an
    older dump would silently merge old and new records).
    """

    def __init__(self, store: SegmentStore, topic: str):
        from kafka_topic_analyzer_tpu.io.segfile import MalformedSegmentError

        self.store = store
        self.topic = topic
        self.segments: "Dict[int, List[SegmentFile]]" = {}
        self.num_files = 0
        self.total_bytes = 0
        refs = store.list_refs(topic)
        # Remote stores open refs concurrently (ObjectSegmentStore.open_all
        # — a header round-trip per chunk must not serialize over an
        # archived year's chunk count); order is preserved either way.
        opener = getattr(store, "open_all", None)
        segs = opener(refs) if opener is not None else [
            store.open(r) for r in refs
        ]
        for ref, seg in zip(refs, segs):
            if seg.partition != ref.partition:
                raise MalformedSegmentError(
                    f"{ref.name}: header partition {seg.partition} does "
                    f"not match filename",
                    path=ref.name,
                    partition=ref.partition,
                )
            self.segments.setdefault(seg.partition, []).append(seg)
            self.num_files += 1
            self.total_bytes += ref.size
        for p, chunks in self.segments.items():
            chunks.sort(key=lambda s: s.start_offset)
            for prev, nxt in zip(chunks, chunks[1:]):
                if nxt.start_offset < prev.end_offset:
                    raise MalformedSegmentError(
                        f"overlapping segment chunks for partition {p}: "
                        f"{os.path.basename(prev.path)} ends at "
                        f"{prev.end_offset} but "
                        f"{os.path.basename(nxt.path)} starts at "
                        f"{nxt.start_offset} — stale chunks from an older "
                        "dump?",
                        path=os.path.basename(nxt.path),
                        partition=p,
                    )
        obs_metrics.SEGMENT_FILES_OPENED.inc(self.num_files)
        obs_metrics.SEGMENT_BYTES_MAPPED.inc(self.total_bytes)

    def partitions(self) -> List[int]:
        return sorted(self.segments)

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        start = {p: c[0].start_offset for p, c in self.segments.items()}
        end = {p: c[-1].end_offset for p, c in self.segments.items()}
        return start, end

    def record_counts(self) -> Dict[int, int]:
        """Per-partition retained record counts — known exactly up front
        (unlike a live topic), so the parallel cold path can balance its
        workers by records instead of partition count."""
        return {
            p: sum(s.count for s in chunks)
            for p, chunks in self.segments.items()
        }
