"""Record sources (ingestion layer).

The reference's ingestion is a single librdkafka consumer polled one message
at a time (src/kafka.rs:74-137).  Here ingestion is a `RecordSource` that
yields pre-extracted `RecordBatch`es:

- `SyntheticSource` — deterministic counter-based workload generator
  (numpy, mirrored bit-for-bit by the native C++ shim);
- `SegmentFileSource` — reads the on-disk segment dump format;
- `KafkaWireSource` — speaks the Kafka wire protocol directly.
"""

from kafka_topic_analyzer_tpu.io.source import RecordSource  # noqa: F401
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec  # noqa: F401
