"""Transport-failure recovery primitives for the live Kafka scan.

The wire client (io/kafka_wire.py) retries *protocol*-level fetch errors,
but a broker restart, connection reset, or truncated response used to
abort the whole scan and discard every accumulated sketch.  This module
holds the pure, clock-injectable pieces of the recovery substrate:

- `Backoff`: capped exponential delay with jitter (librdkafka-style
  retry.backoff.ms / reconnect.backoff.max.ms semantics), with the random
  source and sleep function injectable so the schedule unit-tests
  deterministically with no sockets and no real sleeping;
- `PartitionRetryBudget`: per-partition consecutive-transport-failure
  accounting with the degraded transition — a partition that exhausts its
  budget is *dropped from the scan and reported*, never raised on, so the
  remaining partitions still finish (graceful degradation).

Both are driven by `KafkaWireSource._batches_impl`; neither touches a
socket.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, Optional

from kafka_topic_analyzer_tpu.config import TransportRetryConfig
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics


class Backoff:
    """Capped exponential backoff: attempt k (1-based) sleeps

        min(backoff_max_ms, backoff_ms * 2**(k-1)) * U[1-jitter, 1+jitter]

    with the jittered value re-capped at backoff_max_ms so the configured
    ceiling is a hard bound.  ``rand`` (uniform [0,1) source) and ``sleep``
    are injectable for deterministic tests.
    """

    def __init__(
        self,
        config: TransportRetryConfig,
        rand: Optional[Callable[[], float]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.config = config
        self._rand = rand if rand is not None else random.random
        self._sleep = sleep

    def delay_ms(self, attempt: int) -> float:
        """Jittered delay for the given 1-based consecutive-failure count."""
        if attempt < 1:
            return 0.0
        c = self.config
        # Cap the exponent before shifting: attempt counts are unbounded
        # (a partition past its budget stops retrying, but the scan-level
        # round counter is not) and 2**k must not overflow into bignums.
        base = min(c.backoff_max_ms, c.backoff_ms * (1 << min(attempt - 1, 32)))
        jittered = base * (1.0 - c.jitter + 2.0 * c.jitter * self._rand())
        return min(float(c.backoff_max_ms), jittered)

    def sleep_for(self, attempt: int) -> float:
        """Sleep the schedule's delay for ``attempt``; returns seconds slept."""
        s = self.delay_ms(attempt) / 1000.0
        if s > 0:
            note_backoff_sleep(s)
            self._sleep(s)
        return s


def note_backoff_sleep(seconds: float) -> None:
    """Book a backoff sleep in the telemetry counters — shared by
    ``Backoff.sleep_for`` and the wire client's deferred-leader sleeps
    (which pace to a deadline rather than calling ``sleep_for``)."""
    obs_metrics.BACKOFF_SLEEPS.inc()
    obs_metrics.BACKOFF_SLEEP_SECONDS.inc(seconds)


class PartitionRetryBudget:
    """Consecutive-transport-failure counter per partition.

    ``record_failure`` returns True exactly once — on the failure that
    exhausts the partition's budget — at which point the caller removes the
    partition from the scan and records it in its degraded set.  Any
    successfully-read response covering the partition resets its count
    (the budget bounds *consecutive* failures, mirroring the protocol-level
    ``error_streak``).
    """

    def __init__(self, budget: int):
        if budget < 1:
            raise ValueError("retry budget must be >= 1")
        self.budget = budget
        self.failures: Dict[int, int] = {}
        #: partition -> reason string for every degraded transition.
        self.degraded: Dict[int, str] = {}

    def record_failure(self, partition: int, reason: str) -> bool:
        if partition in self.degraded:
            return False
        n = self.failures.get(partition, 0) + 1
        self.failures[partition] = n
        if n >= self.budget:
            self.degraded[partition] = (
                f"{n} consecutive transport failures (last: {reason})"
            )
            obs_metrics.RETRY_BUDGET_EXHAUSTIONS.inc()
            obs_events.emit(
                "retry_budget_exhausted",
                partition=partition,
                reason=self.degraded[partition],
            )
            return True
        return False

    def record_success(self, partition: int) -> None:
        self.failures.pop(partition, None)
