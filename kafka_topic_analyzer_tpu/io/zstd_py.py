"""Pure-Python zstd (RFC 8878) frame decoder.

Fallback for `io/compression.py::zstd_decompress` when libzstd isn't
loadable.  librdkafka gives the reference zstd support for free
(/root/reference/Cargo.toml:19 — rdkafka statically links the full C
client); this build's fast path is ctypes-on-libzstd, and this module keeps
the wire client correct without it — same split as the snappy/LZ4 decoders.

Scope: single/multi-frame streams, skippable frames, raw/RLE/compressed
blocks, Huffman literals (direct + FSE-compressed weights, 1- and 4-stream),
FSE sequences (predefined/RLE/compressed/repeat modes), repeat offsets.
Dictionaries are rejected (Kafka record batches never use them).  Content
checksums are skipped, not verified (byte-identical behavior to librdkafka's
default ZSTD_d_ignoreChecksum=0?  No — libzstd verifies; a mismatch there
raises too, via the native path).

Like the sibling decoders, every malformed input must raise ValueError —
fuzzed by tests/test_zstd.py over random garbage and truncations.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

ZSTD_MAGIC = 0xFD2FB528
SKIPPABLE_MAGIC_MIN = 0x184D2A50
SKIPPABLE_MAGIC_MAX = 0x184D2A5F

#: Hard output bound, mirrored from compression.MAX_DECOMPRESSED at call
#: time (passed in) — documented here for readers.
_BLOCK_MAX = 128 * 1024


class CorruptZstdStream(ValueError):
    """Classified malformed-zstd error: every decode failure in this
    module raises this (never a bare ValueError/struct.error/IndexError),
    so the codec layer can map it onto the corruption taxonomy
    (io/kafka_codec.py ``BadCompressionError``) while callers written
    against the historical ValueError contract keep working."""


_Err = CorruptZstdStream  # short internal alias (raised ~60x below)


# ---------------------------------------------------------------------------
# bitstreams


class _BackBits:
    """zstd backward bitstream: bytes are a little-endian integer; the
    highest set bit of the final byte is a sentinel; bits are read from
    just below it, downward.  Reads past the start yield zero bits (the
    spec's defined behavior near stream end); `pos` going far negative
    means corrupt input."""

    __slots__ = ("val", "pos")

    def __init__(self, data: bytes):
        if not data or data[-1] == 0:
            raise _Err("zstd: backward bitstream missing sentinel")
        self.val = int.from_bytes(data, "little")
        self.pos = 8 * len(data) - 8 + data[-1].bit_length() - 1

    def read(self, n: int) -> int:
        if n == 0:
            return 0
        self.pos -= n
        if self.pos >= 0:
            return (self.val >> self.pos) & ((1 << n) - 1)
        return (self.val << -self.pos) & ((1 << n) - 1)

    def peek(self, n: int) -> int:
        p = self.pos - n
        if p >= 0:
            return (self.val >> p) & ((1 << n) - 1)
        return (self.val << -p) & ((1 << n) - 1)


class _FwdBits:
    """Forward little-endian bitstream (FSE table descriptions)."""

    __slots__ = ("data", "bitpos")

    def __init__(self, data: bytes):
        self.data = data
        self.bitpos = 0

    def read(self, n: int) -> int:
        end = self.bitpos + n
        if end > 8 * len(self.data):
            raise _Err("zstd: FSE description overruns its stream")
        lo_byte = self.bitpos >> 3
        hi_byte = (end + 7) >> 3
        chunk = int.from_bytes(self.data[lo_byte:hi_byte], "little")
        out = (chunk >> (self.bitpos & 7)) & ((1 << n) - 1)
        self.bitpos = end
        return out

    def bytes_consumed(self) -> int:
        return (self.bitpos + 7) >> 3


# ---------------------------------------------------------------------------
# FSE


def _read_fse_distribution(
    data: bytes, max_accuracy: int, max_symbol: int
) -> Tuple[List[int], int, int]:
    """FSE_readNCount: (probabilities, accuracy_log, bytes_consumed).
    Probabilities may include -1 ("less than one")."""
    br = _FwdBits(data)
    accuracy_log = br.read(4) + 5
    if accuracy_log > max_accuracy:
        raise _Err(f"zstd: FSE accuracy {accuracy_log} > max {max_accuracy}")
    remaining = (1 << accuracy_log) + 1
    threshold = 1 << accuracy_log
    nbits = accuracy_log + 1
    probs: List[int] = []
    previous0 = False
    while remaining > 1:
        if len(probs) > max_symbol:
            raise _Err("zstd: FSE distribution has too many symbols")
        if previous0:
            while True:
                rep = br.read(2)
                probs.extend([0] * rep)
                if rep < 3:
                    break
            previous0 = False
            continue
        maxv = 2 * threshold - 1 - remaining
        v = br.read(nbits - 1)
        if v < maxv:
            count = v  # small value: fits in nbits-1 bits
        else:
            v |= br.read(1) << (nbits - 1)
            count = v if v < threshold else v - maxv
        count -= 1  # encoded +1; -1 means "less than one"
        remaining -= -count if count < 0 else count
        if remaining < 0:
            raise _Err("zstd: FSE distribution exceeds table size")
        probs.append(count)
        previous0 = count == 0
        while remaining < threshold and threshold > 1:
            nbits -= 1
            threshold >>= 1
    if len(probs) > max_symbol + 1:
        raise _Err("zstd: FSE distribution has too many symbols")
    return probs, accuracy_log, br.bytes_consumed()


def _build_fse_table(
    probs: List[int], accuracy_log: int
) -> Tuple[List[int], List[int], List[int]]:
    """FSE decode table → (symbol, nb_bits, new_state_base) per state."""
    size = 1 << accuracy_log
    symbols = [0] * size
    high = size - 1
    for s, p in enumerate(probs):
        if p == -1:
            symbols[high] = s
            high -= 1
    step = (size >> 1) + (size >> 3) + 3
    mask = size - 1
    pos = 0
    for s, p in enumerate(probs):
        if p <= 0:
            continue
        for _ in range(p):
            symbols[pos] = s
            pos = (pos + step) & mask
            while pos > high:
                pos = (pos + step) & mask
    if pos != 0:
        raise _Err("zstd: corrupt FSE distribution (spread mismatch)")
    occur = [1 if p == -1 else max(p, 0) for p in probs]
    nb_bits = [0] * size
    new_state = [0] * size
    for u in range(size):
        s = symbols[u]
        x = occur[s]
        occur[s] = x + 1
        nb = accuracy_log - (x.bit_length() - 1)
        nb_bits[u] = nb
        new_state[u] = (x << nb) - size
    return symbols, nb_bits, new_state


class _FseDecoder:
    """One interactive FSE state machine over a backward bitstream."""

    __slots__ = ("symbols", "nb_bits", "new_state", "accuracy_log", "state")

    def __init__(self, probs: List[int], accuracy_log: int):
        self.symbols, self.nb_bits, self.new_state = _build_fse_table(
            probs, accuracy_log
        )
        self.accuracy_log = accuracy_log
        self.state = 0

    def init_state(self, br: _BackBits) -> None:
        self.state = br.read(self.accuracy_log)

    def symbol(self) -> int:
        return self.symbols[self.state]

    def update(self, br: _BackBits) -> None:
        self.state = self.new_state[self.state] + br.read(
            self.nb_bits[self.state]
        )


class _RleDecoder:
    """Degenerate one-symbol 'FSE' table (Symbol_Compression_Mode 1)."""

    __slots__ = ("sym", "accuracy_log")

    def __init__(self, sym: int):
        self.sym = sym
        self.accuracy_log = 0

    def init_state(self, br: _BackBits) -> None:
        pass

    def symbol(self) -> int:
        return self.sym

    def update(self, br: _BackBits) -> None:
        pass


# ---------------------------------------------------------------------------
# Huffman


def _huffman_weights_fse(data: bytes) -> List[int]:
    """Weights compressed with FSE (header byte < 128): two interleaved
    states decode until the backward bitstream is exhausted."""
    probs, al, consumed = _read_fse_distribution(data, 6, 255)
    table = _build_fse_table(probs, al)
    symbols, nb_bits, new_state = table
    br = _BackBits(data[consumed:])
    s1 = br.read(al)
    s2 = br.read(al)
    weights: List[int] = []
    # Two states take turns; when a state's update exhausts the bitstream,
    # the OTHER state emits its final symbol and decoding stops.
    while True:
        if len(weights) > 255:
            raise _Err("zstd: too many Huffman weights")
        weights.append(symbols[s1])
        s1 = new_state[s1] + br.read(nb_bits[s1])
        if br.pos < 0:
            weights.append(symbols[s2])
            break
        weights.append(symbols[s2])
        s2 = new_state[s2] + br.read(nb_bits[s2])
        if br.pos < 0:
            weights.append(symbols[s1])
            break
    return weights


def _huffman_table(data: bytes) -> Tuple[List[Tuple[int, int]], int, int]:
    """Parse a Huffman tree description.  Returns (decode_table, max_bits,
    bytes_consumed) where decode_table[prefix] = (symbol, code_bits)."""
    if not data:
        raise _Err("zstd: empty Huffman description")
    hb = data[0]
    if hb >= 128:
        n = hb - 127
        nbytes = (n + 1) // 2
        if 1 + nbytes > len(data):
            raise _Err("zstd: truncated Huffman weights")
        weights = []
        for i in range(n):
            b = data[1 + i // 2]
            weights.append((b >> 4) if i % 2 == 0 else (b & 0xF))
        consumed = 1 + nbytes
    else:
        if 1 + hb > len(data):
            raise _Err("zstd: truncated Huffman FSE weights")
        weights = _huffman_weights_fse(data[1 : 1 + hb])
        consumed = 1 + hb
    # Last weight is implied so the code space sums to a power of two
    # (smallest 2^max_bits strictly greater than the partial sum).
    total = sum((1 << (w - 1)) for w in weights if w > 0)
    if total == 0:
        raise _Err("zstd: Huffman weights empty")
    max_bits = total.bit_length()
    if max_bits > 11:  # zstd's Huffman code length limit
        raise _Err("zstd: Huffman max bits exceeds 11")
    rest = (1 << max_bits) - total
    if rest <= 0 or rest & (rest - 1):
        raise _Err("zstd: Huffman weights do not sum to a power of two")
    weights.append(rest.bit_length())  # 2^(w-1) = rest
    # Prefix table: ascending weight (longest codes first), symbols in
    # natural order within a weight.
    table: List[Tuple[int, int]] = [(0, 0)] * (1 << max_bits)
    cur = 0
    for w in range(1, max_bits + 1):
        for sym, sw in enumerate(weights):
            if sw != w:
                continue
            bits = max_bits + 1 - w
            span = 1 << (w - 1)
            if cur + span > len(table):
                raise _Err("zstd: Huffman code space overflow")
            for i in range(cur, cur + span):
                table[i] = (sym, bits)
            cur += span
    if cur != len(table):
        raise _Err("zstd: Huffman code space underfilled")
    return table, max_bits, consumed


def _huffman_decode_stream(
    data: bytes, table: List[Tuple[int, int]], max_bits: int, n: int
) -> bytearray:
    br = _BackBits(data)
    out = bytearray()
    while len(out) < n:
        sym, bits = table[br.peek(max_bits)]
        br.pos -= bits
        if br.pos < -max_bits:
            raise _Err("zstd: Huffman stream overrun")
        out.append(sym)
    return out


# ---------------------------------------------------------------------------
# sequences: code → (baseline, extra_bits)

_LL_BASE = (
    [(i, 0) for i in range(16)]
    + [(16, 1), (18, 1), (20, 1), (22, 1), (24, 2), (28, 2), (32, 3),
       (40, 3), (48, 4), (64, 6), (128, 7), (256, 8), (512, 9), (1024, 10),
       (2048, 11), (4096, 12), (8192, 13), (16384, 14), (32768, 15),
       (65536, 16)]
)
_ML_BASE = (
    [(i + 3, 0) for i in range(32)]
    + [(35, 1), (37, 1), (39, 1), (41, 1), (43, 2), (47, 2), (51, 3),
       (59, 3), (67, 4), (83, 4), (99, 5), (131, 7), (259, 8), (515, 9),
       (1027, 10), (2051, 11), (4099, 12), (8195, 13), (16387, 14),
       (32771, 15), (65539, 16)]
)

_LL_DEFAULT = (
    [4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 1, 1, 1, 2, 2, 2, 2, 2, 2,
     2, 2, 2, 3, 2, 1, 1, 1, 1, 1, -1, -1, -1, -1],
    6,
)
_ML_DEFAULT = (
    [1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
     1, 1, -1, -1, -1, -1, -1, -1, -1],
    6,
)
_OF_DEFAULT = (
    [1, 1, 1, 1, 1, 1, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
     1, 1, -1, -1, -1, -1, -1],
    5,
)

_MAX_ACCURACY = {"ll": 9, "of": 8, "ml": 9}
_MAX_SYMBOL = {"ll": 35, "of": 31, "ml": 52}
_DEFAULTS = {"ll": _LL_DEFAULT, "of": _OF_DEFAULT, "ml": _ML_DEFAULT}

for _name, (_probs, _al) in _DEFAULTS.items():
    assert sum(1 if p == -1 else p for p in _probs) == 1 << _al, _name


def _sequence_decoder(
    kind: str, mode: int, data: bytes, prev, out_consumed: List[int]
):
    """Build the LL/OF/ML decoder for one block per its compression mode.
    Appends bytes consumed from `data` to out_consumed."""
    if mode == 0:  # predefined
        probs, al = _DEFAULTS[kind]
        out_consumed.append(0)
        return _FseDecoder(probs, al)
    if mode == 1:  # RLE
        if not data:
            raise _Err("zstd: missing RLE symbol byte")
        out_consumed.append(1)
        sym = data[0]
        if sym > _MAX_SYMBOL[kind]:
            raise _Err(f"zstd: RLE {kind} symbol {sym} out of range")
        return _RleDecoder(sym)
    if mode == 2:  # FSE-compressed distribution
        probs, al, used = _read_fse_distribution(
            data, _MAX_ACCURACY[kind], _MAX_SYMBOL[kind]
        )
        out_consumed.append(used)
        return _FseDecoder(probs, al)
    if prev is None:  # mode 3: repeat
        raise _Err(f"zstd: repeat {kind} table with no previous table")
    out_consumed.append(0)
    return prev


# ---------------------------------------------------------------------------
# block + frame decode


class _FrameCtx:
    """State carried across blocks within a frame: the previous Huffman
    table (treeless literals) and previous FSE tables (repeat mode), plus
    the rolling repeat offsets."""

    def __init__(self):
        self.huffman: "Optional[Tuple[List[Tuple[int, int]], int]]" = None
        self.fse = {"ll": None, "of": None, "ml": None}
        self.rep = [1, 4, 8]


def _decode_literals(data: bytes, ctx: _FrameCtx) -> Tuple[bytearray, int]:
    if not data:
        raise _Err("zstd: empty literals section")
    b0 = data[0]
    lb_type = b0 & 3
    size_format = (b0 >> 2) & 3
    if lb_type <= 1:  # Raw / RLE
        if size_format in (0, 2):
            rs, hdr = b0 >> 3, 1
        elif size_format == 1:
            if len(data) < 2:
                raise _Err("zstd: truncated literals header")
            rs, hdr = (b0 >> 4) | (data[1] << 4), 2
        else:
            if len(data) < 3:
                raise _Err("zstd: truncated literals header")
            rs, hdr = (b0 >> 4) | (data[1] << 4) | (data[2] << 12), 3
        if lb_type == 0:
            if hdr + rs > len(data):
                raise _Err("zstd: truncated raw literals")
            return bytearray(data[hdr : hdr + rs]), hdr + rs
        if hdr + 1 > len(data):
            raise _Err("zstd: truncated RLE literals")
        return bytearray(data[hdr : hdr + 1] * rs), hdr + 1
    # Compressed (2) / Treeless (3)
    if size_format == 0:
        streams, sbits, hdr = 1, 10, 3
    elif size_format == 1:
        streams, sbits, hdr = 4, 10, 3
    elif size_format == 2:
        streams, sbits, hdr = 4, 14, 4
    else:
        streams, sbits, hdr = 4, 18, 5
    if len(data) < hdr:
        raise _Err("zstd: truncated literals header")
    v = int.from_bytes(data[:hdr], "little") >> 4
    rs = v & ((1 << sbits) - 1)
    cs = (v >> sbits) & ((1 << sbits) - 1)
    if hdr + cs > len(data):
        raise _Err("zstd: truncated compressed literals")
    payload = data[hdr : hdr + cs]
    if lb_type == 2:
        table, max_bits, used = _huffman_table(payload)
        ctx.huffman = (table, max_bits)
        payload = payload[used:]
    else:
        if ctx.huffman is None:
            raise _Err("zstd: treeless literals with no previous table")
        table, max_bits = ctx.huffman
    if rs > _BLOCK_MAX:
        raise _Err("zstd: literals exceed block maximum")
    if streams == 1:
        return _huffman_decode_stream(payload, table, max_bits, rs), hdr + cs
    if len(payload) < 6:
        raise _Err("zstd: truncated 4-stream jump table")
    s1, s2, s3 = struct.unpack_from("<HHH", payload, 0)
    body = payload[6:]
    if s1 + s2 + s3 > len(body):
        raise _Err("zstd: 4-stream sizes exceed payload")
    per = (rs + 3) // 4
    sizes = [per, per, per, rs - 3 * per]
    if sizes[3] < 0:
        raise _Err("zstd: negative fourth-stream size")
    chunks = [
        body[:s1],
        body[s1 : s1 + s2],
        body[s1 + s2 : s1 + s2 + s3],
        body[s1 + s2 + s3 :],
    ]
    out = bytearray()
    for chunk, n in zip(chunks, sizes):
        out += _huffman_decode_stream(chunk, table, max_bits, n)
    return out, hdr + cs


def _decode_block(
    data: bytes, ctx: _FrameCtx, out: bytearray, cap: int, frame_start: int
) -> None:
    literals, used = _decode_literals(data, ctx)
    data = data[used:]
    if not data:
        raise _Err("zstd: missing sequences section")
    b0 = data[0]
    if b0 < 128:
        nseq, hdr = b0, 1
    elif b0 < 255:
        if len(data) < 2:
            raise _Err("zstd: truncated sequence count")
        nseq, hdr = ((b0 - 128) << 8) + data[1], 2
    else:
        if len(data) < 3:
            raise _Err("zstd: truncated sequence count")
        nseq, hdr = data[1] + (data[2] << 8) + 0x7F00, 3
    data = data[hdr:]
    if nseq == 0:
        if len(out) + len(literals) > cap:
            raise _Err("zstd: output exceeds cap")
        out += literals
        return
    if not data:
        raise _Err("zstd: missing symbol compression modes")
    modes = data[0]
    if modes & 3:
        raise _Err("zstd: reserved sequence mode bits set")
    data = data[1:]
    consumed: List[int] = []
    ll = _sequence_decoder("ll", (modes >> 6) & 3, data, ctx.fse["ll"], consumed)
    data = data[consumed[-1] :]
    of = _sequence_decoder("of", (modes >> 4) & 3, data, ctx.fse["of"], consumed)
    data = data[consumed[-1] :]
    ml = _sequence_decoder("ml", (modes >> 2) & 3, data, ctx.fse["ml"], consumed)
    data = data[consumed[-1] :]
    ctx.fse.update(ll=ll, of=of, ml=ml)

    br = _BackBits(data)
    ll.init_state(br)
    of.init_state(br)
    ml.init_state(br)
    lit_pos = 0
    rep = ctx.rep
    for i in range(nseq):
        of_code = of.symbol()
        if of_code > 31:
            raise _Err("zstd: offset code out of range")
        of_value = (1 << of_code) + br.read(of_code)
        ml_base, ml_bits = _ML_BASE[ml.symbol()]
        match_len = ml_base + br.read(ml_bits)
        ll_base, ll_bits = _LL_BASE[ll.symbol()]
        lit_len = ll_base + br.read(ll_bits)
        if i + 1 < nseq:
            ll.update(br)
            ml.update(br)
            of.update(br)
        # Repeat-offset resolution (RFC 8878 §3.1.1.5).
        if of_value > 3:
            offset = of_value - 3
            rep[2] = rep[1]
            rep[1] = rep[0]
            rep[0] = offset
        else:
            idx = of_value - 1 + (1 if lit_len == 0 else 0)
            if idx == 0:
                offset = rep[0]
            elif idx == 1:
                offset = rep[1]
                rep[1] = rep[0]
                rep[0] = offset
            elif idx == 2:
                offset = rep[2]
                rep[2] = rep[1]
                rep[1] = rep[0]
                rep[0] = offset
            else:
                offset = rep[0] - 1
                if offset == 0:
                    raise _Err("zstd: zero repeat offset")
                rep[2] = rep[1]
                rep[1] = rep[0]
                rep[0] = offset
        if lit_pos + lit_len > len(literals):
            raise _Err("zstd: sequence literals overrun")
        if len(out) + lit_len + match_len > cap:
            raise _Err("zstd: output exceeds cap")
        out += literals[lit_pos : lit_pos + lit_len]
        lit_pos += lit_len
        if offset > len(out) - frame_start:
            # Frames are independent: a match may not reach into output
            # produced by a previous frame (libzstd rejects this too).
            raise _Err("zstd: match offset beyond frame start")
        if offset >= match_len:  # non-overlapping fast path
            start = len(out) - offset
            out += out[start : start + match_len]
        else:
            for _ in range(match_len):
                out.append(out[-offset])
    if br.pos < -8:
        raise _Err("zstd: sequence bitstream overrun")
    if len(out) + len(literals) - lit_pos > cap:
        raise _Err("zstd: output exceeds cap")
    out += literals[lit_pos:]


def decompress(data: bytes, cap: int) -> bytes:
    """Decode a (possibly multi-frame) zstd stream, bounding output at
    `cap` bytes.  Raises ValueError on any malformed input."""
    out = bytearray()
    pos = 0
    n = len(data)
    if n < 4:
        raise _Err("zstd: input shorter than a frame header")
    while pos < n:
        if pos + 4 > n:
            raise _Err("zstd: trailing garbage after frame")
        (magic,) = struct.unpack_from("<I", data, pos)
        pos += 4
        if SKIPPABLE_MAGIC_MIN <= magic <= SKIPPABLE_MAGIC_MAX:
            if pos + 4 > n:
                raise _Err("zstd: truncated skippable frame")
            (size,) = struct.unpack_from("<I", data, pos)
            pos += 4 + size
            if pos > n:
                raise _Err("zstd: truncated skippable frame")
            continue
        if magic != ZSTD_MAGIC:
            raise _Err(f"zstd: bad magic 0x{magic:08x}")
        if pos >= n:
            raise _Err("zstd: missing frame header descriptor")
        fhd = data[pos]
        pos += 1
        fcs_flag = fhd >> 6
        single_segment = (fhd >> 5) & 1
        has_checksum = (fhd >> 2) & 1
        dict_flag = fhd & 3
        if fhd & 0x08:
            raise _Err("zstd: reserved frame header bit set")
        if not single_segment:
            if pos >= n:
                raise _Err("zstd: missing window descriptor")
            pos += 1  # window size only bounds the cap, enforced directly
        if dict_flag:
            did_len = (0, 1, 2, 4)[dict_flag]
            did = int.from_bytes(data[pos : pos + did_len], "little")
            pos += did_len
            if did:
                raise _Err("zstd: dictionaries are not supported")
        fcs_len = (1 if single_segment else 0, 2, 4, 8)[fcs_flag]
        if pos + fcs_len > n:
            raise _Err("zstd: truncated frame content size")
        fcs = None
        if fcs_len:
            fcs = int.from_bytes(data[pos : pos + fcs_len], "little")
            if fcs_len == 2:
                fcs += 256
            pos += fcs_len
        if fcs is not None and len(out) + fcs > cap:
            raise _Err("zstd: declared content size exceeds cap")
        ctx = _FrameCtx()
        frame_start = len(out)
        while True:
            if pos + 3 > n:
                raise _Err("zstd: truncated block header")
            h = data[pos] | (data[pos + 1] << 8) | (data[pos + 2] << 16)
            pos += 3
            last = h & 1
            btype = (h >> 1) & 3
            bsize = h >> 3
            if btype == 0:  # raw
                if pos + bsize > n:
                    raise _Err("zstd: truncated raw block")
                if len(out) + bsize > cap:
                    raise _Err("zstd: output exceeds cap")
                out += data[pos : pos + bsize]
                pos += bsize
            elif btype == 1:  # RLE
                if pos >= n:
                    raise _Err("zstd: truncated RLE block")
                if bsize > _BLOCK_MAX or len(out) + bsize > cap:
                    raise _Err("zstd: output exceeds cap")
                out += data[pos : pos + 1] * bsize
                pos += 1
            elif btype == 2:
                if pos + bsize > n:
                    raise _Err("zstd: truncated compressed block")
                before = len(out)
                _decode_block(data[pos : pos + bsize], ctx, out, cap, frame_start)
                if len(out) - before > _BLOCK_MAX:
                    raise _Err("zstd: block exceeds 128 KiB maximum")
                pos += bsize
            else:
                raise _Err("zstd: reserved block type")
            if last:
                break
        if fcs is not None and len(out) - frame_start != fcs:
            raise _Err(
                f"zstd: frame declared {fcs} bytes, produced "
                f"{len(out) - frame_start}"
            )
        if has_checksum:
            if pos + 4 > n:
                raise _Err("zstd: truncated content checksum")
            pos += 4  # xxh64 low 32 bits — parsed, not verified
    return bytes(out)
