"""RecordSource protocol — the ingestion seam.

Mirrors the reference's topology handshake (``get_topic_offsets``,
src/kafka.rs:60-72: metadata + per-partition watermarks fixed at scan start)
followed by a full earliest→latest read, but batched: a source yields
`RecordBatch`es instead of single messages, and can be asked to restrict
itself to a subset of partitions (one data shard's slice — records.py
ordering contract).
"""

from __future__ import annotations

import abc
from typing import Dict, Iterator, List, Optional, Tuple  # noqa: F401

from kafka_topic_analyzer_tpu.records import RecordBatch


class RecordSource(abc.ABC):
    @abc.abstractmethod
    def partitions(self) -> List[int]:
        """Sorted partition ids (src/main.rs:103-106 sorts them too)."""

    @abc.abstractmethod
    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        """(start_offsets, end_offsets) snapshot — the termination contract:
        the scan covers exactly [start, end) per partition as of now
        (src/kafka.rs:60-72, :119-121)."""

    @abc.abstractmethod
    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
    ) -> Iterator[RecordBatch]:
        """Yield batches covering [start, end) for the given partitions (all
        by default), per-partition offset order, batches not padded (the
        backend pads).  ``start_at`` overrides the per-partition start
        offsets (snapshot resume, checkpoint.py); missing partitions start
        at their earliest offset."""

    def refresh_watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        """Re-poll the end watermarks and return the fresh
        (start_offsets, end_offsets) — the follow-mode tail contract
        (serve/follow.py): each poll widens the scan target to the moving
        head.  Static sources (synthetic, segment files) have nothing to
        refresh, so the default returns the fixed snapshot; the live wire
        source re-queries the brokers THROUGH its retry/backoff budget and,
        when the budget is exhausted, keeps the previous snapshot instead
        of failing the service (io/kafka_wire.py)."""
        return self.watermarks()

    def degraded_partitions(self) -> Dict[int, str]:
        """partition -> reason for partitions a scan dropped after
        exhausting their transport/protocol retry budget (graceful
        degradation; io/kafka_wire.py).  Empty for sources that cannot
        degrade (synthetic, segment files)."""
        return {}

    def corruption_stats(self) -> Dict[int, dict]:
        """partition -> corruption accounting (frames/records/bytes/kinds/
        spans) for poisoned frames the scan skipped or quarantined under
        ``--on-corruption`` (io/kafka_wire.py).  Empty for sources that
        cannot observe corruption."""
        return {}

    def corruption_spans(self) -> "list[dict]":
        """Flat JSON-safe span list for checkpoint metadata (the engine
        persists it so a --resume neither re-counts nor re-quarantines an
        already-skipped span; see ``seed_corrupt_spans`` on the wire
        source)."""
        return []

    def total_records(self) -> int:
        start, end = self.watermarks()
        return sum(end[p] - start[p] for p in end)

    def is_empty(self) -> bool:
        """True when every end offset is 0 — the reference exits ``-2``
        (src/main.rs:98-101)."""
        _, end = self.watermarks()
        return all(v == 0 for v in end.values())
