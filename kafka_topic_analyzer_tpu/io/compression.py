"""Record-batch decompression: gzip (stdlib), snappy and LZ4 (native shim
with pure-Python fallback), zstd (ctypes on system libzstd with a
pure-Python RFC 8878 fallback, io/zstd_py.py).

Kafka's snappy payloads use the xerial chunked framing; LZ4 uses the LZ4
frame format.  Python's stdlib has neither, so the fast path is the C++
shim (native/ingest.cpp); the pure-Python decoders keep the wire client
correct when the shim can't be built.

The literal-only *encoders* here exist for tests and the in-process fake
broker: a snappy/LZ4 stream consisting solely of literal runs is valid, so
round-trips exercise real framing without a compressor dependency.
"""

from __future__ import annotations

import struct
import zlib

XERIAL_MAGIC = b"\x82SNAPPY\x00"
LZ4_FRAME_MAGIC = 0x184D2204

#: Safety cap for decompressed record sets (a batch can't meaningfully
#: exceed this: brokers bound message sizes far below it).
MAX_DECOMPRESSED = 1 << 30


class UnsupportedCodecError(RuntimeError):
    pass


class CorruptPayloadError(ValueError):
    """A compressed codec stream that does not decode (truncated, bad
    framing, length mismatch, over-cap).  Subclasses ValueError so callers
    written against the decoders' historical "raise ValueError on garbage"
    contract keep working; the codec layer (io/kafka_codec.py) re-wraps it
    into the `BadCompressionError` corruption classification."""


# ---------------------------------------------------------------------------
# pure-Python decoders (fallback path)


def _total(fn):
    """Truncated streams index past the end in several places; map every
    IndexError to the same ValueError a caller can handle (fuzzed by
    tests/test_properties.py: decoders must be total over garbage)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        try:
            return fn(*a, **k)
        except IndexError as e:
            raise CorruptPayloadError("truncated compressed payload") from e

    return wrapper


def _snappy_raw_py(data: bytes) -> bytes:
    ip = 0
    ulen = 0
    shift = 0
    while ip < len(data):
        b = data[ip]
        ip += 1
        ulen |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray()
    n = len(data)
    while ip < n:
        tag = data[ip]
        ip += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(data[ip : ip + extra], "little") + 1
                ip += extra
            if ip + length > n:
                raise CorruptPayloadError("truncated snappy literal run")
            out += data[ip : ip + length]
            ip += length
        else:
            if kind == 1:
                length = ((tag >> 2) & 7) + 4
                offset = ((tag >> 5) << 8) | data[ip]
                ip += 1
            elif kind == 2:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[ip : ip + 2], "little")
                ip += 2
            else:
                length = (tag >> 2) + 1
                offset = int.from_bytes(data[ip : ip + 4], "little")
                ip += 4
            if offset <= 0 or offset > len(out):
                raise CorruptPayloadError("bad snappy copy offset")
            for _ in range(length):  # may overlap (RLE)
                out.append(out[-offset])
    if len(out) != ulen:
        raise CorruptPayloadError(f"snappy length mismatch: {len(out)} != {ulen}")
    return bytes(out)


@_total
def snappy_decompress_py(data: bytes) -> bytes:
    if data.startswith(XERIAL_MAGIC):
        ip = 16  # magic + version + compat
        out = bytearray()
        while ip + 4 <= len(data):
            (blen,) = struct.unpack(">i", data[ip : ip + 4])
            ip += 4
            # A negative/overlong block length must fail, not loop forever
            # (this decoder's totality cannot depend on callers validating
            # first).
            if blen < 0 or ip + blen > len(data):
                raise CorruptPayloadError("bad xerial block length")
            out += _snappy_raw_py(data[ip : ip + blen])
            ip += blen
        return bytes(out)
    return _snappy_raw_py(data)


def _lz4_block_py(data: bytes, out: bytearray) -> None:
    ip = 0
    n = len(data)
    while ip < n:
        token = data[ip]
        ip += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if ip >= n:
                    raise CorruptPayloadError("truncated lz4 length extension")
                b = data[ip]
                ip += 1
                lit += b
                if b != 255:
                    break
        if ip + lit > n:
            raise CorruptPayloadError("truncated lz4 literal run")
        out += data[ip : ip + lit]
        ip += lit
        if ip >= n:
            break
        offset = int.from_bytes(data[ip : ip + 2], "little")
        ip += 2
        if offset == 0 or offset > len(out):
            raise CorruptPayloadError("bad lz4 match offset")
        mlen = token & 0x0F
        if mlen == 15:
            while True:
                if ip >= n:
                    raise CorruptPayloadError("truncated lz4 length extension")
                b = data[ip]
                ip += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        if len(out) + mlen > MAX_DECOMPRESSED:
            raise CorruptPayloadError("lz4 output exceeds 1 GiB cap")
        for _ in range(mlen):
            out.append(out[-offset])


@_total
def lz4_decompress_py(data: bytes) -> bytes:
    if len(data) >= 7 and struct.unpack("<I", data[:4])[0] == LZ4_FRAME_MAGIC:
        ip = 4
        flg = data[ip]
        ip += 2  # FLG + BD
        if flg & 0x01:
            raise CorruptPayloadError("lz4 dictionaries unsupported")
        if flg & 0x08:  # content size present
            ip += 8
        ip += 1  # header checksum
        out = bytearray()
        while ip + 4 <= len(data):
            (bsize,) = struct.unpack("<I", data[ip : ip + 4])
            ip += 4
            if bsize == 0:  # EndMark
                return bytes(out)
            blen = bsize & 0x7FFFFFFF
            block = data[ip : ip + blen]
            ip += blen
            if bsize & 0x80000000:
                out += block
            else:
                _lz4_block_py(block, out)
            if len(out) > MAX_DECOMPRESSED:
                raise CorruptPayloadError("lz4 output exceeds 1 GiB cap")
            if flg & 0x10:  # block checksum
                ip += 4
        raise CorruptPayloadError("lz4 frame missing EndMark")
    out = bytearray()
    _lz4_block_py(data, out)
    return bytes(out)


# ---------------------------------------------------------------------------
# native dispatch


def _read_uvarint(data: bytes, pos: int) -> "tuple[int, int]":
    val = 0
    shift = 0
    while pos < len(data):
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 35:
            break
    raise CorruptPayloadError("bad varint in compressed payload")


def _snappy_output_size(data: bytes) -> int:
    """Exact decompressed size from the stream's own length preambles."""
    if data.startswith(XERIAL_MAGIC):
        total = 0
        ip = 16
        while ip + 4 <= len(data):
            (blen,) = struct.unpack(">i", data[ip : ip + 4])
            ip += 4
            if blen < 0 or ip + blen > len(data):
                raise CorruptPayloadError("bad xerial block length")
            size, _ = _read_uvarint(data, ip)
            total += size
            ip += blen
        return total
    size, _ = _read_uvarint(data, 0)
    return size


def _lz4_output_bound(data: bytes) -> int:
    """Content size when the frame declares it, else the format's worst-case
    expansion bound (a match emits at most 255x its encoding)."""
    if len(data) >= 7 and struct.unpack("<I", data[:4])[0] == LZ4_FRAME_MAGIC:
        flg = data[4]
        if flg & 0x08:
            if len(data) < 14:
                raise CorruptPayloadError("truncated lz4 frame header")
            return struct.unpack("<Q", data[6:14])[0]
    return len(data) * 255 + 64


def _native_decompress(fn_name: str, data: bytes, cap: int) -> "bytes | None":
    """One-shot native call with an exact/bounded output size — malformed
    input returns None and the Python path raises a clear error."""
    try:
        import ctypes

        import numpy as np

        from kafka_topic_analyzer_tpu.io.native import _as_ptr, load_library, native_available

        if not native_available():
            return None
        lib = load_library()
        fn = getattr(lib, fn_name)
        fn.restype = ctypes.c_int64
        src = np.frombuffer(data, dtype=np.uint8)
        out = np.empty(cap, dtype=np.uint8)
        n = fn(
            _as_ptr(np.ascontiguousarray(src), ctypes.c_uint8),
            ctypes.c_int64(len(data)),
            _as_ptr(out, ctypes.c_uint8),
            ctypes.c_int64(cap),
        )
        if n >= 0:
            return out[:n].tobytes()
        return None
    except Exception:
        return None


def snappy_decompress(data: bytes) -> bytes:
    size = _snappy_output_size(data)  # raises on malformed preambles
    if size > MAX_DECOMPRESSED:
        raise CorruptPayloadError(f"snappy payload declares {size} bytes (> 1 GiB cap)")
    out = _native_decompress("kta_snappy_decompress", data, size)
    return out if out is not None else snappy_decompress_py(data)


def lz4_decompress(data: bytes) -> bytes:
    # Kafka's Java client omits the frame content size, so the only a-priori
    # bound is the 255x worst case — far too big to allocate per batch.
    # Grow on demand instead: -1 from the native decoder means either a
    # short buffer or malformed input, so after reaching the bound the
    # strict Python decoder delivers the verdict (raises on malformed).
    bound = min(_lz4_output_bound(data), MAX_DECOMPRESSED)
    cap = min(max(len(data) * 8, 1 << 20), bound)
    while True:
        out = _native_decompress("kta_lz4_decompress", data, cap)
        if out is not None:
            return out
        if cap >= bound:
            return lz4_decompress_py(data)
        cap = min(cap * 16, bound)


def gzip_decompress(payload: bytes) -> bytes:
    """Bounded gzip/zlib inflate — same MAX_DECOMPRESSED cap the snappy and
    LZ4 paths enforce, so a corrupt or hostile batch can't balloon ~1000x
    into memory unchecked."""
    d = zlib.decompressobj(wbits=47)
    try:
        out = d.decompress(payload, MAX_DECOMPRESSED)
    except zlib.error as e:
        raise CorruptPayloadError(f"corrupt gzip stream: {e}") from e
    if d.unconsumed_tail:
        raise CorruptPayloadError(
            f"gzip batch exceeds decompressed size cap ({MAX_DECOMPRESSED} B)"
        )
    out += d.flush()
    if len(out) > MAX_DECOMPRESSED:
        raise CorruptPayloadError(
            f"gzip batch exceeds decompressed size cap ({MAX_DECOMPRESSED} B)"
        )
    # zlib.decompress raised on truncated streams; a decompressobj only
    # signals it via eof.  Trailing bytes after a complete stream stay
    # ignored (old zlib.decompress(wbits=47) behavior).
    if not d.eof:
        raise CorruptPayloadError("truncated gzip stream")
    return out


_ZSTD_CONTENTSIZE_UNKNOWN = (1 << 64) - 1
_ZSTD_CONTENTSIZE_ERROR = (1 << 64) - 2
_libzstd = "unresolved"  # tri-state: unresolved / CDLL / None


def _load_libzstd():
    """System libzstd via ctypes (the fast path; the reference gets zstd
    from librdkafka's statically-linked libzstd, Cargo.toml:19).  Returns
    None when the shared library isn't loadable — the pure-Python RFC 8878
    decoder (zstd_py.py) then carries correctness."""
    global _libzstd
    if _libzstd == "unresolved":
        try:
            import ctypes

            lib = ctypes.CDLL("libzstd.so.1")
            lib.ZSTD_isError.restype = ctypes.c_uint
            lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
            lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
            lib.ZSTD_getFrameContentSize.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.ZSTD_decompress.restype = ctypes.c_size_t
            lib.ZSTD_decompress.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t,
            ]
            lib.ZSTD_compressBound.restype = ctypes.c_size_t
            lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
            lib.ZSTD_compress.restype = ctypes.c_size_t
            lib.ZSTD_compress.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ]
            lib.ZSTD_createDCtx.restype = ctypes.c_void_p
            lib.ZSTD_freeDCtx.argtypes = [ctypes.c_void_p]
            lib.ZSTD_DCtx_reset.restype = ctypes.c_size_t
            lib.ZSTD_DCtx_reset.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.ZSTD_decompressStream.restype = ctypes.c_size_t
            lib.ZSTD_decompressStream.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            _libzstd = lib
        except Exception:
            _libzstd = None
    return _libzstd


def _zstd_stream_decompress(lib, data: bytes) -> "bytes | None":
    """ZSTD_decompressStream loop for frames without a declared content
    size (the shape stream-compressing producers emit).  Returns None on
    any libzstd error — the pure-Python decoder then delivers the verdict."""
    import ctypes

    class Buf(ctypes.Structure):
        _fields_ = [
            ("ptr", ctypes.c_void_p),
            ("size", ctypes.c_size_t),
            ("pos", ctypes.c_size_t),
        ]

    dctx = lib.ZSTD_createDCtx()
    if not dctx:
        return None
    try:
        src = ctypes.create_string_buffer(data, len(data))
        inbuf = Buf(ctypes.cast(src, ctypes.c_void_p), len(data), 0)
        chunk_size = min(max(len(data) * 4, 1 << 18), MAX_DECOMPRESSED)
        chunk = ctypes.create_string_buffer(chunk_size)
        out = bytearray()
        ret = 0
        while True:
            in_before = inbuf.pos
            outbuf = Buf(ctypes.cast(chunk, ctypes.c_void_p), chunk_size, 0)
            ret = int(lib.ZSTD_decompressStream(
                dctx, ctypes.byref(outbuf), ctypes.byref(inbuf)
            ))
            if lib.ZSTD_isError(ret):
                return None
            if inbuf.pos == in_before and outbuf.pos == 0:
                return None  # no progress: treat as corrupt
            out += ctypes.string_at(chunk, outbuf.pos)
            if len(out) > MAX_DECOMPRESSED:
                raise CorruptPayloadError(
                    f"zstd batch exceeds decompressed size cap "
                    f"({MAX_DECOMPRESSED} B)"
                )
            if inbuf.pos >= inbuf.size and (
                ret == 0 or outbuf.pos < outbuf.size
            ):
                # Input drained and either the frame completed (ret == 0 —
                # even when the output chunk filled exactly) or the decoder
                # flushed everything it could (not full ⇒ it wants more
                # input: truncated, handled below).
                break
        if ret != 0:
            return None  # truncated final frame
        return bytes(out)
    finally:
        lib.ZSTD_freeDCtx(dctx)


def zstd_decompress(data: bytes) -> bytes:
    """Bounded zstd decode: libzstd one-shot when the frame declares its
    content size, growing-cap retries when it doesn't; the pure-Python
    decoder is the fallback and the verdict on malformed input."""
    import ctypes

    lib = _load_libzstd()
    if lib is not None and len(data) >= 4:
        csize = int(lib.ZSTD_getFrameContentSize(data, len(data)))
        if csize not in (_ZSTD_CONTENTSIZE_UNKNOWN, _ZSTD_CONTENTSIZE_ERROR):
            if csize > MAX_DECOMPRESSED:
                raise CorruptPayloadError(
                    f"zstd batch declares {csize} bytes (> 1 GiB cap)"
                )
            buf = ctypes.create_string_buffer(max(csize, 1))
            n = int(lib.ZSTD_decompress(buf, csize, data, len(data)))
            if not lib.ZSTD_isError(n):
                return buf.raw[:n]
            # fall through: the Python decoder raises the precise error
        elif csize == _ZSTD_CONTENTSIZE_UNKNOWN:
            # Streaming producers (ZSTD_compressStream2, i.e. most real
            # Kafka clients) omit the content size: decode incrementally.
            out = _zstd_stream_decompress(lib, data)
            if out is not None:
                return out
            # corrupt input: fall through, Python delivers the verdict
    from kafka_topic_analyzer_tpu.io import zstd_py

    return zstd_py.decompress(data, MAX_DECOMPRESSED)


def zstd_compress_frame(data: bytes, level: int = 3) -> bytes:
    """zstd encoder for tests and the fake broker: real libzstd when
    loadable, else a valid literal-only frame (raw blocks)."""
    import ctypes

    lib = _load_libzstd()
    if lib is not None:
        bound = int(lib.ZSTD_compressBound(len(data)))
        buf = ctypes.create_string_buffer(max(bound, 1))
        n = int(lib.ZSTD_compress(buf, bound, data, len(data), level))
        if not lib.ZSTD_isError(n):
            return buf.raw[:n]
    from kafka_topic_analyzer_tpu.io.zstd_py import ZSTD_MAGIC

    # Single-segment frame, 8-byte declared content size, raw blocks.
    out = bytearray(struct.pack("<IB", ZSTD_MAGIC, 0xE0))
    out += struct.pack("<Q", len(data))
    pos = 0
    block_max = 128 * 1024
    while True:
        chunk = data[pos : pos + block_max]
        pos += len(chunk)
        last = 1 if pos >= len(data) else 0
        h = last | (len(chunk) << 3)  # type 0 = raw
        out += struct.pack("<I", h)[:3] + chunk
        if last:
            break
    return bytes(out)


def decompress(codec: int, payload: bytes) -> bytes:
    """Kafka record-batch attribute codec → decompressed payload."""
    if codec == 0:
        return payload
    if isinstance(payload, (bytearray, memoryview)):
        # The wire client hands out zero-copy bytearray slices; the ctypes
        # codec fast paths (c_char_p) need real bytes.  Compressed payloads
        # are the small side of the pipe, so this copy is cheap.
        payload = bytes(payload)
    if codec == 1:  # gzip (RFC1952; wbits=47 auto-detects zlib too)
        return gzip_decompress(payload)
    if codec == 2:
        return snappy_decompress(payload)
    if codec == 3:
        return lz4_decompress(payload)
    if codec == 4:
        return zstd_decompress(payload)
    raise UnsupportedCodecError(f"unknown compression codec {codec}")


# ---------------------------------------------------------------------------
# literal-only encoders (tests / fake broker interop)


def _snappy_literal_block(data: bytes) -> bytes:
    out = bytearray()
    # preamble: uncompressed length varint
    n = len(data)
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            break
    pos = 0
    while pos < len(data):
        chunk = data[pos : pos + 60]
        out.append((len(chunk) - 1) << 2)  # literal tag, kind 0
        out += chunk
        pos += len(chunk)
    return bytes(out)


def snappy_compress_xerial(data: bytes) -> bytes:
    """Valid xerial-framed snappy stream using literal-only encoding."""
    block = _snappy_literal_block(data)
    return (
        XERIAL_MAGIC
        + struct.pack(">ii", 1, 1)  # version, compat
        + struct.pack(">i", len(block))
        + block
    )


def lz4_compress_frame(data: bytes) -> bytes:
    """Valid LZ4 frame using one uncompressed block (flag bit set)."""
    header = struct.pack("<I", LZ4_FRAME_MAGIC) + bytes([0x60, 0x40])
    # FLG 0x60: version 01, block-independence; BD 0x40: 64KB max block.
    # header checksum byte: xxhash of descriptor — brokers don't verify in
    # our decoder; real clients do, so use the real second byte of
    # XXH32(desc) >> 8 ... we skip verification on decode, write 0.
    header += b"\x00"
    body = struct.pack("<I", 0x80000000 | len(data)) + data
    return header + body + struct.pack("<I", 0)  # EndMark
