"""Kafka wire-protocol codec: primitives + the three RPCs the analyzer needs.

The reference delegates the entire Kafka protocol to librdkafka (C)
(src/kafka.rs:6-11, Cargo.toml:19).  This build speaks the protocol directly:
the analyzer only ever *reads* — Metadata (api 3), ListOffsets (api 2), Fetch
(api 1), plus ApiVersions (api 18) for the handshake — so a compact codec
covers the whole surface.  Both the client (`kafka_wire.py`) and the test
fake broker use these encoders/decoders, mirroring SURVEY.md §4's
backend-contract strategy.

Implemented versions — each API in both the classic and the KIP-482
flexible (compact/tagged-field) encodings, negotiated per broker via
ApiVersions (`_FLEXIBLE_FROM` below; version choice in kafka_wire.py's
`_CANDIDATES`):
- Metadata v1–v5 classic / v12 flexible (v5 is the Kafka 4.0 floor after
  KIP-896), ListOffsets v1 classic / v7 flexible, Fetch v4 classic /
  v12 flexible (sessionless: session_id 0, epoch -1), ApiVersions
  v0 classic / v3 flexible-request (response header stays v0 per KIP-511)
- SaslHandshake v1 + SaslAuthenticate v0 for PLAIN/SCRAM (`kafka_wire.py`)
- RecordBatch v2 ("magic 2", Kafka >= 0.11) with zigzag-varint records;
  all four codecs decode via io/compression.py: gzip (zlib), snappy
  (xerial framing), LZ4 frames, and zstd (from-scratch RFC 8878 decoder,
  io/zstd_py.py).  v0/v1 MessageSets are rejected with a clear error.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_SASL_HANDSHAKE = 17
API_VERSIONS = 18
API_OFFSET_FOR_LEADER_EPOCH = 23
API_SASL_AUTHENTICATE = 36

ERR_SASL_AUTHENTICATION_FAILED = 58

EARLIEST_TIMESTAMP = -2
LATEST_TIMESTAMP = -1

#: Kafka error codes the client interprets.
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_NOT_LEADER_FOR_PARTITION = 6
#: KIP-320 fencing errors: the request's current_leader_epoch is older
#: (74) or newer (75) than the leader's — the client must refresh
#: metadata, and a *regression* means the log may have been truncated.
ERR_FENCED_LEADER_EPOCH = 74
ERR_UNKNOWN_LEADER_EPOCH = 75


class KafkaProtocolError(RuntimeError):
    pass


class CorruptFrameError(KafkaProtocolError):
    """A record frame whose *bytes* are wrong — as opposed to transport
    faults (handled by io/retry.py) or protocol-level errors.  Corruption
    on the broker's disk is deterministic: every re-fetch returns the same
    poisoned bytes, so retrying is useless and callers need to decide
    (fail / skip / quarantine) instead.

    ``kind`` classifies the damage (one of CORRUPTION_KINDS); the context
    fields let the wire layer account for and quarantine the frame:

    - ``partition``: filled by the wire layer (the codec never knows it)
    - ``base_offset``: the frame header's claimed base offset (-1 unknown)
    - ``span``: (start, end) byte range of the frame in the record-set
      buffer, when the frame's bounds were readable (None otherwise)
    - ``claimed_end``: base + last_offset_delta + 1 when the header was
      parseable (-1 otherwise) — the offset a skip should resume at
    - ``num_records``: header-claimed record count (0 when unreadable)
    - ``crc_expected`` / ``crc_actual``: set for CRC mismatches
    """

    kind = "corrupt"

    def __init__(
        self,
        message: str,
        *,
        partition: "Optional[int]" = None,
        base_offset: int = -1,
        span: "Optional[Tuple[int, int]]" = None,
        claimed_end: int = -1,
        num_records: int = 0,
        crc_expected: "Optional[int]" = None,
        crc_actual: "Optional[int]" = None,
    ):
        super().__init__(message)
        self.partition = partition
        self.base_offset = base_offset
        self.span = span
        self.claimed_end = claimed_end
        self.num_records = num_records
        self.crc_expected = crc_expected
        self.crc_actual = crc_actual


class CrcMismatchError(CorruptFrameError):
    """Stored CRC32-C (v2) / CRC32 (legacy) disagrees with the bytes."""

    kind = "crc-mismatch"


class TruncatedFrameError(CorruptFrameError):
    """A frame or record body ends before its declared length (inside the
    buffer — a partial *trailing* batch is the broker's byte-limit
    truncation and is tolerated, not classified)."""

    kind = "truncated"


class MalformedHeaderError(CorruptFrameError):
    """Structurally impossible header fields: non-positive batch length,
    unknown magic, negative record count/length, bad nesting."""

    kind = "malformed-header"


class BadCompressionError(CorruptFrameError):
    """The frame's compressed payload does not decode (bad gzip/snappy/
    LZ4/zstd stream, or an unknown codec id)."""

    kind = "bad-compression"


class BadUtf8Error(CorruptFrameError):
    """A wire field declared as a string is not valid UTF-8."""

    kind = "bad-utf8"


#: The full classification surface — untrusted wire input must map onto
#: exactly these (tests/test_corruption.py fuzzes the contract).
CORRUPTION_KINDS = (
    "crc-mismatch", "truncated", "malformed-header", "bad-compression",
    "bad-utf8",
)


class UnsupportedVersionError(KafkaProtocolError):
    """Error 35: the broker rejected the request's api version — the
    caller may retry at a lower version (KIP-511 ApiVersions dance)."""


ERR_UNSUPPORTED_VERSION = 35


# ---------------------------------------------------------------------------
# primitives


class ByteWriter:
    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def raw(self, b: bytes) -> "ByteWriter":
        self._parts.append(b)
        return self

    def i8(self, v: int) -> "ByteWriter":
        return self.raw(struct.pack(">b", v))

    def i16(self, v: int) -> "ByteWriter":
        return self.raw(struct.pack(">h", v))

    def i32(self, v: int) -> "ByteWriter":
        return self.raw(struct.pack(">i", v))

    def i64(self, v: int) -> "ByteWriter":
        return self.raw(struct.pack(">q", v))

    def u32(self, v: int) -> "ByteWriter":
        return self.raw(struct.pack(">I", v))

    def string(self, s: Optional[str]) -> "ByteWriter":
        if s is None:
            return self.i16(-1)
        b = s.encode()
        return self.i16(len(b)).raw(b)

    def bytes_(self, b: Optional[bytes]) -> "ByteWriter":
        if b is None:
            return self.i32(-1)
        return self.i32(len(b)).raw(b)

    def varint(self, v: int) -> "ByteWriter":
        """Zigzag varint (signed)."""
        z = (v << 1) ^ (v >> 63) if v < 0 else (v << 1)
        z &= (1 << 64) - 1
        out = bytearray()
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        return self.raw(bytes(out))

    def varbytes(self, b: Optional[bytes]) -> "ByteWriter":
        if b is None:
            return self.varint(-1)
        return self.varint(len(b)).raw(b)

    # -- flexible-version (KIP-482) primitives ------------------------------

    def uvarint(self, v: int) -> "ByteWriter":
        """UNSIGNED varint — compact lengths and tag ids (flexible
        encodings use these, unlike record fields' zigzag varints)."""
        if v < 0:
            raise ValueError("uvarint requires v >= 0")
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        return self.raw(bytes(out))

    def compact_string(self, s: Optional[str]) -> "ByteWriter":
        """COMPACT_NULLABLE_STRING: uvarint(len + 1), 0 = null."""
        if s is None:
            return self.uvarint(0)
        b = s.encode()
        return self.uvarint(len(b) + 1).raw(b)

    def compact_bytes(self, b: Optional[bytes]) -> "ByteWriter":
        if b is None:
            return self.uvarint(0)
        return self.uvarint(len(b) + 1).raw(b)

    def compact_array_len(self, n: Optional[int]) -> "ByteWriter":
        """COMPACT_ARRAY header: uvarint(count + 1), 0 = null array."""
        return self.uvarint(0 if n is None else n + 1)

    def tags(self) -> "ByteWriter":
        """Empty tagged-field buffer (this client sends no tagged fields)."""
        return self.uvarint(0)

    def done(self) -> bytes:
        return b"".join(self._parts)


class ByteReader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if n < 0 or self.pos < 0 or self.pos + n > len(self.buf):
            raise KafkaProtocolError(
                f"truncated message: need {n} bytes at {self.pos}, have {len(self.buf)}"
            )
        b = self.buf[self.pos : self.pos + n]
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        if n < 0:
            return None
        try:
            # bytes() first: response buffers may be memoryviews (the
            # zero-copy receive path) and memoryview has no .decode.
            return bytes(self._take(n)).decode()
        except UnicodeDecodeError as e:
            # Untrusted wire input must not leak UnicodeDecodeError.
            raise BadUtf8Error(f"invalid UTF-8 string on the wire: {e}") from e

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)

    def bytes_view(self) -> "Optional[memoryview]":
        """Like bytes_ but zero-copy: a memoryview over the buffer.  For
        bulk fields (fetch record sets run to tens of MB) where the caller
        only slices/unpacks.  (memoryview truthiness follows __len__, like
        bytes — an empty view is falsy.)"""
        n = self.i32()
        if n < 0:
            return None
        if n > len(self.buf) - self.pos:
            raise KafkaProtocolError(
                f"truncated message: need {n} bytes at {self.pos}, "
                f"have {len(self.buf)}"
            )
        v = memoryview(self.buf)[self.pos : self.pos + n]
        self.pos += n
        return v

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self._take(1)[0]
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise KafkaProtocolError("varint too long")
        return (z >> 1) ^ -(z & 1)  # un-zigzag

    def varbytes(self) -> Optional[bytes]:
        n = self.varint()
        if n < 0:
            return None
        return self._take(n)

    # -- flexible-version (KIP-482) primitives ------------------------------

    def uvarint(self) -> int:
        shift = 0
        v = 0
        while True:
            b = self._take(1)[0]
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 63:
                raise KafkaProtocolError("uvarint too long")

    def compact_string(self) -> Optional[str]:
        n = self.uvarint()
        if n == 0:
            return None
        try:
            return bytes(self._take(n - 1)).decode()
        except UnicodeDecodeError as e:
            raise BadUtf8Error(f"invalid UTF-8 string on the wire: {e}") from e

    def compact_bytes(self) -> Optional[bytes]:
        n = self.uvarint()
        if n == 0:
            return None
        return self._take(n - 1)

    def compact_bytes_view(self) -> "Optional[memoryview]":
        """Zero-copy compact bytes — the flexible twin of bytes_view, for
        fetch record sets."""
        n = self.uvarint()
        if n == 0:
            return None
        n -= 1
        if n > len(self.buf) - self.pos:
            raise KafkaProtocolError(
                f"truncated message: need {n} bytes at {self.pos}, "
                f"have {len(self.buf)}"
            )
        v = memoryview(self.buf)[self.pos : self.pos + n]
        self.pos += n
        return v

    def compact_array_len(self) -> int:
        """COMPACT_ARRAY count; null arrays read as empty."""
        n = self.uvarint()
        return 0 if n == 0 else n - 1

    def skip_tags(self) -> None:
        """Skip a tagged-field buffer (forward compatibility: unknown
        tagged fields are ignorable by contract)."""
        for _ in range(self.uvarint()):
            self.uvarint()  # tag id
            self._take(self.uvarint())

    def remaining(self) -> int:
        return len(self.buf) - self.pos


# ---------------------------------------------------------------------------
# request framing


#: First flexible (KIP-482 tagged/compact encoding) version per API this
#: client speaks.  Flexible requests use header v2 (a tag buffer after
#: client_id) and flexible responses header v1 (a tag buffer after the
#: correlation id) — EXCEPT ApiVersions responses, which stay header v0 at
#: every version so that brokers can answer clients whose flexible support
#: is still unknown.
_FLEXIBLE_FROM = {
    API_METADATA: 9,
    API_FETCH: 12,
    API_LIST_OFFSETS: 6,
    API_VERSIONS: 3,
    API_OFFSET_FOR_LEADER_EPOCH: 4,
}


def is_flexible(api_key: int, api_version: int) -> bool:
    v = _FLEXIBLE_FROM.get(api_key)
    return v is not None and api_version >= v


def encode_request(
    api_key: int, api_version: int, correlation_id: int, client_id: str, body: bytes
) -> bytes:
    """Length-prefixed request with header v1 — or v2 (trailing tag
    buffer) for flexible api versions (src client.id analog: the
    reference sets client.id=topic-analyzer, src/kafka.rs:36)."""
    w = ByteWriter()
    w.i16(api_key).i16(api_version).i32(correlation_id).string(client_id)
    if is_flexible(api_key, api_version):
        w.tags()
    payload = w.done() + body
    return struct.pack(">i", len(payload)) + payload


def decode_request_header(buf: bytes) -> Tuple[int, int, int, Optional[str], ByteReader]:
    r = ByteReader(buf)
    api_key = r.i16()
    api_version = r.i16()
    corr = r.i32()
    client_id = r.string()  # header v2 keeps the classic NULLABLE_STRING
    if is_flexible(api_key, api_version):
        r.skip_tags()
    return api_key, api_version, corr, client_id, r


# ---------------------------------------------------------------------------
# Metadata v1 / v5 (classic encoding; v5 is the floor on Kafka 4.0 brokers
# after KIP-896 removed pre-2.1 protocol versions) / v12 (flexible,
# KIP-482 compact encoding + KIP-516 topic ids)

#: All-zero UUID = "name lookup" in topic-id-aware requests (KIP-516).
_NULL_UUID = b"\x00" * 16


def encode_metadata_request(
    topics: Optional[List[str]], version: int = 1
) -> bytes:
    w = ByteWriter()
    if version >= 9:
        w.compact_array_len(None if topics is None else len(topics))
        for t in topics or []:
            if version >= 10:
                w.raw(_NULL_UUID)  # topic_id: lookup by name
            w.compact_string(t)
            w.tags()
        w.i8(0)  # allow_auto_topic_creation = false (read-only tool)
        if version <= 10:
            w.i8(0)  # include_cluster_authorized_operations
        w.i8(0)  # include_topic_authorized_operations
        w.tags()
        return w.done()
    if topics is None:
        w.i32(-1)
    else:
        w.i32(len(topics))
        for t in topics:
            w.string(t)
    if version >= 4:
        w.i8(0)  # allow_auto_topic_creation = false (read-only tool)
    return w.done()


def decode_metadata_request(
    r: ByteReader, version: int = 1
) -> Optional[List[str]]:
    """Topic names of a Metadata request (fake-broker side)."""
    if version >= 9:
        n = r.uvarint()
        if n == 0:
            topics = None
        else:
            topics = []
            for _ in range(n - 1):
                if version >= 10:
                    r._take(16)  # topic_id
                topics.append(r.compact_string() or "")
                r.skip_tags()
        r.i8()  # allow_auto_topic_creation
        if version <= 10:
            r.i8()
        r.i8()
        r.skip_tags()
        return topics
    n = r.i32()
    if n < 0:
        return None
    topics = [r.string() or "" for _ in range(n)]
    if version >= 4:
        r.i8()
    return topics


@dataclasses.dataclass
class PartitionMetadata:
    error: int
    partition: int
    leader: int


@dataclasses.dataclass
class TopicMetadata:
    error: int
    name: str
    partitions: List[PartitionMetadata]
    #: Broker-flagged internal topic (``__consumer_offsets`` and friends;
    #: Metadata v1+).  Fleet discovery (fleet/discovery.py) excludes these
    #: by default — auditing the cluster means the *user's* topics.
    is_internal: int = 0


@dataclasses.dataclass
class MetadataResponse:
    brokers: "dict[int, tuple[str, int]]"  # node_id -> (host, port)
    controller_id: int
    topics: List[TopicMetadata]


def encode_metadata_response(resp: MetadataResponse, version: int = 1) -> bytes:
    w = ByteWriter()
    if version >= 9:
        w.i32(0)  # throttle_time_ms
        w.compact_array_len(len(resp.brokers))
        for node_id, (host, port) in resp.brokers.items():
            w.i32(node_id).compact_string(host).i32(port)
            w.compact_string(None)  # rack
            w.tags()
        w.compact_string(None)  # cluster_id
        w.i32(resp.controller_id)
        w.compact_array_len(len(resp.topics))
        for t in resp.topics:
            w.i16(t.error).compact_string(t.name)
            if version >= 10:
                w.raw(_NULL_UUID)  # topic_id
            w.i8(t.is_internal)
            w.compact_array_len(len(t.partitions))
            for p in t.partitions:
                w.i16(p.error).i32(p.partition).i32(p.leader)
                w.i32(0)  # leader_epoch (v7+)
                w.compact_array_len(1).i32(p.leader)  # replicas
                w.compact_array_len(1).i32(p.leader)  # isr
                w.compact_array_len(0)  # offline_replicas
                w.tags()
            w.i32(-2147483648)  # topic_authorized_operations (v8+)
            w.tags()
        if 8 <= version <= 10:
            w.i32(-2147483648)  # cluster_authorized_operations
        w.tags()
        return w.done()
    if version >= 3:
        w.i32(0)  # throttle_time_ms
    w.i32(len(resp.brokers))
    for node_id, (host, port) in resp.brokers.items():
        w.i32(node_id).string(host).i32(port).string(None)  # rack
    if version >= 2:
        w.string(None)  # cluster_id
    w.i32(resp.controller_id)
    w.i32(len(resp.topics))
    for t in resp.topics:
        w.i16(t.error).string(t.name).i8(t.is_internal)
        w.i32(len(t.partitions))
        for p in t.partitions:
            w.i16(p.error).i32(p.partition).i32(p.leader)
            w.i32(1).i32(p.leader)  # replicas
            w.i32(1).i32(p.leader)  # isr
            if version >= 5:
                w.i32(0)  # offline_replicas: empty
    return w.done()


def decode_metadata_response(r: ByteReader, version: int = 1) -> MetadataResponse:
    if version >= 9:
        r.i32()  # throttle_time_ms
        brokers = {}
        for _ in range(r.compact_array_len()):
            node_id = r.i32()
            host = r.compact_string() or ""
            port = r.i32()
            r.compact_string()  # rack
            r.skip_tags()
            brokers[node_id] = (host, port)
        r.compact_string()  # cluster_id
        controller = r.i32()
        topics = []
        for _ in range(r.compact_array_len()):
            err = r.i16()
            name = r.compact_string() or ""
            if version >= 10:
                r._take(16)  # topic_id
            internal = r.i8()
            parts = []
            for _ in range(r.compact_array_len()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                r.i32()  # leader_epoch
                for _ in range(r.compact_array_len()):
                    r.i32()  # replicas
                for _ in range(r.compact_array_len()):
                    r.i32()  # isr
                for _ in range(r.compact_array_len()):
                    r.i32()  # offline_replicas
                r.skip_tags()
                parts.append(PartitionMetadata(perr, pid, leader))
            r.i32()  # topic_authorized_operations
            r.skip_tags()
            topics.append(TopicMetadata(err, name, parts, is_internal=internal))
        if 8 <= version <= 10:
            r.i32()  # cluster_authorized_operations
        r.skip_tags()
        return MetadataResponse(brokers, controller, topics)
    if version >= 3:
        r.i32()  # throttle_time_ms
    brokers = {}
    for _ in range(r.i32()):
        node_id = r.i32()
        host = r.string() or ""
        port = r.i32()
        r.string()  # rack
        brokers[node_id] = (host, port)
    if version >= 2:
        r.string()  # cluster_id
    controller = r.i32()
    topics = []
    for _ in range(r.i32()):
        err = r.i16()
        name = r.string() or ""
        internal = r.i8()
        parts = []
        for _ in range(r.i32()):
            perr = r.i16()
            pid = r.i32()
            leader = r.i32()
            for _ in range(r.i32()):
                r.i32()  # replicas
            for _ in range(r.i32()):
                r.i32()  # isr
            if version >= 5:
                for _ in range(r.i32()):
                    r.i32()  # offline_replicas
            parts.append(PartitionMetadata(perr, pid, leader))
        topics.append(TopicMetadata(err, name, parts, is_internal=internal))
    return MetadataResponse(brokers, controller, topics)


# ---------------------------------------------------------------------------
# ListOffsets v1 (classic) / v7 (flexible)


def encode_list_offsets_request(
    topic: str, partition_timestamps: List[Tuple[int, int]], version: int = 1
) -> bytes:
    w = ByteWriter()
    w.i32(-1)  # replica_id
    if version >= 6:
        w.i8(0)  # isolation_level: read_uncommitted (v2+)
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(partition_timestamps))
        for pid, ts in partition_timestamps:
            w.i32(pid)
            w.i32(-1)  # current_leader_epoch (v4+): unknown
            w.i64(ts)
            w.tags()
        w.tags()  # topic
        w.tags()  # request
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(partition_timestamps))
    for pid, ts in partition_timestamps:
        w.i32(pid).i64(ts)
    return w.done()


def decode_list_offsets_request(
    r: ByteReader, version: int = 1
) -> Tuple[str, List[Tuple[int, int]]]:
    r.i32()  # replica_id
    if version >= 6:
        r.i8()  # isolation_level
        ntopics = r.compact_array_len()
        if ntopics != 1:
            raise KafkaProtocolError(
                f"single-topic request invariant: got {ntopics} topics"
            )
        topic = r.compact_string() or ""
        out = []
        for _ in range(r.compact_array_len()):
            pid = r.i32()
            r.i32()  # current_leader_epoch
            out.append((pid, r.i64()))
            r.skip_tags()
        r.skip_tags()
        r.skip_tags()
        return topic, out
    ntopics = r.i32()
    if ntopics != 1:
        raise KafkaProtocolError(
            f"single-topic request invariant: got {ntopics} topics"
        )
    topic = r.string() or ""
    out = []
    for _ in range(r.i32()):
        out.append((r.i32(), r.i64()))
    return topic, out


def encode_list_offsets_response(
    topic: str, results: List[Tuple[int, ...]], version: int = 1
) -> bytes:
    """results: (partition, error, timestamp, offset[, leader_epoch])
    — the epoch element is optional (and only carried by v4+ wires)."""
    w = ByteWriter()
    if version >= 6:
        w.i32(0)  # throttle_time_ms (v2+)
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(results))
        for item in results:
            pid, err, ts, off = item[:4]
            w.i32(pid).i16(err).i64(ts).i64(off)
            w.i32(item[4] if len(item) > 4 else -1)  # leader_epoch (v4+)
            w.tags()
        w.tags()
        w.tags()
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(results))
    for item in results:
        pid, err, ts, off = item[:4]
        w.i32(pid).i16(err).i64(ts).i64(off)
    return w.done()


def decode_list_offsets_response(
    r: ByteReader, version: int = 1
) -> "dict[int, tuple[int, int, int]]":
    """{partition: (error, offset, leader_epoch)} — epoch -1 on wires
    that do not carry it (classic v1)."""
    out = {}
    if version >= 6:
        r.i32()  # throttle_time_ms
        for _ in range(r.compact_array_len()):
            r.compact_string()  # topic
            for _ in range(r.compact_array_len()):
                pid = r.i32()
                err = r.i16()
                r.i64()  # timestamp
                off = r.i64()
                epoch = r.i32()  # leader_epoch (v4+)
                r.skip_tags()
                out[pid] = (err, off, epoch)
            r.skip_tags()
        r.skip_tags()
        return out
    for _ in range(r.i32()):
        r.string()  # topic
        for _ in range(r.i32()):
            pid = r.i32()
            err = r.i16()
            r.i64()  # timestamp
            off = r.i64()
            out[pid] = (err, off, -1)
    return out


# ---------------------------------------------------------------------------
# Fetch v4 (classic) / v12 (flexible; sessionless — session_id 0, epoch -1)


def encode_fetch_request(
    topic: str,
    partition_offsets: List[Tuple[int, ...]],
    max_wait_ms: int,
    min_bytes: int,
    max_bytes: int,
    partition_max_bytes: int,
    version: int = 4,
) -> bytes:
    """``partition_offsets``: (partition, offset[, current_leader_epoch])
    — the optional epoch (KIP-320 fencing) rides the v9+ wire only; the
    classic v4 encoding has no epoch field, so fencing degrades to
    unfenced fetches there."""
    w = ByteWriter()
    w.i32(-1)  # replica_id
    w.i32(max_wait_ms).i32(min_bytes).i32(max_bytes).i8(0)  # isolation: read_uncommitted
    if version >= 12:
        w.i32(0).i32(-1)  # session_id / session_epoch: sessionless (KIP-227)
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(partition_offsets))
        for item in partition_offsets:
            pid, off = item[:2]
            w.i32(pid)
            # current_leader_epoch (v9+): the tracked epoch, or -1 unknown
            w.i32(item[2] if len(item) > 2 else -1)
            w.i64(off)
            w.i32(-1)       # last_fetched_epoch (v12+): none
            w.i64(-1)       # log_start_offset (v5+): consumer
            w.i32(partition_max_bytes)
            w.tags()
        w.tags()  # topic
        w.compact_array_len(0)  # forgotten_topics_data (v7+)
        w.compact_string("")    # rack_id (v11+)
        w.tags()
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(partition_offsets))
    for item in partition_offsets:
        pid, off = item[:2]
        w.i32(pid).i64(off).i32(partition_max_bytes)
    return w.done()


def decode_fetch_request(r: ByteReader, version: int = 4):
    """parts: (partition, offset, partition_max_bytes,
    current_leader_epoch) — epoch -1 on classic wires (no field) and
    from clients that do not track one (the fake broker validates it)."""
    r.i32()  # replica
    max_wait = r.i32()
    min_bytes = r.i32()
    max_bytes = r.i32()
    r.i8()  # isolation
    if version >= 12:
        r.i32()  # session_id
        r.i32()  # session_epoch
        ntopics = r.compact_array_len()
        if ntopics != 1:
            raise KafkaProtocolError(
                f"single-topic request invariant: got {ntopics} topics"
            )
        topic = r.compact_string() or ""
        parts = []
        for _ in range(r.compact_array_len()):
            pid = r.i32()
            epoch = r.i32()  # current_leader_epoch (v9+)
            off = r.i64()
            r.i32()  # last_fetched_epoch
            r.i64()  # log_start_offset
            pmax = r.i32()
            r.skip_tags()
            parts.append((pid, off, pmax, epoch))
        r.skip_tags()  # topic
        for _ in range(r.compact_array_len()):  # forgotten topics
            r.compact_string()
            for _ in range(r.compact_array_len()):
                r.i32()
            r.skip_tags()
        r.compact_string()  # rack_id
        r.skip_tags()
        return topic, parts, max_wait, min_bytes, max_bytes
    ntopics = r.i32()
    if ntopics != 1:
        raise KafkaProtocolError(
            f"single-topic request invariant: got {ntopics} topics"
        )
    topic = r.string() or ""
    parts = []
    for _ in range(r.i32()):
        pid = r.i32()
        off = r.i64()
        pmax = r.i32()
        parts.append((pid, off, pmax, -1))
    return topic, parts, max_wait, min_bytes, max_bytes


def encode_fetch_response(
    topic: str, partitions: List[Tuple[int, ...]], version: int = 4
) -> bytes:
    """partitions: (partition, error, high_watermark, record_set_bytes
    [, log_start_offset]) — log_start rides the v5+ wire only (the
    classic v4 encoding has no field for it)."""
    w = ByteWriter()
    w.i32(0)  # throttle_time_ms
    if version >= 12:
        w.i16(0)  # top-level error_code (v7+)
        w.i32(0)  # session_id (v7+)
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(partitions))
        for item in partitions:
            pid, err, hw, records = item[:4]
            w.i32(pid).i16(err).i64(hw)
            w.i64(hw)   # last_stable_offset (v4+)
            w.i64(item[4] if len(item) > 4 else 0)  # log_start_offset (v5+)
            w.compact_array_len(0)  # aborted_transactions
            w.i32(-1)   # preferred_read_replica (v11+)
            w.compact_bytes(records)
            w.tags()
        w.tags()
        w.tags()
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(partitions))
    for item in partitions:
        pid, err, hw, records = item[:4]
        w.i32(pid).i16(err).i64(hw)
        w.i64(hw)  # last_stable_offset
        w.i32(0)   # aborted_transactions: empty
        w.bytes_(records)
    return w.done()


@dataclasses.dataclass
class FetchedPartition:
    partition: int
    error: int
    high_watermark: int
    records: bytes
    #: Broker-reported first retained offset (v5+ wires; -1 when the wire
    #: does not carry it) — the retention-race accounting compares it
    #: against the cursor without an extra ListOffsets round trip.
    log_start_offset: int = -1


def decode_fetch_response(r: ByteReader, version: int = 4) -> List[FetchedPartition]:
    r.i32()  # throttle
    out = []
    if version >= 12:
        err_top = r.i16()
        if err_top:
            raise KafkaProtocolError(f"Fetch error {err_top}")
        r.i32()  # session_id
        for _ in range(r.compact_array_len()):
            r.compact_string()  # topic
            for _ in range(r.compact_array_len()):
                pid = r.i32()
                err = r.i16()
                hw = r.i64()
                r.i64()  # last_stable_offset
                log_start = r.i64()  # log_start_offset (v5+)
                for _ in range(r.compact_array_len()):  # aborted txns
                    r.i64()
                    r.i64()
                    r.skip_tags()
                r.i32()  # preferred_read_replica
                records = r.compact_bytes_view()
                r.skip_tags()
                out.append(
                    FetchedPartition(
                        pid, err, hw,
                        records if records is not None else b"",
                        log_start_offset=log_start,
                    )
                )
            r.skip_tags()
        r.skip_tags()
        return out
    for _ in range(r.i32()):
        r.string()  # topic
        for _ in range(r.i32()):
            pid = r.i32()
            err = r.i16()
            hw = r.i64()
            r.i64()  # last_stable_offset
            for _ in range(r.i32()):  # aborted txns
                r.i64()
                r.i64()
            records = r.bytes_view()
            out.append(
                FetchedPartition(
                    pid, err, hw, records if records is not None else b""
                )
            )
    return out


# ---------------------------------------------------------------------------
# ApiVersions v0 (classic) / v3 (flexible request; response header stays v0
# at EVERY version — the broker answers before knowing the client's
# flexible support)


def encode_api_versions_request(version: int = 0) -> bytes:
    if version < 3:
        return b""
    w = ByteWriter()
    w.compact_string("kafka-topic-analyzer-tpu")
    w.compact_string("2")
    w.tags()
    return w.done()


def encode_api_versions_response(
    apis: List[Tuple[int, int, int]], version: int = 0
) -> bytes:
    w = ByteWriter()
    w.i16(0)  # error
    if version >= 3:
        w.compact_array_len(len(apis))
        for key, vmin, vmax in apis:
            w.i16(key).i16(vmin).i16(vmax)
            w.tags()
        w.i32(0)  # throttle_time_ms (v1+)
        w.tags()
        return w.done()
    w.i32(len(apis))
    for key, vmin, vmax in apis:
        w.i16(key).i16(vmin).i16(vmax)
    return w.done()


def decode_api_versions_response(
    r: ByteReader, version: int = 0
) -> "dict[int, tuple[int, int]]":
    err = r.i16()
    if err == ERR_UNSUPPORTED_VERSION:
        # Answered in v0 format regardless of the requested version
        # (KIP-511): the caller downgrades and retries.
        raise UnsupportedVersionError("ApiVersions error 35")
    if err:
        raise KafkaProtocolError(f"ApiVersions error {err}")
    out = {}
    if version >= 3:
        for _ in range(r.compact_array_len()):
            api_key = r.i16()
            vmin = r.i16()
            vmax = r.i16()
            r.skip_tags()
            out[api_key] = (vmin, vmax)
        r.i32()  # throttle_time_ms
        r.skip_tags()
        return out
    for _ in range(r.i32()):
        # Read fields in explicit order: `out[r.i16()] = (r.i16(), r.i16())`
        # evaluates the RHS before the key and scrambles the triples.
        api_key = r.i16()
        vmin = r.i16()
        vmax = r.i16()
        out[api_key] = (vmin, vmax)
    return out


# ---------------------------------------------------------------------------
# OffsetForLeaderEpoch v3 (classic) / v4 (flexible) — KIP-320's divergence
# check: "what is the end offset of epoch E?"  The broker answers with the
# end offset of the largest epoch <= E; an answer BELOW the client's cursor
# means the log was truncated (unclean election) and everything from the
# answer to the cursor no longer exists.


def encode_offset_for_leader_epoch_request(
    topic: str,
    partitions: List[Tuple[int, int, int]],
    version: int = 3,
) -> bytes:
    """partitions: (partition, current_leader_epoch, leader_epoch) — the
    fencing epoch the client believes is current, and the epoch whose end
    offset it asks for."""
    w = ByteWriter()
    if version >= 3:
        w.i32(-1)  # replica_id (v3+): consumer
    if version >= 4:
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(partitions))
        for pid, cur_epoch, epoch in partitions:
            w.i32(pid).i32(cur_epoch).i32(epoch)
            w.tags()
        w.tags()  # topic
        w.tags()  # request
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(partitions))
    for pid, cur_epoch, epoch in partitions:
        w.i32(pid)
        w.i32(cur_epoch)  # current_leader_epoch (v2+)
        w.i32(epoch)
    return w.done()


def decode_offset_for_leader_epoch_request(
    r: ByteReader, version: int = 3
) -> Tuple[str, List[Tuple[int, int, int]]]:
    if version >= 3:
        r.i32()  # replica_id
    if version >= 4:
        ntopics = r.compact_array_len()
        if ntopics != 1:
            raise KafkaProtocolError(
                f"single-topic request invariant: got {ntopics} topics"
            )
        topic = r.compact_string() or ""
        out = []
        for _ in range(r.compact_array_len()):
            pid = r.i32()
            cur_epoch = r.i32()
            epoch = r.i32()
            r.skip_tags()
            out.append((pid, cur_epoch, epoch))
        r.skip_tags()
        r.skip_tags()
        return topic, out
    ntopics = r.i32()
    if ntopics != 1:
        raise KafkaProtocolError(
            f"single-topic request invariant: got {ntopics} topics"
        )
    topic = r.string() or ""
    out = []
    for _ in range(r.i32()):
        pid = r.i32()
        cur_epoch = r.i32()
        epoch = r.i32()
        out.append((pid, cur_epoch, epoch))
    return topic, out


def encode_offset_for_leader_epoch_response(
    topic: str,
    results: List[Tuple[int, int, int, int]],
    version: int = 3,
) -> bytes:
    """results: (partition, error, leader_epoch, end_offset)."""
    w = ByteWriter()
    w.i32(0)  # throttle_time_ms (v2+)
    if version >= 4:
        w.compact_array_len(1).compact_string(topic)
        w.compact_array_len(len(results))
        for pid, err, epoch, end_off in results:
            w.i16(err).i32(pid).i32(epoch).i64(end_off)
            w.tags()
        w.tags()
        w.tags()
        return w.done()
    w.i32(1).string(topic)
    w.i32(len(results))
    for pid, err, epoch, end_off in results:
        w.i16(err).i32(pid).i32(epoch).i64(end_off)
    return w.done()


def decode_offset_for_leader_epoch_response(
    r: ByteReader, version: int = 3
) -> "dict[int, tuple[int, int, int]]":
    """{partition: (error, leader_epoch, end_offset)} — end_offset is the
    first offset AFTER the requested epoch's last record (-1 on error)."""
    r.i32()  # throttle_time_ms
    out = {}
    if version >= 4:
        for _ in range(r.compact_array_len()):
            r.compact_string()  # topic
            for _ in range(r.compact_array_len()):
                err = r.i16()
                pid = r.i32()
                epoch = r.i32()
                end_off = r.i64()
                r.skip_tags()
                out[pid] = (err, epoch, end_off)
            r.skip_tags()
        r.skip_tags()
        return out
    for _ in range(r.i32()):
        r.string()  # topic
        for _ in range(r.i32()):
            err = r.i16()
            pid = r.i32()
            epoch = r.i32()
            end_off = r.i64()
            out[pid] = (err, epoch, end_off)
    return out


# ---------------------------------------------------------------------------
# SASL (handshake v1 + authenticate v0; PLAIN + SCRAM-SHA-256/512)


def encode_sasl_handshake_request(mechanism: str) -> bytes:
    return ByteWriter().string(mechanism).done()


def decode_sasl_handshake_request(r: ByteReader) -> str:
    return r.string() or ""


def encode_sasl_handshake_response(error: int, mechanisms: List[str]) -> bytes:
    w = ByteWriter()
    w.i16(error).i32(len(mechanisms))
    for m in mechanisms:
        w.string(m)
    return w.done()


def decode_sasl_handshake_response(r: ByteReader) -> "tuple[int, list[str]]":
    err = r.i16()
    mechanisms = [r.string() or "" for _ in range(r.i32())]
    return err, mechanisms


def sasl_plain_token(username: str, password: str) -> bytes:
    return b"\x00" + username.encode() + b"\x00" + password.encode()


def encode_sasl_authenticate_request(auth_bytes: bytes) -> bytes:
    return ByteWriter().bytes_(auth_bytes).done()


def decode_sasl_authenticate_request(r: ByteReader) -> bytes:
    return r.bytes_() or b""


def encode_sasl_authenticate_response(
    error: int,
    error_message: Optional[str] = None,
    auth_bytes: bytes = b"",
) -> bytes:
    return (
        ByteWriter().i16(error).string(error_message).bytes_(auth_bytes).done()
    )


def decode_sasl_authenticate_response(
    r: ByteReader,
) -> "tuple[int, Optional[str], bytes]":
    err = r.i16()
    msg = r.string()
    # bytes() guard: response buffers may be memoryviews (zero-copy
    # receive) and SCRAM parsing splits/decodes the token.
    auth = r.bytes_()  # SCRAM server-first/server-final rides here
    return err, msg, bytes(auth) if auth is not None else b""


# -- SCRAM (RFC 5802/7677 over Kafka's SaslAuthenticate round trips) --------

SCRAM_MECHANISMS = {"SCRAM-SHA-256": "sha256", "SCRAM-SHA-512": "sha512"}


def _scram_saslname(name: str) -> str:
    """RFC 5802 saslname escaping for the n= attribute."""
    return name.replace("=", "=3D").replace(",", "=2C")


def _scram_parse(msg: bytes) -> "dict[str, str]":
    out = {}
    try:
        text = msg.decode("utf-8")
    except UnicodeDecodeError as e:
        raise KafkaProtocolError(f"non-UTF-8 SCRAM server message: {e}") from e
    for part in text.split(","):
        if len(part) >= 2 and part[1] == "=":
            out[part[0]] = part[2:]
    return out


def _scram_hi(hash_name: str, password: bytes, salt: bytes, it: int) -> bytes:
    import hashlib

    return hashlib.pbkdf2_hmac(hash_name, password, salt, it)


class ScramClient:
    """Client side of one SCRAM exchange (no channel binding, like the
    Kafka clients).  Usage: first_message() → broker; final_message(
    server_first) → broker; verify_server_final(server_final)."""

    def __init__(self, mechanism: str, username: str, password: str):
        import base64
        import os as _os

        self.hash_name = SCRAM_MECHANISMS[mechanism]
        self.password = password.encode("utf-8")
        self.nonce = base64.b64encode(_os.urandom(24)).decode()
        self._first_bare = f"n={_scram_saslname(username)},r={self.nonce}"
        self._auth_message: Optional[bytes] = None
        self._salted: Optional[bytes] = None

    def first_message(self) -> bytes:
        return ("n,," + self._first_bare).encode("utf-8")

    def final_message(self, server_first: bytes) -> bytes:
        import base64
        import hashlib
        import hmac as _hmac

        attrs = _scram_parse(server_first)
        if "e" in attrs:
            raise KafkaProtocolError(f"SCRAM server error: {attrs['e']}")
        try:
            full_nonce = attrs["r"]
            salt = base64.b64decode(attrs["s"])
            iterations = int(attrs["i"])
        except (KeyError, ValueError) as e:
            raise KafkaProtocolError(
                f"malformed SCRAM server-first message: {e}"
            ) from e
        if not full_nonce.startswith(self.nonce):
            raise KafkaProtocolError(
                "SCRAM server nonce does not extend the client nonce"
            )
        if iterations < 4096 or iterations > 10_000_000:
            # RFC 7677 / Kafka's ScramMechanism.minIterations: a lower
            # count is a MITM downgrade making offline cracking cheap.
            raise KafkaProtocolError(
                f"SCRAM iteration count {iterations} out of range "
                "(4096..10M)"
            )
        without_proof = f"c=biws,r={full_nonce}"  # biws = b64("n,,")
        self._auth_message = ",".join(
            [self._first_bare, server_first.decode("utf-8"), without_proof]
        ).encode("utf-8")
        self._salted = _scram_hi(
            self.hash_name, self.password, salt, iterations
        )
        client_key = _hmac.new(
            self._salted, b"Client Key", self.hash_name
        ).digest()
        stored_key = hashlib.new(self.hash_name, client_key).digest()
        signature = _hmac.new(
            stored_key, self._auth_message, self.hash_name
        ).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        return (
            without_proof + ",p=" + base64.b64encode(proof).decode()
        ).encode("utf-8")

    def verify_server_final(self, server_final: bytes) -> None:
        import base64
        import hmac as _hmac

        attrs = _scram_parse(server_final)
        if "e" in attrs:
            raise KafkaProtocolError(f"SCRAM server error: {attrs['e']}")
        if "v" not in attrs or self._salted is None:
            raise KafkaProtocolError("malformed SCRAM server-final message")
        try:
            got = base64.b64decode(attrs["v"], validate=True)
        except Exception as e:
            raise KafkaProtocolError(
                f"malformed SCRAM server signature: {e}"
            ) from e
        server_key = _hmac.new(
            self._salted, b"Server Key", self.hash_name
        ).digest()
        expected = _hmac.new(
            server_key, self._auth_message, self.hash_name
        ).digest()
        if not _hmac.compare_digest(got, expected):
            raise KafkaProtocolError(
                "SCRAM server signature verification failed "
                "(broker does not know the password)"
            )


class ScramServer:
    """Server side, for the credential-enforcing fake broker (and as the
    client's test oracle).  One instance per connection attempt."""

    def __init__(
        self,
        mechanism: str,
        username: str,
        password: str,
        iterations: int = 4096,
        salt: Optional[bytes] = None,
    ):
        import os as _os

        self.hash_name = SCRAM_MECHANISMS[mechanism]
        self.username = username
        self.password = password.encode("utf-8")
        self.iterations = iterations
        self.salt = salt if salt is not None else _os.urandom(16)
        self._client_first_bare: Optional[str] = None
        self._server_first: Optional[str] = None
        self._user_ok = False

    def handle_first(self, client_first: bytes) -> bytes:
        import base64
        import os as _os

        text = client_first.decode("utf-8")
        if not text.startswith("n,,"):
            raise ValueError("expected gs2 header 'n,,'")
        self._client_first_bare = text[3:]
        attrs = _scram_parse(self._client_first_bare.encode())
        # Real brokers look credentials up by username; an unknown user
        # completes the exchange (no information leak) but always fails
        # the proof check.
        self._user_ok = attrs.get("n") == _scram_saslname(self.username)
        nonce = attrs.get("r", "") + base64.b64encode(_os.urandom(18)).decode()
        self._server_first = (
            f"r={nonce},s={base64.b64encode(self.salt).decode()},"
            f"i={self.iterations}"
        )
        return self._server_first.encode("utf-8")

    def handle_final(self, client_final: bytes) -> "tuple[bool, bytes]":
        import base64
        import hashlib
        import hmac as _hmac

        attrs = _scram_parse(client_final)
        cf_text = client_final.decode("utf-8")
        without_proof = cf_text[: cf_text.rfind(",p=")]
        auth_message = ",".join(
            [self._client_first_bare or "", self._server_first or "",
             without_proof]
        ).encode("utf-8")
        salted = _scram_hi(
            self.hash_name, self.password, self.salt, self.iterations
        )
        client_key = _hmac.new(salted, b"Client Key", self.hash_name).digest()
        stored_key = hashlib.new(self.hash_name, client_key).digest()
        signature = _hmac.new(stored_key, auth_message, self.hash_name).digest()
        try:
            proof = base64.b64decode(attrs.get("p", ""))
        except ValueError:
            proof = b""
        recovered = bytes(a ^ b for a, b in zip(proof, signature))
        if (
            not self._user_ok
            or len(proof) != len(signature)
            or not _hmac.compare_digest(
                hashlib.new(self.hash_name, recovered).digest(), stored_key
            )
        ):
            return False, b"e=invalid-proof"
        server_key = _hmac.new(salted, b"Server Key", self.hash_name).digest()
        server_sig = _hmac.new(server_key, auth_message, self.hash_name).digest()
        return True, b"v=" + base64.b64encode(server_sig)


# ---------------------------------------------------------------------------
# RecordBatch v2

COMPRESSION_NONE = 0
COMPRESSION_GZIP = 1
COMPRESSION_SNAPPY = 2
COMPRESSION_LZ4 = 3
COMPRESSION_ZSTD = 4

#: (timestamp_ms, key bytes|None, value bytes|None)
RecordTuple = Tuple[int, Optional[bytes], Optional[bytes]]

#: (absolute_offset, timestamp_ms, key, value) — offsets may have gaps, as
#: log compaction leaves holes in retained batches.
OffsetRecord = Tuple[int, int, Optional[bytes], Optional[bytes]]


def encode_record_batch(
    records: List[OffsetRecord],
    compression: int = COMPRESSION_NONE,
    last_offset: Optional[int] = None,
    leader_epoch: int = -1,
) -> bytes:
    """``last_offset`` overrides the batch header's covered range (default:
    the last record's offset) — a compacted log's batches keep their
    original last_offset_delta even when the tail records were removed.
    ``leader_epoch`` stamps the header's partition_leader_epoch (outside
    the CRC, like a real broker, which rewrites it on leader change)."""
    if not records:
        return b""
    base_offset = records[0][0]
    first_ts = records[0][1]
    max_ts = max(ts for _, ts, _, _ in records)
    body = ByteWriter()
    for off, ts, key, value in records:
        rec = ByteWriter()
        rec.i8(0)  # attributes
        rec.varint(ts - first_ts)
        rec.varint(off - base_offset)
        rec.varbytes(key)
        rec.varbytes(value)
        rec.varint(0)  # headers
        rb = rec.done()
        body.varint(len(rb)).raw(rb)
    payload = body.done()
    if compression == COMPRESSION_GZIP:
        # Kafka's gzip codec is RFC-1952 gzip framing (Java GZIPOutputStream),
        # not a bare zlib stream.
        co = zlib.compressobj(wbits=31)
        payload = co.compress(payload) + co.flush()
    elif compression == COMPRESSION_SNAPPY:
        from kafka_topic_analyzer_tpu.io.compression import snappy_compress_xerial

        payload = snappy_compress_xerial(payload)
    elif compression == COMPRESSION_LZ4:
        from kafka_topic_analyzer_tpu.io.compression import lz4_compress_frame

        payload = lz4_compress_frame(payload)
    elif compression == COMPRESSION_ZSTD:
        from kafka_topic_analyzer_tpu.io.compression import zstd_compress_frame

        payload = zstd_compress_frame(payload)

    # Fields covered by the CRC (everything from attributes onward).
    crcw = ByteWriter()
    crcw.i16(compression)  # attributes (low bits = codec)
    crcw.i32(
        (last_offset if last_offset is not None else records[-1][0])
        - base_offset
    )  # last_offset_delta
    crcw.i64(first_ts).i64(max_ts)
    crcw.i64(-1).i16(-1).i32(-1)  # producer id/epoch, base sequence
    crcw.i32(len(records))
    crc_part = crcw.done() + payload
    crc = _crc32c(crc_part)  # Kafka checksums batches with CRC32-C

    head = ByteWriter()
    head.i64(base_offset)
    head.i32(4 + 1 + 4 + len(crc_part))  # batch_length: from leader_epoch on
    head.i32(leader_epoch)  # partition_leader_epoch (outside the CRC)
    head.i8(2)  # magic
    head.u32(crc)
    return head.done() + crc_part


def encode_control_batch(offset: int, ts_ms: int, commit: bool = True) -> bytes:
    """A transaction control batch (attributes bits: 0x20 control, 0x10
    transactional) holding one COMMIT/ABORT marker record.  Consumers
    never surface these as messages; offsets still advance past them."""
    key = struct.pack(">hh", 0, 1 if commit else 0)  # version, type
    value = struct.pack(">hi", 0, 0)  # version, coordinator epoch
    rec = ByteWriter()
    rec.i8(0)
    rec.varint(0)  # ts delta
    rec.varint(0)  # offset delta
    rec.varbytes(key)
    rec.varbytes(value)
    rec.varint(0)  # headers
    rb = rec.done()
    body = ByteWriter()
    body.varint(len(rb)).raw(rb)
    payload = body.done()

    crcw = ByteWriter()
    crcw.i16(0x30)  # attributes: control | transactional
    crcw.i32(0)  # last_offset_delta
    crcw.i64(ts_ms).i64(ts_ms)
    crcw.i64(-1).i16(-1).i32(-1)
    crcw.i32(1)
    crc_part = crcw.done() + payload
    head = ByteWriter()
    head.i64(offset)
    head.i32(4 + 1 + 4 + len(crc_part))
    head.i32(-1)
    head.i8(2)
    head.u32(_crc32c(crc_part))
    return head.done() + crc_part


def _encode_legacy_message(
    offset: int,
    ts_ms: int,
    key: Optional[bytes],
    value: Optional[bytes],
    magic: int,
    attributes: int = 0,
) -> bytes:
    body = bytearray([magic, attributes])
    if magic == 1:
        body += struct.pack(">q", ts_ms)
    body += struct.pack(">i", -1) if key is None else (
        struct.pack(">i", len(key)) + key
    )
    body += struct.pack(">i", -1) if value is None else (
        struct.pack(">i", len(value)) + value
    )
    msg = struct.pack(">I", zlib.crc32(bytes(body))) + bytes(body)
    return struct.pack(">qi", offset, len(msg)) + msg


def encode_message_set(
    records: List[OffsetRecord],
    magic: int = 1,
    compression: int = COMPRESSION_NONE,
    log_append_time: bool = False,
) -> bytes:
    """Legacy MessageSet v0/v1 encoder (tests / fake-broker fixtures for
    pre-0.11 segments).  Compressed sets use the wrapper-message scheme:
    the wrapper's offset is the last inner message's absolute offset, and
    magic-1 inner messages carry relative offsets starting at 0 (KIP-31);
    magic-0 inner messages keep absolute offsets."""
    if magic not in (0, 1):
        raise ValueError("legacy message sets are magic 0 or 1")
    if not records:
        return b""
    if compression == COMPRESSION_NONE:
        return b"".join(
            _encode_legacy_message(off, ts, k, v, magic)
            for off, ts, k, v in records
        )
    base = records[0][0]
    inner = b"".join(
        _encode_legacy_message(
            # KIP-31 relative offsets are deltas from the first inner
            # message (gaps from compaction are preserved), not 0..n-1.
            (off - base) if magic == 1 else off, ts, k, v, magic
        )
        for off, ts, k, v in records
    )
    from kafka_topic_analyzer_tpu.io import compression as comp_mod

    if compression == COMPRESSION_GZIP:
        co = zlib.compressobj(wbits=31)
        payload = co.compress(inner) + co.flush()
    elif compression == COMPRESSION_SNAPPY:
        payload = comp_mod.snappy_compress_xerial(inner)
    elif compression == COMPRESSION_LZ4:
        payload = comp_mod.lz4_compress_frame(inner)
    elif compression == COMPRESSION_ZSTD:
        raise ValueError("zstd requires RecordBatch v2 (magic 2)")
    else:
        raise ValueError(f"unknown compression codec {compression}")
    attrs = compression | (0x08 if (log_append_time and magic == 1) else 0)
    wrapper_ts = records[-1][1] if magic == 1 else -1
    return _encode_legacy_message(
        records[-1][0], wrapper_ts, None, payload, magic, attrs
    )


def _crc32c_py(data: bytes) -> int:
    """Pure-Python CRC32-C (reference/fallback; ~100 ms/MB)."""
    table = _CRC32C_TABLE
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


_crc32c_impl = None  # resolved once on first use (per-frame hot path)


def _crc32c(data: bytes) -> int:
    """CRC32-C (Castagnoli) — Kafka's record-batch checksum.  Uses the
    native shim when available; otherwise the Python table loop."""
    global _crc32c_impl
    if _crc32c_impl is None:
        try:
            import ctypes

            from kafka_topic_analyzer_tpu.io.native import load_library

            lib = load_library()  # sets kta_crc32c.restype
            fn = lib.kta_crc32c

            def _native_crc(d):
                if isinstance(d, bytearray):
                    # zero-copy: ctypes' default conversion accepts bytes
                    # only, but a bytearray exposes its buffer directly.
                    buf = (ctypes.c_ubyte * len(d)).from_buffer(d)
                    return int(fn(buf, ctypes.c_int64(len(d))))
                return int(fn(d, ctypes.c_int64(len(d))))

            _crc32c_impl = _native_crc
        except Exception:
            _crc32c_impl = _crc32c_py
    return _crc32c_impl(data)


def _make_crc32c_table():
    poly = 0x82F63B78
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC32C_TABLE = _make_crc32c_table()


@dataclasses.dataclass
class BatchFrame:
    """One parsed RecordBatch v2 frame with its payload decompressed —
    the shared input of the per-record Python generator and the native
    array decoder (io/native.py::decode_records_native)."""

    base_offset: int
    first_ts: int
    num_records: int
    payload: bytes
    #: One past the last offset this batch COVERS (base + last_offset_delta
    #: + 1).  On compacted topics this can exceed the last retained record's
    #: offset — the fetch loop uses it to advance past removed ranges.
    end_offset: int = -1
    #: Pre-decoded records for legacy MessageSet v0/v1 entries (magic 0/1):
    #: [(abs_offset, ts_ms, key, value)].  When set, `payload` is empty and
    #: the per-record decoders read from here (the native array decoder
    #: returns None so callers fall back).
    legacy_records: Optional[list] = None
    #: Byte range of this frame in the record-set buffer it was parsed
    #: from (-1 when unknown) — the corruption layer slices the raw frame
    #: for quarantine from these.
    byte_start: int = -1
    byte_end: int = -1
    #: The header's partition_leader_epoch (v2 frames; -1 on legacy
    #: MessageSets, which predate epochs) — the wire layer tracks the max
    #: seen per partition for KIP-320 fencing, and a REGRESSION signals a
    #: stale replica / truncated log.
    leader_epoch: int = -1


def _decode_legacy_entry(
    buf: bytes, pos: int, end: int, verify_crc: bool, depth: int = 0
) -> "list[tuple[int, int, Optional[bytes], Optional[bytes]]]":
    """One MessageSet v0/v1 entry → [(abs_offset, ts_ms, key, value)].
    Compressed entries are wrapper messages whose value is a nested
    MessageSet (exactly one level in valid data — enforced).  Offset
    rules: magic-1 wrappers carry the absolute offset of the LAST inner
    message while inner messages store relative offsets (KIP-31, gaps
    preserved); magic-0 wrappers hold absolute inner offsets."""
    if end - pos < 26:  # header(12) + crc(4) + magic+attrs(2) + klen+vlen(8)
        raise MalformedHeaderError("legacy message below minimum size")
    offset = struct.unpack_from(">q", buf, pos)[0]
    crc = struct.unpack_from(">I", buf, pos + 12)[0]
    magic = buf[pos + 16]
    attributes = buf[pos + 17]
    if verify_crc:
        actual = zlib.crc32(buf[pos + 16 : end])
        if actual != crc:
            raise CrcMismatchError(
                f"legacy message CRC mismatch at offset {offset}",
                base_offset=offset,
                crc_expected=crc,
                crc_actual=actual,
            )
    p = pos + 18
    ts_ms = -1
    if magic == 1:
        if p + 8 > end:
            raise TruncatedFrameError("truncated v1 message timestamp")
        ts_ms = struct.unpack_from(">q", buf, p)[0]
        p += 8
    if p + 4 > end:
        raise TruncatedFrameError("truncated legacy message key")
    (klen,) = struct.unpack_from(">i", buf, p)
    p += 4
    key = None
    if klen >= 0:
        if p + klen > end:
            raise TruncatedFrameError("truncated legacy message key")
        key = buf[p : p + klen]
        p += klen
    if p + 4 > end:
        raise TruncatedFrameError("truncated legacy message value")
    (vlen,) = struct.unpack_from(">i", buf, p)
    p += 4
    value = None
    if vlen >= 0:
        if p + vlen > end:
            raise TruncatedFrameError("truncated legacy message value")
        value = buf[p : p + vlen]
        p += vlen
    codec = attributes & 0x07
    if codec == COMPRESSION_NONE:
        return [(offset, ts_ms, key, value)]
    # Wrapper message: decompress and recurse into the inner MessageSet.
    if depth >= 1:
        # Valid Kafka data nests exactly one wrapper level; deeper nesting
        # would multiply the per-decompression memory cap per level.
        raise MalformedHeaderError("nested compressed wrapper messages")
    if value is None:
        raise MalformedHeaderError("compressed wrapper message with null value")
    from kafka_topic_analyzer_tpu.io.compression import decompress

    try:
        inner_buf = decompress(codec, value)
    except CorruptFrameError:
        raise
    except Exception as e:
        raise BadCompressionError(
            f"legacy wrapper message at offset {offset}: {e}",
            base_offset=offset,
        ) from e
    inner: "list[tuple[int, int, Optional[bytes], Optional[bytes]]]" = []
    ipos = 0
    while ipos + 12 <= len(inner_buf):
        (isize,) = struct.unpack_from(">i", inner_buf, ipos + 8)
        iend = ipos + 12 + isize
        if isize <= 0 or iend > len(inner_buf):
            raise TruncatedFrameError("truncated inner message set")
        inner.extend(
            _decode_legacy_entry(inner_buf, ipos, iend, verify_crc, depth + 1)
        )
        ipos = iend
    if not inner:
        return []
    if magic == 1:
        # KIP-31: wrapper offset = last inner's ABSOLUTE offset, inner
        # offsets are relative — so base = wrapper - last, unconditionally.
        # Old producers that wrote absolute inner offsets get base == 0,
        # which this handles too (the official clients do the same).
        base = offset - inner[-1][0]
        inner = [(base + o, ts, k, v) for o, ts, k, v in inner]
    if magic == 1 and attributes & 0x08:
        # LogAppendTime: the wrapper's timestamp applies to every record.
        inner = [(o, ts_ms, k, v) for o, _ts, k, v in inner]
    return inner


@dataclasses.dataclass
class CorruptSpan:
    """One poisoned byte span isolated by `salvage_batch_frames`: the
    classified error plus everything the wire layer needs to skip, account
    for, and quarantine the frame — byte bounds for the raw evidence,
    claimed offsets for the resume position."""

    error: CorruptFrameError
    start: int           # byte start of the poisoned span in the buffer
    end: int             # byte end (exclusive) — iteration resumes here
    base_offset: int = -1   # header-claimed base offset (-1 unreadable)
    claimed_end: int = -1   # base + last_offset_delta + 1 when readable
    resume_offset: int = -1  # next salvaged frame's base offset (-1 unknown)
    num_records: int = 0    # header-claimed record count when plausible

    def skip_offset(self, floor: int) -> int:
        """Offset a skip should resume the partition at, or -1 when the
        span gives no bound past ``floor`` (unskippable)."""
        return preferred_skip_offset(
            floor, self.resume_offset, self.claimed_end
        )


def preferred_skip_offset(
    floor: int, resume_offset: int, claimed_end: int
) -> int:
    """The ONE skip-bound policy (CorruptSpan.skip_offset and the wire
    layer's _note_corrupt both use it): prefer the validated next-frame
    base over the corrupt frame's own claimed coverage.  ``claimed_end``
    comes from a header that just FAILED its checksum — a bit-flipped
    last_offset_delta must not swallow the rest of the partition — while
    ``resume_offset`` was structurally (and, under check.crcs, checksum-)
    validated by the salvage resync.  Offsets between the true coverage
    and the next retained frame hold no records (compaction holes), so
    preferring resume_offset never skips data.  -1 when neither candidate
    exceeds ``floor``."""
    for candidate in (resume_offset, claimed_end):
        if candidate > floor:
            return candidate
    return -1


#: Minimum plausible v2 batch_length: the fields it covers
#: (leader_epoch+magic+crc+attrs+delta+2 ts+pid+pepoch+bseq+count).
_MIN_V2_BATCH_LENGTH = 4 + 1 + 4 + 2 + 4 + 8 + 8 + 8 + 2 + 4 + 4
#: Minimum plausible legacy message_size: crc+magic+attrs+klen+vlen.
_MIN_LEGACY_MESSAGE_SIZE = 4 + 1 + 1 + 4 + 4


def _parse_frame_at(
    buf: bytes, pos: int, end: int, verify_crc: bool
) -> Optional[BatchFrame]:
    """Parse one complete frame at ``pos`` (bounds already validated) into
    a BatchFrame — or None for an empty legacy entry.  Every failure mode
    raises a classified CorruptFrameError carrying the frame's byte span
    and whatever header fields were readable."""
    base_offset = struct.unpack_from(">q", buf, pos)[0]
    magic = buf[pos + 16]
    if magic in (0, 1):
        try:
            records = _decode_legacy_entry(buf, pos, end, verify_crc)
        except CorruptFrameError as e:
            e.span = (pos, end)
            if e.base_offset < 0:
                e.base_offset = base_offset
            if e.claimed_end < 0 and base_offset >= 0:
                # Legacy wrapper offsets are the LAST covered offset.
                e.claimed_end = base_offset + 1
            raise
        if not records:
            return None
        return BatchFrame(
            base_offset=records[0][0],
            first_ts=records[0][1],
            num_records=len(records),
            payload=b"",
            end_offset=records[-1][0] + 1,
            legacy_records=records,
            byte_start=pos,
            byte_end=end,
        )
    if magic != 2:
        raise MalformedHeaderError(
            f"unsupported record format magic={magic} (need magic <= 2)",
            base_offset=base_offset,
            span=(pos, end),
        )
    leader_epoch = struct.unpack_from(">i", buf, pos + 12)[0]
    r = ByteReader(buf, pos + 17)
    crc = r.u32()
    crc_start = r.pos
    attributes = r.i16()
    last_offset_delta = r.i32()
    first_ts = r.i64()
    r.i64()  # max_ts
    r.i64()  # producer id
    r.i16()  # producer epoch
    r.i32()  # base sequence
    num_records = r.i32()
    claimed_end = base_offset + max(last_offset_delta, 0) + 1
    if num_records < 0:
        raise MalformedHeaderError(
            f"negative record count at offset {base_offset}",
            base_offset=base_offset,
            span=(pos, end),
            claimed_end=claimed_end,
        )
    payload = buf[r.pos : end]
    if verify_crc:
        actual = _crc32c(buf[crc_start:end])
        if actual != crc:
            raise CrcMismatchError(
                f"record batch CRC mismatch at offset {base_offset}",
                base_offset=base_offset,
                span=(pos, end),
                claimed_end=claimed_end,
                num_records=num_records,
                crc_expected=crc,
                crc_actual=actual,
            )
    if attributes & 0x20:
        # Control batch (transaction commit/abort markers): consumers
        # never see these as messages — librdkafka filters them at any
        # isolation level — but their offsets ARE part of the log, so
        # the frame still advances the covered range.
        return BatchFrame(
            base_offset,
            first_ts,
            0,
            b"",
            end_offset=claimed_end,
            byte_start=pos,
            byte_end=end,
            leader_epoch=leader_epoch,
        )
    codec = attributes & 0x07
    if codec != COMPRESSION_NONE:
        from kafka_topic_analyzer_tpu.io.compression import decompress

        try:
            payload = decompress(codec, payload)
        except Exception as e:
            # Unknown codec or corrupt codec stream: classify so callers
            # (and the CLI) report one clean line — or skip/quarantine.
            raise BadCompressionError(
                f"record batch at offset {base_offset}: {e}",
                base_offset=base_offset,
                span=(pos, end),
                claimed_end=claimed_end,
                num_records=num_records,
            ) from e
    return BatchFrame(
        base_offset,
        first_ts,
        num_records,
        payload,
        end_offset=claimed_end,
        byte_start=pos,
        byte_end=end,
        leader_epoch=leader_epoch,
    )


def _plausible_frame_at(buf, q: int, n: int, verify_crc: bool) -> bool:
    """Is ``q`` a believable frame boundary?  Structural checks always;
    with ``verify_crc`` the candidate's checksum must also pass, so a
    resync cannot lock onto bytes that merely look like a header."""
    base = struct.unpack_from(">q", buf, q)[0]
    if base < 0:
        return False
    blen = struct.unpack_from(">i", buf, q + 8)[0]
    end = q + 12 + blen
    magic = buf[q + 16]
    if magic == 2:
        if blen < _MIN_V2_BATCH_LENGTH or end > n:
            return False
        if verify_crc:
            crc = struct.unpack_from(">I", buf, q + 17)[0]
            return _crc32c(buf[q + 21 : end]) == crc
        return True
    if magic in (0, 1):
        if blen < _MIN_LEGACY_MESSAGE_SIZE or end > n:
            return False
        if verify_crc:
            crc = struct.unpack_from(">I", buf, q + 12)[0]
            return zlib.crc32(buf[q + 16 : end]) == crc
        return True
    return False


def _resync(buf, pos: int, n: int, verify_crc: bool) -> "Tuple[int, int]":
    """Scan forward from a poisoned position for the next plausible frame
    boundary: (resync_byte, resume_offset).  (n, -1) when the rest of the
    buffer yields nothing — the caller then skips to the buffer end."""
    q = pos + 1
    while q + 17 <= n:
        if buf[q + 16] in (0, 1, 2) and _plausible_frame_at(
            buf, q, n, verify_crc
        ):
            return q, struct.unpack_from(">q", buf, q)[0]
        q += 1
    return n, -1


def _iter_frames(
    buf: bytes, verify_crc: bool, salvage: bool
) -> "Iterator[BatchFrame | CorruptSpan]":
    pos = 0
    n = len(buf)
    while pos + 17 <= n:  # base_offset + batch_length + leader_epoch + magic
        batch_length = struct.unpack_from(">i", buf, pos + 8)[0]
        end = pos + 12 + batch_length
        err: Optional[CorruptFrameError] = None
        frame: Optional[BatchFrame] = None
        magic = buf[pos + 16]
        min_len = (
            _MIN_LEGACY_MESSAGE_SIZE if magic in (0, 1)
            else _MIN_V2_BATCH_LENGTH
        )
        if batch_length <= 0:
            # A non-positive length is never a broker's byte-limit
            # truncation — silently stopping here would drop every frame
            # after it in the fetch response.
            err = MalformedHeaderError(
                f"non-positive batch length {batch_length} at record-set "
                f"byte {pos}",
                base_offset=struct.unpack_from(">q", buf, pos)[0],
            )
        elif magic in (0, 1, 2) and batch_length < min_len:
            # A positive length too small to hold the format's own header
            # is corruption, not truncation — and it must be rejected
            # BEFORE parsing, or the header reader would run past the
            # frame's declared end into the next frame's bytes (an
            # unclassified overrun at the buffer tail, silent garbage
            # fields mid-buffer).  The length field itself is suspect, so
            # the salvage skip re-syncs (span=None) instead of trusting it.
            err = MalformedHeaderError(
                f"batch length {batch_length} below the magic-{magic} "
                f"minimum size ({min_len}) at record-set byte {pos}",
                base_offset=struct.unpack_from(">q", buf, pos)[0],
            )
        elif end > n:
            return  # partial trailing batch (broker truncates at max_bytes)
        else:
            try:
                frame = _parse_frame_at(buf, pos, end, verify_crc)
            except CorruptFrameError as e:
                err = e
        if err is None:
            if frame is not None:
                yield frame
            pos = end
            continue
        if not salvage:
            raise err
        if err.span is not None:
            # The frame's bounds were readable: skip exactly this frame
            # using its length prefix — frames after it still decode.
            span_end = err.span[1]
            resume_q, resume_off = span_end, -1
            if span_end + 17 <= n:
                if _plausible_frame_at(buf, span_end, n, verify_crc):
                    # A validated boundary: its base offset is trustworthy.
                    resume_off = struct.unpack_from(">q", buf, span_end)[0]
                else:
                    blen_next = struct.unpack_from(">i", buf, span_end + 8)[0]
                    if blen_next > 0 and span_end + 12 + blen_next > n:
                        # Looks like the broker's trailing partial batch:
                        # stop at span_end, but offer NO resume offset —
                        # these bytes failed the plausibility check, so an
                        # i64 read from them would be arbitrary garbage.
                        pass
                    else:
                        # The claimed length lands on implausible bytes
                        # (the length field itself may be the corrupt
                        # part): fall back to the scan.
                        resume_q, resume_off = _resync(
                            buf, pos, n, verify_crc
                        )
        else:
            resume_q, resume_off = _resync(buf, pos, n, verify_crc)
        yield CorruptSpan(
            error=err,
            start=pos,
            end=resume_q,
            base_offset=err.base_offset,
            claimed_end=err.claimed_end,
            resume_offset=resume_off,
            num_records=err.num_records,
        )
        pos = max(resume_q, pos + 1)


def iter_batch_frames(buf: bytes, verify_crc: bool = False) -> Iterator[BatchFrame]:
    """Parse batch headers (CRC check, decompression) without touching
    records.  Tolerates a trailing partial batch (brokers may truncate at
    max_bytes).  Legacy MessageSet v0/v1 entries (pre-0.11 segments that
    survive on upgraded clusters) are decoded eagerly into
    ``legacy_records`` — the magic byte sits at entry offset 16 in all
    three formats, so mixed-format record sets stream through one loop.
    Corrupt frames raise a classified `CorruptFrameError`; use
    `salvage_batch_frames` to skip them instead."""
    for item in _iter_frames(buf, verify_crc, salvage=False):
        yield item  # salvage=False never yields CorruptSpan


def salvage_batch_frames(
    buf: bytes, verify_crc: bool = False
) -> "Iterator[BatchFrame | CorruptSpan]":
    """Like `iter_batch_frames`, but poisoned frames are isolated instead
    of raising: the stream yields a `CorruptSpan` for each and resumes at
    the next batch boundary.  A frame whose length prefix is intact is
    skipped exactly (payload-level damage: CRC mismatch, bad codec
    stream); when the header itself is mangled, the iterator re-syncs by
    scanning for the next plausible frame header (CRC-checked when
    ``verify_crc``, structural checks otherwise)."""
    return _iter_frames(buf, verify_crc, salvage=True)


def decode_frame_records(frame: BatchFrame) -> Iterator[Tuple[int, RecordTuple]]:
    """Per-record Python decode of one frame (reference implementation; the
    hot path uses the native array decoder).  Record-body damage — only
    reachable when the batch CRC wasn't verified or didn't cover it —
    raises classified `CorruptFrameError` subtypes carrying the frame's
    byte span, so the wire layer's skip/quarantine policy applies to
    payload corruption exactly like header corruption."""
    if frame.legacy_records is not None:
        for off, ts_ms, key, value in frame.legacy_records:
            yield off, (ts_ms, key, value)
        return
    payload = frame.payload
    rr = ByteReader(payload)
    try:
        for _ in range(frame.num_records):
            length = rr.varint()
            rec_end = rr.pos + length
            # A negative declared length would walk the reader backwards
            # (negative positions slice "successfully" in Python).
            if length < 0 or rec_end > len(payload):
                cls = MalformedHeaderError if length < 0 else TruncatedFrameError
                raise cls(
                    f"record length {length} out of range at offset "
                    f"{frame.base_offset}",
                    base_offset=frame.base_offset,
                    span=_frame_span(frame),
                    claimed_end=frame.end_offset,
                    num_records=frame.num_records,
                )
            rr.i8()  # attributes
            ts_delta = rr.varint()
            off_delta = rr.varint()
            key = rr.varbytes()
            value = rr.varbytes()
            nheaders = rr.varint()
            for _ in range(nheaders):
                hk = rr.varbytes()
                rr.varbytes()
                del hk
            rr.pos = rec_end  # tolerate unknown trailing record fields
            yield frame.base_offset + off_delta, (frame.first_ts + ts_delta, key, value)
    except CorruptFrameError:
        raise
    except KafkaProtocolError as e:
        # ByteReader overruns (truncated varint/field) inside a record body.
        raise TruncatedFrameError(
            f"corrupt record body in batch at offset {frame.base_offset}: {e}",
            base_offset=frame.base_offset,
            span=_frame_span(frame),
            claimed_end=frame.end_offset,
            num_records=frame.num_records,
        ) from e


def _frame_span(frame: BatchFrame) -> "Optional[Tuple[int, int]]":
    if frame.byte_start < 0 or frame.byte_end < 0:
        return None
    return (frame.byte_start, frame.byte_end)


def decode_record_batches(
    buf: bytes, verify_crc: bool = False
) -> Iterator[Tuple[int, RecordTuple]]:
    """Yield (absolute_offset, (timestamp_ms, key, value)) for every record."""
    for frame in iter_batch_frames(buf, verify_crc):
        yield from decode_frame_records(frame)
