"""ctypes bindings to the native C++ ingest shim (native/ingest.cpp).

Auto-builds ``libkta_ingest.so`` with the repo's Makefile on first use (g++
is part of the environment; no Python build deps needed).  The native layer
fills caller-allocated numpy buffers directly — zero copies on the Python
side — and is asserted bit-identical to the numpy generator by
tests/test_native.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional

import numpy as np

from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.records import RecordBatch

#: The C++ source ships INSIDE the package (package-data in pyproject) so
#: an installed wheel can build it on first use, not just a checkout.
_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
#: ABI version baked into the filename (see native/Makefile): a rebuild can
#: never be shadowed by a stale still-mapped library at the same path.
_ABI = 14
_SO_NAME = f"libkta_ingest.v{_ABI}.so"

#: Env knob that disables the native shim entirely (pure-Python chain
#: everywhere, including the fused decode→pack path).  Tier-1 must pass
#: with it set — every native call site keeps a reachable Python fallback
#: (tools/lint.sh rule 6).
_DISABLE_ENV = "KTA_DISABLE_NATIVE"


def _build_dir() -> str:
    """Prefer the in-tree build dir; for read-only installs (site-packages
    owned by root, containers) fall back to a per-user cache.  The cache
    key includes a hash of ingest.cpp, not just the ABI — the ABI is an
    interface version, so a source bugfix without an interface change
    must still invalidate the cached binary (in-tree builds get this from
    make's mtime check)."""
    in_tree = os.path.join(_NATIVE_DIR, "build")
    if os.access(_NATIVE_DIR, os.W_OK) or os.path.exists(
        os.path.join(in_tree, _SO_NAME)
    ):
        return in_tree
    import hashlib

    with open(os.path.join(_NATIVE_DIR, "ingest.cpp"), "rb") as f:
        src = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(
        os.path.expanduser("~"), ".cache", "kta-native", f"v{_ABI}-{src}"
    )

_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_load_error: "Exception | None" = None


class _KtaSynthSpec(ctypes.Structure):
    # Mirrors struct KtaSynthSpec in native/ingest.cpp (wire contract).
    _fields_ = [
        ("seed", ctypes.c_uint64),
        ("num_partitions", ctypes.c_int32),
        ("messages_per_partition", ctypes.c_int64),
        ("keys_per_partition", ctypes.c_uint64),
        ("key_null_permille", ctypes.c_int32),
        ("tombstone_permille", ctypes.c_int32),
        ("value_len_min", ctypes.c_int32),
        ("value_len_max", ctypes.c_int32),
        ("key_digits", ctypes.c_int32),
        ("ts_start_ms", ctypes.c_int64),
        ("ts_step_ms", ctypes.c_int64),
    ]


def _build(build_dir: str) -> None:
    os.makedirs(build_dir, exist_ok=True)
    subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-s", f"BUILD={build_dir}"],
        check=True,
        capture_output=True,
        text=True,
    )


def load_library(build_if_missing: bool = True) -> ctypes.CDLL:
    """Load (building if needed) the native shim; raises on failure.

    A failed build/load is cached ONCE, with its reason: hot paths probe
    via `native_available` without re-running `make` every time, and the
    fused-fallback telemetry / ``--stats`` digest surface the cached
    reason class (`native_status`) instead of each call site re-probing.
    """
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise _load_error
        try:
            if os.environ.get(_DISABLE_ENV):
                raise RuntimeError(
                    f"native shim disabled via {_DISABLE_ENV}"
                )
            so_path = os.path.join(_build_dir(), _SO_NAME)
            if not os.path.exists(so_path):
                if not build_if_missing:
                    raise FileNotFoundError(so_path)
                _build(os.path.dirname(so_path))
            lib = ctypes.CDLL(so_path)
            lib.kta_version.restype = ctypes.c_int32
            if lib.kta_version() != _ABI:
                raise RuntimeError(
                    f"libkta_ingest ABI mismatch: {so_path} reports "
                    f"{lib.kta_version()}, expected {_ABI}"
                )
            lib.kta_synth_batch.restype = ctypes.c_int32
            lib.kta_hash_batch.restype = ctypes.c_int32
            lib.kta_dedupe_slots.restype = ctypes.c_int64
            lib.kta_pack_batch.restype = ctypes.c_int64
            lib.kta_decode_records.restype = ctypes.c_int64
            lib.kta_scan_record_set.restype = ctypes.c_int64
            lib.kta_decode_record_set.restype = ctypes.c_int64
            lib.kta_crc32c.restype = ctypes.c_uint32
            lib.kta_pack_scratch_len.restype = ctypes.c_int64
            lib.kta_pairs_to_masks.restype = ctypes.c_int64
            lib.kta_pack_row_init.restype = ctypes.c_int64
            lib.kta_decode_pack_record_set.restype = ctypes.c_int64
            lib.kta_pack_append_columns.restype = ctypes.c_int64
        except Exception as e:  # remember the failure
            _load_error = e
            raise
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except Exception:
        return False


def native_status() -> "tuple[bool, str]":
    """(available, reason) — the cached load outcome in one probe.

    ``reason`` is a short, bounded label suitable for a metric label or a
    ``--stats`` line: ``""`` when the shim loaded, else one of
    ``disabled`` (KTA_DISABLE_NATIVE), ``build-failed`` (make error),
    ``abi-mismatch``, or ``load-failed`` (missing/undloadable .so).  The
    negative result is cached by `load_library` — probing here never
    re-runs the build."""
    if native_available():
        return True, ""
    err = _load_error
    if isinstance(err, RuntimeError) and _DISABLE_ENV in str(err):
        return False, "disabled"
    if isinstance(err, subprocess.CalledProcessError):
        return False, "build-failed"
    if isinstance(err, RuntimeError) and "ABI mismatch" in str(err):
        return False, "abi-mismatch"
    return False, "load-failed"


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def synth_batch_native(
    spec: SyntheticSpec,
    parts: np.ndarray,
    lo: int,
    hi: int,
    threads: int = 0,
) -> RecordBatch:
    """Generate records for global indices [lo, hi) via the C++ shim."""
    lib = load_library()
    n = hi - lo
    if threads <= 0:
        threads = min(os.cpu_count() or 1, 16)
    parts = np.ascontiguousarray(parts, dtype=np.int32)
    out = {name: np.empty(n, dtype=dt) for name, dt in RecordBatch.FIELDS}
    cspec = _KtaSynthSpec(
        seed=spec.seed,
        num_partitions=spec.num_partitions,
        messages_per_partition=spec.messages_per_partition,
        keys_per_partition=spec.keys_per_partition,
        key_null_permille=spec.key_null_permille,
        tombstone_permille=spec.tombstone_permille,
        value_len_min=spec.value_len_min,
        value_len_max=spec.value_len_max,
        key_digits=spec.key_digits,
        ts_start_ms=spec.ts_start_ms,
        ts_step_ms=spec.ts_step_ms,
    )
    rc = lib.kta_synth_batch(
        ctypes.byref(cspec),
        _as_ptr(parts, ctypes.c_int32),
        ctypes.c_int32(len(parts)),
        ctypes.c_int64(lo),
        ctypes.c_int64(hi),
        ctypes.c_int32(threads),
        _as_ptr(out["partition"], ctypes.c_int32),
        _as_ptr(out["key_len"], ctypes.c_int32),
        _as_ptr(out["value_len"], ctypes.c_int32),
        _as_ptr(out["key_null"], ctypes.c_uint8),
        _as_ptr(out["value_null"], ctypes.c_uint8),
        _as_ptr(out["ts_s"], ctypes.c_int64),
        _as_ptr(out["key_hash32"], ctypes.c_uint32),
        _as_ptr(out["key_hash64"], ctypes.c_uint64),
        _as_ptr(out["valid"], ctypes.c_uint8),
    )
    if rc != 0:
        raise RuntimeError(f"kta_synth_batch failed with rc={rc}")
    return RecordBatch(**out)


def hash_batch_native(
    data: bytes | np.ndarray, offsets: np.ndarray, threads: int = 0
) -> "tuple[np.ndarray, np.ndarray]":
    """Hash n packed byte slices: returns (fnv32-variant, fnv64) arrays."""
    lib = load_library()
    if threads <= 0:
        threads = min(os.cpu_count() or 1, 16)
    buf = np.frombuffer(data, dtype=np.uint8) if isinstance(data, bytes) else data
    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    n = len(offsets) - 1
    h32 = np.empty(n, dtype=np.uint32)
    h64 = np.empty(n, dtype=np.uint64)
    rc = lib.kta_hash_batch(
        _as_ptr(buf, ctypes.c_uint8),
        _as_ptr(offsets, ctypes.c_int64),
        ctypes.c_int64(n),
        ctypes.c_int32(threads),
        _as_ptr(h32, ctypes.c_uint32),
        _as_ptr(h64, ctypes.c_uint64),
    )
    if rc != 0:
        raise RuntimeError(f"kta_hash_batch failed with rc={rc}")
    return h32, h64


def dedupe_slots_native(
    h32: np.ndarray, active: np.ndarray, alive: np.ndarray, bits: int
) -> "tuple[np.ndarray, np.ndarray]":
    """Last-writer-wins (slot, aliveness) dedupe via the C++ shim.

    NOTE: pair order differs from the numpy implementation (first-touch vs
    sorted) — callers must not rely on ordering, only on the set semantics.
    """
    lib = load_library()
    n = len(h32)
    h32 = np.ascontiguousarray(h32, dtype=np.uint32)
    active = np.ascontiguousarray(active, dtype=np.uint8)
    alive = np.ascontiguousarray(alive, dtype=np.uint8)
    slot_out = np.empty(n, dtype=np.uint32)
    alive_out = np.empty(n, dtype=np.uint8)
    count = lib.kta_dedupe_slots(
        _as_ptr(h32, ctypes.c_uint32),
        _as_ptr(active, ctypes.c_uint8),
        _as_ptr(alive, ctypes.c_uint8),
        ctypes.c_int64(n),
        ctypes.c_int32(bits),
        _as_ptr(slot_out, ctypes.c_uint32),
        _as_ptr(alive_out, ctypes.c_uint8),
    )
    if count < 0:
        raise RuntimeError(f"kta_dedupe_slots failed with rc={count}")
    return slot_out[:count], alive_out[:count]


def pairs_to_masks_native(
    slots: np.ndarray,
    flags: np.ndarray,
    bits: int,
    set_out: np.ndarray,
    clear_out: np.ndarray,
) -> int:
    """LWW-apply a raw (slot, flag) pair stream — stream order, duplicates
    allowed — straight into zeroed set/clear word masks (the compacted
    alive table's MASK form, packing.alive_table_mode == 2).  Returns the
    distinct touched-slot count (emitted-pairs telemetry)."""
    lib = load_library()
    slots = np.ascontiguousarray(slots, dtype=np.uint32)
    flags = np.ascontiguousarray(flags, dtype=np.uint8)
    touched = lib.kta_pairs_to_masks(
        _as_ptr(slots, ctypes.c_uint32),
        _as_ptr(flags, ctypes.c_uint8),
        ctypes.c_int64(len(slots)),
        ctypes.c_int32(bits),
        _as_ptr(set_out, ctypes.c_uint32),
        _as_ptr(clear_out, ctypes.c_uint32),
    )
    if touched < 0:
        raise RuntimeError(f"kta_pairs_to_masks failed rc={touched}")
    return int(touched)


#: The decoder's SoA layout — ONE spec for every allocation site (per-frame
#: decode, record-set decode, the marker-only empty result).
_SOA_COLUMNS = (
    ("offsets", np.int64),
    ("ts_ms", np.int64),
    ("key_len", np.int32),
    ("value_len", np.int32),
    ("key_null", np.uint8),
    ("value_null", np.uint8),
    ("key_hash32", np.uint32),
    ("key_hash64", np.uint64),
)


def _soa_columns(n: int) -> "dict[str, np.ndarray]":
    return {k: np.empty(n, dtype=d) for k, d in _SOA_COLUMNS}


def decode_records_native(frame) -> "dict[str, np.ndarray] | None":
    """Decode one RecordBatch v2 frame (kafka_codec.BatchFrame) into SoA
    columns with key hashes computed inline — the wire client's hot half
    (the Python per-record generator manages ~225k records/s; this runs at
    tens of millions).  Returns None on malformed input so the caller can
    fall back to the Python decoder for a precise error."""
    if getattr(frame, "legacy_records", None) is not None:
        return None  # MessageSet v0/v1: the Python per-record path decodes
    lib = load_library()
    n = frame.num_records
    # num_records is an untrusted wire field: a valid record needs >= 7
    # payload bytes, so a count beyond len/7 is malformed — reject BEFORE
    # sizing eight output arrays by it (a hostile header could otherwise
    # demand ~80 GB of allocations).
    if n > max(len(frame.payload) // 7, 0):
        return None
    payload = np.frombuffer(frame.payload, dtype=np.uint8)
    out = _soa_columns(n)
    rc = lib.kta_decode_records(
        _as_ptr(payload, ctypes.c_uint8),
        ctypes.c_int64(len(payload)),
        ctypes.c_int32(n),
        ctypes.c_int64(frame.base_offset),
        ctypes.c_int64(frame.first_ts),
        _as_ptr(out["offsets"], ctypes.c_int64),
        _as_ptr(out["ts_ms"], ctypes.c_int64),
        _as_ptr(out["key_len"], ctypes.c_int32),
        _as_ptr(out["value_len"], ctypes.c_int32),
        _as_ptr(out["key_null"], ctypes.c_uint8),
        _as_ptr(out["value_null"], ctypes.c_uint8),
        _as_ptr(out["key_hash32"], ctypes.c_uint32),
        _as_ptr(out["key_hash64"], ctypes.c_uint64),
    )
    if rc != n:
        return None
    return out


def scan_record_set_native(
    buf, verify_crc: bool = False
) -> "tuple[int, int, int]":
    """Header-jump walk of a record set's native-decodable prefix:
    (record_count, consumed_bytes, covered_end) without touching records.
    The wire client's send-ahead uses covered_end as the speculative next
    fetch offset while the full decode proceeds."""
    lib = load_library()
    data = np.frombuffer(buf, dtype=np.uint8)
    consumed = ctypes.c_int64(0)
    covered = ctypes.c_int64(-1)
    n = lib.kta_scan_record_set(
        _as_ptr(data, ctypes.c_uint8),
        ctypes.c_int64(len(data)),
        ctypes.c_int32(1 if verify_crc else 0),
        ctypes.byref(consumed),
        ctypes.byref(covered),
    )
    if n < 0:
        return 0, 0, -1
    return int(n), int(consumed.value), int(covered.value)


def decode_record_set_native(
    buf,
    verify_crc: bool = False,
    prescan: "tuple[int, int, int] | None" = None,
) -> "tuple[dict[str, np.ndarray], int, int] | None":
    """Decode the native-decodable PREFIX of a whole fetch record set
    (consecutive complete uncompressed v2 frames) in one C++ call.

    Returns (SoA columns, consumed_bytes, covered_end) — covered_end is
    the compaction-aware max of base_offset+last_offset_delta+1 across
    decoded frames (-1 when none).  None when the shim is unavailable.
    Frames past `consumed` (compressed, legacy MessageSet, truncated tail,
    malformed) are the caller's per-frame path; a malformed frame inside
    the prefix returns consumed=0 so that path can raise precisely.

    ``prescan``: a scan_record_set_native result for this buffer, so a
    caller that already walked the headers (the send-ahead speculation)
    doesn't pay the scan — or its CRC pass — a second time."""
    lib = load_library()
    data = np.frombuffer(buf, dtype=np.uint8)
    consumed = ctypes.c_int64(0)
    scan_covered = ctypes.c_int64(-1)
    if prescan is not None:
        n = prescan[0]
        verify_crc = False  # the prescan already checksummed the prefix
        consumed.value, scan_covered.value = prescan[1], prescan[2]
    else:
        n = lib.kta_scan_record_set(
            _as_ptr(data, ctypes.c_uint8),
            ctypes.c_int64(len(data)),
            ctypes.c_int32(1 if verify_crc else 0),
            ctypes.byref(consumed),
            ctypes.byref(scan_covered),
        )
    if n < 0:
        return {}, 0, -1
    if n == 0:
        # No messages in the decodable prefix, but it may still cover
        # offsets (a transaction-marker-only stretch): the caller must
        # advance past it, so consumed/covered ride along with empty
        # columns.
        return _soa_columns(0), int(consumed.value), int(scan_covered.value)
    out = _soa_columns(n)
    covered = ctypes.c_int64(-1)
    rc = lib.kta_decode_record_set(
        _as_ptr(data, ctypes.c_uint8),
        ctypes.c_int64(len(data)),
        ctypes.c_int32(1 if verify_crc else 0),
        ctypes.c_int64(n),
        _as_ptr(out["offsets"], ctypes.c_int64),
        _as_ptr(out["ts_ms"], ctypes.c_int64),
        _as_ptr(out["key_len"], ctypes.c_int32),
        _as_ptr(out["value_len"], ctypes.c_int32),
        _as_ptr(out["key_null"], ctypes.c_uint8),
        _as_ptr(out["value_null"], ctypes.c_uint8),
        _as_ptr(out["key_hash32"], ctypes.c_uint32),
        _as_ptr(out["key_hash64"], ctypes.c_uint64),
        ctypes.byref(consumed),
        ctypes.byref(covered),
    )
    if rc != n:
        return {}, 0, -1  # malformed inside prefix: per-frame path reports
    return out, int(consumed.value), int(covered.value)


def _pallas_value_cap(config) -> int:
    """The 16 MiB value-length cap exists for the v4 MXU kernel's 12-bit
    digit decomposition only; under wire v5 no per-record value length
    reaches a pallas kernel (the counter fold ships pre-reduced), so the
    cap must not reject v5 scans."""
    from kafka_topic_analyzer_tpu.packing import MAX_VALUE_LEN

    return (
        MAX_VALUE_LEN
        if config.use_pallas_counters and config.wire_format == 4
        else 0
    )


def _quant_section(config) -> "tuple[int, int, np.ndarray | None]":
    """(q_rows, q_nbuckets, edges) for the wire-v5 DDSketch section —
    (0, 0, None) when the config ships no quantile table.  The edge array
    is the ddsketch_edges lru-cached singleton, so the pointer handed to
    C++ stays alive for the process lifetime."""
    if config.wire_format != 5 or not config.enable_quantiles:
        return 0, 0, None
    from kafka_topic_analyzer_tpu.ops.ddsketch import ddsketch_edges

    q_rows = config.num_partitions if config.quantiles_per_partition else 1
    return (
        q_rows,
        config.quantile_buckets,
        ddsketch_edges(config.quantile_gamma, config.quantile_buckets),
    )


def _edges_ptr(edges: "np.ndarray | None"):
    if edges is None:
        return ctypes.POINTER(ctypes.c_int64)()
    return _as_ptr(edges, ctypes.c_int64)


def pack_batch_native(
    batch, config, out: "np.ndarray | None" = None
) -> "np.ndarray | None":
    """Fused SoA→wire-format packing in C++ (see packing.py for the v4/v5
    layout contracts).  Returns None when the shim rejects the batch (out
    of range values) so the numpy path can raise its descriptive error.
    ``out`` packs into a caller-provided contiguous ``uint8[packed_nbytes]``
    buffer (e.g. a SuperbatchStager row) instead of allocating one — note
    that a rejected batch may leave partial bytes in it (the numpy
    fallback overwrites every byte before raising or returning)."""
    from kafka_topic_analyzer_tpu.packing import (
        hll_table_rows,
        hll_wire_mode,
        packed_nbytes,
    )

    lib = load_library()
    b = config.batch_size
    n = len(batch)
    if n > b:
        raise ValueError(f"batch of {n} exceeds batch_size {b}")
    hll_rows = hll_table_rows(config, b)
    q_rows, q_nb, edges = _quant_section(config)
    if out is None:
        out = np.empty(packed_nbytes(config, b), dtype=np.uint8)
    elif (
        out.shape != (packed_nbytes(config, b),)
        or out.dtype != np.uint8
        or not out.flags.c_contiguous
    ):
        raise ValueError(
            "pack_batch_native out= must be contiguous uint8[packed_nbytes]"
        )
    c = np.ascontiguousarray  # strided views would be read with wrong strides
    nbytes = lib.kta_pack_batch(
        _as_ptr(c(batch.partition), ctypes.c_int32),
        _as_ptr(c(batch.key_len), ctypes.c_int32),
        _as_ptr(c(batch.value_len), ctypes.c_int32),
        _as_ptr(c(batch.key_null).view(np.uint8), ctypes.c_uint8),
        _as_ptr(c(batch.value_null).view(np.uint8), ctypes.c_uint8),
        _as_ptr(c(batch.ts_s), ctypes.c_int64),
        _as_ptr(c(batch.key_hash32), ctypes.c_uint32),
        _as_ptr(c(batch.key_hash64), ctypes.c_uint64),
        ctypes.c_int64(batch.num_valid),
        ctypes.c_int64(b),
        ctypes.c_int32(config.num_partitions),
        # Under pair compaction the row carries no pair sections; the
        # caller dedupes the columns separately (packing.batch_alive_pairs)
        # so this whole-batch packer runs with alive OFF.
        ctypes.c_int32(
            0
            if getattr(config, "compact_alive", False)
            else (1 if config.count_alive_keys else 0)
        ),
        ctypes.c_int32(config.alive_bitmap_bits),
        ctypes.c_int32(hll_wire_mode(config, b)),
        ctypes.c_int32(config.hll_p),
        ctypes.c_int32(hll_rows),
        ctypes.c_int32(_pallas_value_cap(config)),
        ctypes.c_int32(1 if config.wire_format == 5 else 0),
        ctypes.c_int32(q_rows),
        ctypes.c_int32(q_nb),
        _edges_ptr(edges),
        _as_ptr(out, ctypes.c_uint8),
        ctypes.c_int64(out.nbytes),
    )
    if nbytes < 0:
        return None
    assert nbytes == out.nbytes, (nbytes, out.nbytes)
    return out


# ---------------------------------------------------------------------------
# fused decode→pack (native/ingest.cpp fused entry points)
#
# One GIL-released C++ pass from raw record-set bytes (or already-decoded
# SoA columns on the fallback half) straight into a wire-v4 packed row —
# the SoA materialization between kta_decode_record_set and kta_pack_batch
# never happens.  packing.FusedPackSink owns row/scratch lifecycle; these
# are the thin ctypes wrappers.  Every caller keeps a reachable
# python-chain fallback (lint rule 6): a missing shim degrades to the
# decode→RecordBatch→pack_batch chain, never to an error.


def _fused_pack_params(config, batch_size: int) -> "tuple":
    """The (b, P, with_alive, alive_bits, with_hll, hll_p, hll_rows, vcap,
    wire_v5, q_rows, q_nbuckets, edges) tail shared by the fused entry
    points — derived through the same packing.py rules as
    pack_batch_native, so the fused row layout can never skew from the
    chained one."""
    from kafka_topic_analyzer_tpu.packing import hll_table_rows, hll_wire_mode

    q_rows, q_nb, edges = _quant_section(config)
    return (
        batch_size,
        config.num_partitions,
        _with_alive_mode(config),
        config.alive_bitmap_bits,
        hll_wire_mode(config, batch_size),
        config.hll_p,
        hll_table_rows(config, batch_size),
        _pallas_value_cap(config),
        1 if config.wire_format == 5 else 0,
        q_rows,
        q_nb,
        edges,
    )


def _fused_ctail(params) -> "list":
    b, P, wa, ab, wh, hp, hr, vc, v5, qr, qn, edges = params
    return [
        ctypes.c_int64(b), ctypes.c_int32(P), ctypes.c_int32(wa),
        ctypes.c_int32(ab), ctypes.c_int32(wh), ctypes.c_int32(hp),
        ctypes.c_int32(hr), ctypes.c_int32(vc), ctypes.c_int32(v5),
        ctypes.c_int32(qr), ctypes.c_int32(qn), _edges_ptr(edges),
    ]


def _with_alive_mode(config) -> int:
    """The fused pass's alive mode: 0 = off, 1 = per-row pair sections,
    2 = compacted (pairs divert to the scratch emission region and the
    dispatch ships one merged pair table — AnalyzerConfig.compact_alive)."""
    if not config.count_alive_keys:
        return 0
    return 2 if getattr(config, "compact_alive", False) else 1


def _raise_pack_range(field: int, value: int) -> None:
    """Map the fused pass's pack-range error detail onto the SAME
    ValueError messages packing.pack_batch raises, so a scan aborts
    identically whichever path met the out-of-range record."""
    from kafka_topic_analyzer_tpu.packing import MAX_KEY_LEN, MAX_VALUE_LEN

    if value < 0:
        raise ValueError("negative key/value length in record batch")
    if field == 0:
        raise ValueError(
            f"key length {int(value)} exceeds the packed "
            f"transfer limit of {MAX_KEY_LEN} bytes"
        )
    raise ValueError(
        f"value length {int(value)} exceeds the Pallas "
        f"counter kernel's limit of {MAX_VALUE_LEN} bytes — disable "
        f"use_pallas_counters for such topics"
    )


def pack_scratch_len(config, batch_size: int) -> int:
    """int64 elements of append scratch one fused row needs (includes the
    compacted-pair emission region under AnalyzerConfig.compact_alive)."""
    lib = load_library()
    n = lib.kta_pack_scratch_len(
        ctypes.c_int64(batch_size),
        ctypes.c_int32(_with_alive_mode(config)),
        ctypes.c_int32(config.alive_bitmap_bits),
    )
    if n < 0:
        raise RuntimeError("kta_pack_scratch_len rejected batch_size")
    return int(n)


def pack_take_pairs(
    scratch: np.ndarray, config, batch_size: int
) -> "tuple[np.ndarray, np.ndarray]":
    """Copy the current row's compacted (slot, alive) pairs out of the
    fused scratch's emission region (with_alive mode 2).  Callers harvest
    BEFORE the next ``pack_row_init`` re-initializes the scratch; the
    returned arrays are copies, safe past the row's lifetime.  The region
    sits exactly ``kta_pack_scratch_len(b, 1, bits)`` int64 elements in —
    the with_alive == 1 length, by the shim's layout contract."""
    lib = load_library()
    n = int(scratch[1])
    off = int(
        lib.kta_pack_scratch_len(
            ctypes.c_int64(batch_size),
            ctypes.c_int32(1),
            ctypes.c_int32(config.alive_bitmap_bits),
        )
    )
    region = scratch[off:].view(np.uint8)
    slots = region[: 4 * batch_size].view(np.uint32)[:n].copy()
    flags = region[4 * batch_size : 5 * batch_size][:n].copy()
    return slots, flags


def pack_row_init(out: np.ndarray, scratch: np.ndarray, config,
                  batch_size: int) -> None:
    """Initialize a wire-v4 row for incremental fused appends.  The
    initialized row is byte-identical to a packed empty batch, so it
    doubles as the partial-row / superbatch identity pad."""
    lib = load_library()
    need = lib.kta_pack_row_init(
        _as_ptr(out, ctypes.c_uint8),
        ctypes.c_int64(out.nbytes),
        _as_ptr(scratch, ctypes.c_int64),
        ctypes.c_int64(len(scratch)),
        *_fused_ctail(_fused_pack_params(config, batch_size)),
    )
    if need != out.nbytes:
        raise RuntimeError(
            f"kta_pack_row_init layout mismatch: need={need}, "
            f"buffer={out.nbytes}"
        )


def decode_pack_record_set_native(
    data: np.ndarray,
    out: np.ndarray,
    scratch: np.ndarray,
    config,
    batch_size: int,
    dense_partition: int,
    min_off: int,
    max_off: int,
    verify_crc: bool = False,
    start_pos: int = 0,
    skip: int = 0,
) -> "tuple[int, int, int, int, int, bool, int]":
    """Fused decode→pack over a record set's native-decodable prefix.

    Returns ``(appended, consumed, covered_end, last_off, last_ts_s,
    row_full, resume_skip)`` — on ``row_full`` the caller rotates rows and
    re-calls with ``start_pos=consumed, skip=resume_skip``.  A malformed
    frame ends the walk at its boundary (the per-frame python chain
    classifies it from ``consumed``); a record the wire-v4 layout cannot
    carry raises the same ValueError the numpy packer would."""
    lib = load_library()
    st = np.zeros(8, dtype=np.int64)
    st[4] = skip
    rc = lib.kta_decode_pack_record_set(
        _as_ptr(data, ctypes.c_uint8),
        ctypes.c_int64(len(data)),
        ctypes.c_int32(1 if verify_crc else 0),
        ctypes.c_int64(start_pos),
        ctypes.c_int64(min_off),
        ctypes.c_int64(max_off),
        ctypes.c_int32(dense_partition),
        *_fused_ctail(_fused_pack_params(config, batch_size)),
        _as_ptr(out, ctypes.c_uint8),
        ctypes.c_int64(out.nbytes),
        _as_ptr(scratch, ctypes.c_int64),
        _as_ptr(st, ctypes.c_int64),
    )
    if rc == -2:
        _raise_pack_range(int(st[6]), int(st[7]))
    if rc < 0:
        raise RuntimeError(f"kta_decode_pack_record_set failed rc={rc}")
    return (
        int(rc), int(st[0]), int(st[1]), int(st[2]), int(st[3]),
        bool(st[5]), int(st[4]),
    )


def pack_append_columns_native(
    out: np.ndarray,
    scratch: np.ndarray,
    config,
    batch_size: int,
    dense_partition: int,
    key_len: np.ndarray,
    value_len: np.ndarray,
    key_null: np.ndarray,
    value_null: np.ndarray,
    ts: np.ndarray,
    key_hash32: np.ndarray,
    key_hash64: np.ndarray,
    start: int,
    n: int,
    ts_mode: int = 0,
) -> int:
    """Append records ``[start, n)`` — ``n`` is the EXCLUSIVE end index
    into the columns, not a count — of single-partition SoA columns into a
    fused row (stops at row capacity; returns appended count).
    ``ts_mode``: 0 = ts[] already seconds, 1 = ms floor-divided (segment
    reader rule), 2 = ms clamped at 0 then divided (wire decoder rule)."""
    lib = load_library()
    c = np.ascontiguousarray
    detail = np.zeros(2, dtype=np.int64)
    rc = lib.kta_pack_append_columns(
        _as_ptr(out, ctypes.c_uint8),
        ctypes.c_int64(out.nbytes),
        _as_ptr(scratch, ctypes.c_int64),
        ctypes.c_int32(dense_partition),
        _as_ptr(c(key_len, dtype=np.int32), ctypes.c_int32),
        _as_ptr(c(value_len, dtype=np.int32), ctypes.c_int32),
        _as_ptr(c(key_null).view(np.uint8), ctypes.c_uint8),
        _as_ptr(c(value_null).view(np.uint8), ctypes.c_uint8),
        _as_ptr(c(ts, dtype=np.int64), ctypes.c_int64),
        ctypes.c_int32(ts_mode),
        _as_ptr(c(key_hash32, dtype=np.uint32), ctypes.c_uint32),
        _as_ptr(c(key_hash64, dtype=np.uint64), ctypes.c_uint64),
        ctypes.c_int64(start),
        ctypes.c_int64(n),
        *_fused_ctail(_fused_pack_params(config, batch_size)),
        _as_ptr(detail, ctypes.c_int64),
    )
    if rc == -2:
        _raise_pack_range(int(detail[0]), int(detail[1]))
    if rc < 0:
        raise RuntimeError(f"kta_pack_append_columns failed rc={rc}")
    return int(rc)


class NativeSyntheticSource(SyntheticSource):
    """SyntheticSource with generation delegated to the C++ shim.

    Identical stream to the numpy implementation (asserted by parity tests);
    an order of magnitude faster, which matters when the host generator must
    keep a TPU fed (SURVEY.md §7 hard part (a)).
    """

    def __init__(self, spec: SyntheticSpec, threads: int = 0):
        super().__init__(spec)
        self.threads = threads
        load_library()  # fail fast if the shim cannot be built

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: "Optional[dict[int, int]] | None" = None,
    ) -> Iterator[RecordBatch]:
        parts = np.array(
            sorted(partitions) if partitions is not None else self.partitions(),
            dtype=np.int32,
        )
        if len(parts) == 0:
            return
        n = self.spec.messages_per_partition
        if start_at:
            # Partition-sequential resume: with a single partition, the
            # global index equals the offset.
            for p in parts.tolist():
                one = np.array([p], dtype=np.int32)
                for lo in range(min(start_at.get(p, 0), n), n, batch_size):
                    yield synth_batch_native(
                        self.spec, one, lo, min(lo + batch_size, n), self.threads
                    )
            return
        total = n * len(parts)
        for lo in range(0, total, batch_size):
            yield synth_batch_native(
                self.spec, parts, lo, min(lo + batch_size, total), self.threads
            )
