"""Multi-topic fan-in source (BASELINE.json config 5).

The reference analyzes exactly one topic per run.  Fan-in generalizes the
data-parallel axis: each (topic, partition) pair becomes one dense row of
the counter matrix, so T topics scan concurrently through one backend —
across the mesh they shard like any other partitions, and the merged state
yields both per-topic reports (row slices) and a cross-topic union (column
sums / sketch merges, which are associative by design).

`MultiTopicSource` wraps per-topic sources and remaps their true partition
ids into disjoint dense row ranges; `rows_for(topic)` recovers the slice for
per-topic reporting.

**Alive-key semantics under fan-in.**  The alive bitmap's last-writer-wins
update is only well-defined along a single partition's offset order; the
same key living in two topics has no global order (and its rows may land on
different mesh shards), so a raw shared bitmap would give mesh- and
interleaving-dependent counts.  Fan-in therefore *salts* the 32-bit slot
hash per topic (a bijection per topic, preserving within-topic collision
statistics): aliveness is tracked per (topic, key), every slot is owned by
exactly one topic's partitions, and the reported number is the
**sum of per-topic alive keys** — deterministic on any mesh.  The 64-bit
hash is left unsalted: HLL distinct counting is insertion-only (order- and
shard-insensitive), so the distinct-keys line remains a true cross-topic
union.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.records import RecordBatch


class MultiTopicSource(RecordSource):
    def __init__(self, topic_sources: "list[tuple[str, RecordSource]]"):
        if not topic_sources:
            raise ValueError("need at least one topic")
        names = [t for t, _ in topic_sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate topic names in fan-in: {names}")
        if any(not t for t in names):
            raise ValueError("empty topic name in fan-in")
        self.topic_sources = topic_sources
        #: (topic, true_partition) per dense row, topics in given order.
        self.rows: List[Tuple[str, int]] = []
        self._row_of: Dict[Tuple[str, int], int] = {}
        #: Per-topic bijective salt for the 32-bit bitmap slot hash (see
        #: module docstring); topic index 0 keeps the identity so a 1-topic
        #: fan-in behaves exactly like a plain scan.
        self._salt32: Dict[str, int] = {}
        for i, (topic, src) in enumerate(topic_sources):
            from kafka_topic_analyzer_tpu.ops.fnv import splitmix64

            self._salt32[topic] = (splitmix64(i) & 0xFFFFFFFF) if i else 0
            for p in src.partitions():
                self._row_of[(topic, p)] = len(self.rows)
                self.rows.append((topic, p))

    def rows_for(self, topic: str) -> List[int]:
        return [i for i, (t, _) in enumerate(self.rows) if t == topic]

    def true_partition(self, row: int) -> int:
        return self.rows[row][1]

    # -- RecordSource --------------------------------------------------------

    def partitions(self) -> List[int]:
        return list(range(len(self.rows)))

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        start: Dict[int, int] = {}
        end: Dict[int, int] = {}
        for topic, src in self.topic_sources:
            s, e = src.watermarks()
            for p, v in s.items():
                start[self._row_of[(topic, p)]] = v
            for p, v in e.items():
                end[self._row_of[(topic, p)]] = v
        return start, end

    def offsets_for_timestamp(self, ts_ms: int) -> Dict[int, int]:
        """Per-row first offset with record ts >= ts_ms (broker timestamp
        index per topic, remapped into dense row space)."""
        out: Dict[int, int] = {}
        for topic, src in self.topic_sources:
            for p, off in src.offsets_for_timestamp(ts_ms).items():
                out[self._row_of[(topic, p)]] = off
        return out

    def degraded_partitions(self) -> Dict[int, str]:
        """Degraded rows across the fan-in, keyed by dense row id (the
        partition-id space this source exposes), reasons prefixed with the
        owning topic."""
        out: Dict[int, str] = {}
        for topic, src in self.topic_sources:
            for p, reason in src.degraded_partitions().items():
                out[self._row_of[(topic, p)]] = f"{topic}/{p}: {reason}"
        return out

    def corruption_stats(self) -> Dict[int, dict]:
        """Corruption accounting across the fan-in, keyed by dense row id
        like `degraded_partitions`; spans gain ``topic``/``topic_partition``
        so `seed_corrupt_spans` can route them back."""
        out: Dict[int, dict] = {}
        for topic, src in self.topic_sources:
            for p, d in src.corruption_stats().items():
                row = self._row_of[(topic, p)]
                d = dict(d, topic=topic)
                d["spans"] = [
                    dict(s, partition=row, topic=topic, topic_partition=p)
                    for s in d.get("spans", [])
                ]
                out[row] = d
        return out

    def corruption_spans(self) -> "list[dict]":
        return [
            dict(
                s,
                partition=self._row_of[(topic, s["partition"])],
                topic=topic,
                topic_partition=s["partition"],
            )
            for topic, src in self.topic_sources
            for s in src.corruption_spans()
        ]

    def seed_corrupt_spans(self, spans: "list[dict]") -> None:
        by_topic: Dict[str, list] = {}
        for s in spans:
            topic = s.get("topic")
            if topic is not None and "topic_partition" in s:
                by_topic.setdefault(topic, []).append(
                    dict(s, partition=int(s["topic_partition"]))
                )
                continue
            row = int(s["partition"])  # pre-fan-in snapshot shape: row id
            if 0 <= row < len(self.rows):
                t, p = self.rows[row]
                by_topic.setdefault(t, []).append(dict(s, partition=p))
        for topic, src in self.topic_sources:
            seed = getattr(src, "seed_corrupt_spans", None)
            if seed is not None and topic in by_topic:
                seed(by_topic[topic])

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
    ) -> Iterator[RecordBatch]:
        rows = partitions if partitions is not None else self.partitions()
        wanted = set(rows)
        for topic, src in self.topic_sources:
            sub_parts = [
                p for p in src.partitions() if self._row_of[(topic, p)] in wanted
            ]
            if not sub_parts:
                continue
            sub_start = None
            if start_at:
                sub_start = {
                    p: start_at[self._row_of[(topic, p)]]
                    for p in sub_parts
                    if self._row_of[(topic, p)] in start_at
                }
            remap = np.full(max(sub_parts) + 1, -1, dtype=np.int32)
            for p in sub_parts:
                remap[p] = self._row_of[(topic, p)]
            salt = np.uint32(self._salt32[topic])
            for batch in src.batches(batch_size, partitions=sub_parts, start_at=sub_start):
                batch.partition = remap[batch.partition]
                if salt:
                    keyed = ~batch.key_null
                    batch.key_hash32 = np.where(
                        keyed, batch.key_hash32 ^ salt, batch.key_hash32
                    )
                yield batch

    def close(self) -> None:
        for _, src in self.topic_sources:
            if hasattr(src, "close"):
                src.close()
