"""Minimal Kafka producer — just enough to seed a LIVE broker for the
integration tier.

The reference was validated against a real cluster (the published
demo_output.png run, /root/reference/README.md:27-28); SURVEY.md §4 keeps
that tier in the test strategy.  This repo's analyzer is consumer-only by
design (io/kafka_wire.py:11-15), so end-to-end validation against a broker
we didn't write needs a way to put KNOWN records into a topic first.  That
is this module's whole job; it is a test rig, not a product surface — no
batching, retries, idempotence, or transactions.

Wire format: ApiVersions-negotiated CreateTopics (v0–v4 classic) and
Produce (v3–v7 classic; v3 is the Kafka 4.0 / KIP-896 floor, and v7 is
the ceiling this parser actually consumes — v8 appended per-partition
``record_errors``/``error_message`` fields the response loop below does
not read, so negotiating it would desync the connection).  Record sets
are encoded by the same ``kafka_codec.encode_record_batch`` the fake broker
uses, so the bytes a live broker stores are the bytes the decode path is
golden-locked against (tests/test_golden.py).

Used by tests/test_live_broker.py (gated on KTA_KAFKA_BOOTSTRAP; see
ROADMAP.md "Real-broker integration" for the environment verdict).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import (
    BrokerConnection,
    parse_bootstrap,
)

API_PRODUCE = 0
API_CREATE_TOPICS = 19

ERR_TOPIC_ALREADY_EXISTS = 36

#: (ts_ms, key, value) — offsets are assigned by the broker; the encoder is
#: fed offsets 0..n-1 so the batch header's base_offset/deltas are what a
#: producer must send (base 0, delta = position in batch).
ProduceRecord = Tuple[int, Optional[bytes], Optional[bytes]]


def _negotiated(conn: BrokerConnection, api_key: int,
                lo: int, hi: int) -> int:
    """Highest version in [lo, hi] the broker advertises for api_key.

    One ApiVersions v0 round-trip, cached on the connection.  v0 is
    universally supported (KIP-511 keeps its response header v0 forever),
    and this module never needs flexible encodings, so the consumer
    client's downgrade dance (kafka_wire.py:520-575) is not replicated.
    """
    if conn.api_versions is None:
        r = conn.request(kc.API_VERSIONS, 0, kc.encode_api_versions_request(0))
        conn.api_versions = kc.decode_api_versions_response(r, 0)
    vmin, vmax = conn.api_versions.get(api_key, (lo, lo))
    v = min(hi, vmax)
    if v < max(lo, vmin):
        raise kc.KafkaProtocolError(
            f"broker offers api {api_key} v{vmin}-{vmax}; "
            f"this producer speaks v{lo}-{hi}"
        )
    return v


def create_topic(bootstrap: str, topic: str, partitions: int,
                 replication: int = 1, timeout_ms: int = 30_000) -> None:
    """CreateTopics via the first reachable bootstrap broker.

    TOPIC_ALREADY_EXISTS is tolerated (idempotent test setup); any other
    per-topic error raises.  Real clusters route CreateTopics to the
    controller; single-node test brokers (the gated tier's target) ARE the
    controller, and a NOT_CONTROLLER error from a bigger cluster raises
    with the broker's own message rather than chasing controller metadata.
    """
    host, port = parse_bootstrap(bootstrap)[0]
    conn = BrokerConnection(host, port)
    try:
        v = _negotiated(conn, API_CREATE_TOPICS, 0, 4)
        w = kc.ByteWriter()
        w.i32(1).string(topic).i32(partitions).i16(replication)
        w.i32(0)  # assignments: broker-chosen
        w.i32(0)  # configs: broker defaults
        w.i32(timeout_ms)
        if v >= 1:
            w.i8(0)  # validate_only=false
        r = conn.request(API_CREATE_TOPICS, v, w.done())
        if v >= 2:
            r.i32()  # throttle_time_ms
        for _ in range(r.i32()):
            name = r.string()
            err = r.i16()
            msg = r.string() if v >= 1 else None
            if err not in (0, ERR_TOPIC_ALREADY_EXISTS):
                raise kc.KafkaProtocolError(
                    f"CreateTopics('{name}') failed: error {err}"
                    + (f" ({msg})" if msg else "")
                )
    finally:
        conn.close()


def encode_produce_request(topic: str, partition: int, record_set: bytes,
                           acks: int = -1,
                           timeout_ms: int = 30_000) -> "kc.ByteWriter":
    """Produce v3–v7 body (the schema is identical across that range):
    transactional_id, acks, timeout, one topic, one partition."""
    w = kc.ByteWriter()
    w.string(None)          # transactional_id
    w.i16(acks)
    w.i32(timeout_ms)
    w.i32(1).string(topic)  # topic_data[1]
    w.i32(1).i32(partition)  # partition_data[1]
    w.bytes_(record_set)
    return w


def produce(bootstrap: str, topic: str,
            partition_records: Dict[int, List[ProduceRecord]],
            timeout_ms: int = 30_000) -> Dict[int, int]:
    """Produce each partition's records (one batch per partition, acks=-1,
    uncompressed) and return partition → broker-assigned base offset.

    Leaders are resolved through a negotiated Metadata round-trip (v5 on
    modern brokers, v1 legacy) so multi-node clusters work; the single
    connection is reused for every partition a broker leads.
    """
    host, port = parse_bootstrap(bootstrap)[0]
    boot = BrokerConnection(host, port)
    conns: "Dict[int, BrokerConnection]" = {}
    try:
        # Negotiated like everything else: v1 is gone from Kafka 4.0
        # brokers (KIP-896; v5 is the classic floor there), and this
        # module never needs the flexible v9+ encodings.
        mv = _negotiated(boot, kc.API_METADATA, 1, 5)
        # A topic created moments ago may report LEADER_NOT_AVAILABLE /
        # leader=-1 until election propagates — the standard race on a
        # real cluster (the consumer side retries it too,
        # kafka_wire.py's leaderless-partition handling).  Bounded retry,
        # then a clear error naming the stuck partitions.
        deadline = time.monotonic() + 30.0
        while True:
            meta = kc.decode_metadata_response(
                boot.request(kc.API_METADATA, mv,
                             kc.encode_metadata_request([topic], mv)),
                mv,
            )
            (tmeta,) = [t for t in meta.topics if t.name == topic]
            if tmeta.error:
                raise kc.KafkaProtocolError(
                    f"Metadata('{topic}') error {tmeta.error}"
                )
            leaderless = [
                p.partition for p in tmeta.partitions
                if p.error or p.leader < 0 or p.leader not in meta.brokers
            ]
            if not leaderless:
                break
            if time.monotonic() >= deadline:
                raise kc.KafkaProtocolError(
                    f"topic '{topic}' partitions {sorted(leaderless)} "
                    "still leaderless after 30s"
                )
            time.sleep(0.5)
        leaders = {p.partition: p.leader for p in tmeta.partitions}
        base_offsets: "Dict[int, int]" = {}
        for pid, recs in sorted(partition_records.items()):
            if pid not in leaders:
                raise kc.KafkaProtocolError(
                    f"partition {pid} not in topic '{topic}' metadata"
                )
            node = leaders[pid]
            if node not in conns:
                nh, np_ = meta.brokers[node]
                conns[node] = BrokerConnection(nh, np_)
            conn = conns[node]
            # Ceiling v7: the parse loop below consumes exactly the
            # v3–v7 partition_response schema.  v8 appended record_errors
            # + error_message per partition; negotiating it without
            # parsing that tail would leave unread bytes on the
            # connection and desync every later request.
            v = _negotiated(conn, API_PRODUCE, 3, 7)
            record_set = kc.encode_record_batch(
                [(i, ts, k, val) for i, (ts, k, val) in enumerate(recs)]
            )
            r = conn.request(
                API_PRODUCE, v,
                encode_produce_request(topic, pid, record_set,
                                       timeout_ms=timeout_ms).done(),
            )
            # Invariant this parse loop relies on: each request carries
            # exactly ONE topic with ONE partition (encode_produce_request
            # builds it that way), so the nested loops run once each and
            # `rp == pid` always matches the partition just produced —
            # a multi-partition request would need per-entry routing of
            # base offsets and errors.
            for _ in range(r.i32()):       # responses[]
                r.string()                 # topic
                for _ in range(r.i32()):   # partition_responses[]
                    rp = r.i32()
                    err = r.i16()
                    base = r.i64()
                    r.i64()                # log_append_time
                    if v >= 5:
                        r.i64()            # log_start_offset
                    if err:
                        raise kc.KafkaProtocolError(
                            f"Produce({topic}/{rp}) failed: error {err}"
                        )
                    if rp == pid:
                        base_offsets[pid] = base
        return base_offsets
    finally:
        boot.close()
        for c in conns.values():
            c.close()
