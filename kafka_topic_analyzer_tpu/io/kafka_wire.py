"""Kafka wire-protocol source.

Implementation lands with the ingestion milestone (SURVEY.md §7 M2): a
from-scratch client for ApiVersions/Metadata/ListOffsets/Fetch with
RecordBatch v2 decoding, replacing the reference's librdkafka dependency
(src/kafka.rs:23-54).  Until then, constructing it reports the gap cleanly
instead of a ModuleNotFoundError.
"""

from __future__ import annotations


class KafkaWireSource:  # pragma: no cover - placeholder until M2 lands
    def __init__(self, bootstrap_servers: str, topic: str, overrides=None):
        raise SystemExit(
            "the kafka wire-protocol source is not available yet in this "
            "build — use --source synthetic or --source segfile"
        )
