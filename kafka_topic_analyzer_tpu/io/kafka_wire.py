"""Kafka wire-protocol record source (librdkafka replacement).

Speaks the Kafka protocol directly over TCP (codec in kafka_codec.py) and
reproduces the reference consumer's observable behavior (src/kafka.rs):

- topology handshake: Metadata + per-partition earliest/latest watermarks
  fixed at scan start (src/kafka.rs:60-72); missing topic raises, like the
  reference's ``panic!("Topic not found!")``;
- full earliest→latest read per partition; termination when every partition
  reaches its snapshot-time high watermark (src/kafka.rs:119-121);
- no consumer group protocol at all: the reference already runs with
  ``enable.auto.commit=false`` + a fresh UUID group id per run
  (src/kafka.rs:28-34), i.e. group membership never has an observable
  effect — so this client fetches directly from partition leaders;
- ``--librdkafka`` overrides map onto this client's knobs: fetch tuning
  (fetch.wait.max.ms, fetch.min.bytes, fetch.max.bytes,
  max.partition.fetch.bytes, fetch.error.backoff.ms, check.crcs,
  receive.message.max.bytes), socket tuning (socket.timeout.ms,
  socket.connection.setup.timeout.ms, broker.address.family,
  socket.keepalive.enable, socket.nagle.disable,
  socket.send/receive.buffer.bytes), transport-fault recovery
  (retry.backoff.ms, reconnect.backoff.ms, reconnect.backoff.max.ms, and
  the non-librdkafka transport.retry.budget — see config.py
  ``TransportRetryConfig`` and io/retry.py), TLS and SASL properties.
  A partition that stays unreachable past its retry budget is marked
  *degraded* (``self.degraded``) and dropped from the scan instead of
  aborting it; the engine/CLI report it and exit non-zero.  Properties
  that are valid librdkafka consumer config but can have no effect here
  (KNOWN_NOOP_PROPERTIES — group/commit settings the reference disables
  anyway) are accepted silently; truly unknown keys warn, like librdkafka
  logs unknown properties.
- corrupt-data resilience (``--on-corruption``/``--quarantine-dir``, or
  the ``on.corruption``/``quarantine.dir`` overrides): a frame that fails
  decode is re-fetched once to rule out an in-flight bit flip; a second
  byte-identical failure is deterministic on-disk corruption, and policy
  applies — ``fail`` aborts with the classified `CorruptFrameError`
  (default), ``skip``/``quarantine`` skip exactly the poisoned frame
  (salvaging the rest of the response via
  kafka_codec.salvage_batch_frames), account for it per partition
  (``corruption_stats``), and optionally spool the raw bytes + JSON
  sidecar (io/quarantine.py).  ``check.crcs`` (or ``--check-crcs``)
  upgrades detection from structural damage to full payload checksums.

Record metadata is extracted batch-at-a-time: key/value lengths, null flags,
second-granularity timestamps (truncated toward zero like Rust's ``/ 1000``,
src/metric.rs:209-211), and key hashes via the native C++ shim when
available (numpy fallback otherwise).  Payload bytes never leave this module
(SURVEY.md §7 hard part (b)).
"""

from __future__ import annotations

import dataclasses
import logging
import socket
import struct
import threading
from time import perf_counter as _perf_counter
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.config import (
    CorruptionConfig,
    DataLossConfig,
    TransportRetryConfig,
)
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.retry import (
    Backoff,
    PartitionRetryBudget,
    note_backoff_sleep,
)
from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.obs import events as obs_events
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs import trace as obs_trace
from kafka_topic_analyzer_tpu.records import RecordBatch

log = logging.getLogger(__name__)

CLIENT_ID = "topic-analyzer"  # src/kafka.rs:36

#: Ceiling for the auto-grown per-partition fetch size (librdkafka caps
#: message.max.bytes at ~1 GB; also keeps the i32 wire field safe).
MAX_PARTITION_FETCH_BYTES = 1 << 30

#: Disambiguation re-fetches a corrupt span survives at one anchor before
#: the verdict is forced even when the classification KIND keeps changing
#: (a link that mutates every response differently must not re-fetch
#: forever; a matching kind — the deterministic-on-disk case — settles
#: after a single re-fetch regardless).
_MAX_SUSPECT_ROUNDS = 4

#: librdkafka property names that are VALID for the reference's consumer
#: (src/kafka.rs:24-44 sets several of them) but have no observable effect
#: in this client by design: no consumer group is ever formed (the
#: reference never commits), there is no producer, and log tuning is
#: handled by Python logging.  Accepted silently (debug log) rather than
#: warned about, so reference-style invocations stay quiet.
KNOWN_NOOP_PROPERTIES = frozenset({
    "group.id", "session.timeout.ms", "heartbeat.interval.ms",
    "max.poll.interval.ms", "enable.auto.commit", "auto.commit.interval.ms",
    "auto.offset.reset", "enable.partition.eof", "enable.auto.offset.store",
    "queue.buffering.max.ms", "queued.min.messages",
    "queued.max.messages.kbytes", "client.id", "statistics.interval.ms",
    "api.version.request", "broker.version.fallback", "debug", "log_level",
    "allow.auto.create.topics", "client.rack", "metadata.max.age.ms",
    "topic.metadata.refresh.interval.ms",
})


@dataclasses.dataclass
class SocketOptions:
    """Socket-level knobs mapped from librdkafka property names."""

    connect_timeout_s: float = 30.0
    #: 0 = any family; socket.AF_INET / AF_INET6 to pin (broker.address.family)
    family: int = 0
    keepalive: bool = False      # socket.keepalive.enable
    nodelay: bool = True         # socket.nagle.disable (our default: on)
    sndbuf: int = 0              # socket.send.buffer.bytes (0 = OS default)
    rcvbuf: int = 0              # socket.receive.buffer.bytes


def _hash_keys(
    keys: List[Optional[bytes]], use_native: bool = True
) -> Tuple[np.ndarray, np.ndarray]:
    """fnv32-variant + fnv64 hashes for a list of key byte strings."""
    n = len(keys)
    data = b"".join(k or b"" for k in keys)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) if k else 0 for k in keys], out=offsets[1:])
    if use_native:
        from kafka_topic_analyzer_tpu.io.native import hash_batch_native, native_available

        # native_available caches build failures, so a broken toolchain costs
        # one probe, not one `make` per batch.
        if native_available():
            return hash_batch_native(data, offsets)
    from kafka_topic_analyzer_tpu.ops.fnv import fnv1a32_ref_batch, fnv1a64_batch

    maxlen = int((offsets[1:] - offsets[:-1]).max(initial=0))
    padded = np.zeros((n, max(maxlen, 1)), dtype=np.uint8)
    buf = np.frombuffer(data, dtype=np.uint8)
    lengths = offsets[1:] - offsets[:-1]
    for i in range(n):
        if lengths[i]:
            padded[i, : lengths[i]] = buf[offsets[i] : offsets[i + 1]]
    return fnv1a32_ref_batch(padded, lengths), fnv1a64_batch(padded, lengths)


class BrokerConnection:
    """One blocking TCP (optionally TLS) connection to a broker.

    `request` is serialized by a lock: sharded scans prefetch per-shard
    batch streams from worker threads (utils/prefetch.py) that share the
    per-broker connections.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        ssl_context=None,
        sasl: "Optional[Tuple[str, str, str]]" = None,
        sock_opts: Optional[SocketOptions] = None,
    ):
        """``sasl`` is (mechanism, username, password); mechanism one of
        PLAIN, SCRAM-SHA-256, SCRAM-SHA-512."""
        self.host = host
        self.port = port
        opts = sock_opts or SocketOptions()
        if opts.family:
            # Pinned address family (broker.address.family=v4/v6):
            # create_connection can't filter, so resolve explicitly.
            infos = socket.getaddrinfo(
                host, port, opts.family, socket.SOCK_STREAM
            )
            if not infos:
                raise OSError(f"no address of requested family for {host}")
            af, kind, proto, _cn, addr = infos[0]
            sock = socket.socket(af, kind, proto)
            sock.settimeout(opts.connect_timeout_s)
            try:
                sock.connect(addr)
            except BaseException:
                sock.close()
                raise
        else:
            sock = socket.create_connection(
                (host, port), timeout=opts.connect_timeout_s
            )
        sock.settimeout(timeout_s)
        if opts.nodelay:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if opts.keepalive:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        if opts.sndbuf:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, opts.sndbuf)
        if opts.rcvbuf:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, opts.rcvbuf)
        if ssl_context is not None:
            sock = ssl_context.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        self._corr = 0
        self._inflight: "Dict[int, Tuple[int, int]]" = {}
        self._lock = threading.Lock()
        #: ApiVersions handshake result, filled lazily ({} = legacy broker).
        self.api_versions: "Optional[Dict[int, tuple[int, int]]]" = None
        if sasl is not None:
            try:
                self._authenticate(*sasl)
            except BaseException:
                self.close()  # don't leak the fd on failed auth
                raise

    def _sasl_handshake(self, mechanism: str) -> None:
        r = self.request(
            kc.API_SASL_HANDSHAKE, 1, kc.encode_sasl_handshake_request(mechanism)
        )
        err, mechanisms = kc.decode_sasl_handshake_response(r)
        if err:
            raise kc.KafkaProtocolError(
                f"SASL handshake failed (error {err}); broker offers "
                f"mechanisms {mechanisms} — this client asked for {mechanism}"
            )

    def _sasl_round(self, auth_bytes: bytes) -> bytes:
        """One SaslAuthenticate round trip → server auth bytes."""
        r = self.request(
            kc.API_SASL_AUTHENTICATE,
            0,
            kc.encode_sasl_authenticate_request(auth_bytes),
        )
        err, msg, server_bytes = kc.decode_sasl_authenticate_response(r)
        if err:
            raise kc.KafkaProtocolError(
                f"SASL authentication failed (error {err}): {msg or 'no detail'}"
            )
        return server_bytes

    def _authenticate(self, mechanism: str, username: str, password: str) -> None:
        """SaslHandshake v1 + SaslAuthenticate v0 exchange(s) — must be the
        first traffic on the connection (brokers reject anything else
        before authentication).  PLAIN is one round; SCRAM is two (RFC
        5802 client-first/client-final), with the server's signature
        verified so a spoofed broker can't fake success."""
        self._sasl_handshake(mechanism)
        if mechanism == "PLAIN":
            self._sasl_round(kc.sasl_plain_token(username, password))
            return
        scram = kc.ScramClient(mechanism, username, password)
        server_first = self._sasl_round(scram.first_message())
        server_final = self._sasl_round(scram.final_message(server_first))
        scram.verify_server_final(server_final)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> "memoryview":
        # recv_into one preallocated buffer: fetch responses run to tens
        # of MB, so chunk-list assembly (or a final bytes() copy) would
        # duplicate every byte.  numpy's allocator skips the zero-fill a
        # bytearray(n) would pay (a full extra memset pass at 64 MB).
        # ByteReader and the frame decoders slice/unpack memoryviews;
        # string fields go through bytes() at the decode site.
        import numpy as _np

        buf = _np.empty(n, dtype=_np.uint8)
        view = memoryview(buf).cast("B")
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:])
            if r == 0:
                raise kc.KafkaProtocolError(
                    f"broker {self.host}:{self.port} closed the connection"
                )
            got += r
        return view

    def send_request(self, api_key: int, api_version: int, body: bytes) -> int:
        """Pipelining half 1: send only, return the correlation id.

        Kafka responds strictly in request order per connection, so a
        caller that owns the connection may send the next fetch before
        reading the previous response (the wire client's send-ahead).
        Callers sharing a connection must use `request` instead — split
        halves from two threads would race for each other's bytes."""
        with self._lock:
            self._corr += 1
            corr = self._corr
            # Response-header shape depends on the REQUEST's api version
            # (flexible responses carry a tag buffer after the correlation
            # id); remember it so read_response can strip it even when
            # requests are pipelined.
            self._inflight[corr] = (api_key, api_version)
            self.sock.sendall(
                kc.encode_request(api_key, api_version, corr, CLIENT_ID, body)
            )
            return corr

    @staticmethod
    def _strip_header_tags(r: kc.ByteReader, api_key: int, api_version: int) -> None:
        # ApiVersions responses keep header v0 forever (the broker answers
        # before knowing the client's flexible support).
        if api_key != kc.API_VERSIONS and kc.is_flexible(api_key, api_version):
            r.skip_tags()

    def read_response(self, corr: int) -> kc.ByteReader:
        """Pipelining half 2: read the next response; must match ``corr``."""
        with self._lock:
            (length,) = struct.unpack(">i", self._recv_exact(4))
            payload = self._recv_exact(length)
            meta = self._inflight.pop(corr, None)
        r = kc.ByteReader(payload)
        got_corr = r.i32()
        if got_corr != corr:
            raise kc.KafkaProtocolError(
                f"correlation id mismatch: sent {corr}, got {got_corr}"
            )
        if meta is not None:
            self._strip_header_tags(r, *meta)
        return r

    def request(self, api_key: int, api_version: int, body: bytes) -> kc.ByteReader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            self.sock.sendall(
                kc.encode_request(api_key, api_version, corr, CLIENT_ID, body)
            )
            (length,) = struct.unpack(">i", self._recv_exact(4))
            payload = self._recv_exact(length)
        r = kc.ByteReader(payload)
        got_corr = r.i32()
        if got_corr != corr:
            raise kc.KafkaProtocolError(
                f"correlation id mismatch: sent {corr}, got {got_corr}"
            )
        self._strip_header_tags(r, api_key, api_version)
        return r


def parse_bootstrap(bootstrap_servers: str) -> List[Tuple[str, int]]:
    """Comma-separated host[:port] list (src/main.rs:45-51).

    IPv6: ``[2001:db8::1]:9092`` (bracketed, RFC 3986 style) and bare
    ``::1`` (multiple colons ⇒ whole string is the host, default port)."""
    out = []
    for hp in bootstrap_servers.split(","):
        hp = hp.strip()
        if not hp:
            continue
        if hp.startswith("["):  # bracketed IPv6, optional :port
            host, _, rest = hp[1:].partition("]")
            port = rest[1:] if rest.startswith(":") else ""
        elif hp.count(":") > 1:  # bare IPv6 literal, no port
            host, port = hp, ""
        else:
            host, _, port = hp.rpartition(":") if ":" in hp else (hp, "", "")
        out.append((host or hp, int(port) if port else 9092))
    return out


def discover_cluster_topics(
    bootstrap_servers: str,
    timeout_s: float = 10.0,
    retries: int = 3,
) -> "List[kc.TopicMetadata]":
    """All-topics Metadata request: every topic the cluster knows, with
    partition topology and the broker's ``is_internal`` flag — the fleet
    discovery path (fleet/discovery.py).

    Same Metadata v5–v12 negotiation `KafkaWireSource` runs for its one
    topic (preferred-first candidates against the broker's advertised
    ApiVersions range, with the KIP-511 v3→v0 handshake downgrade), but
    with a *null* topic array, which Kafka defines as "all topics".  One
    bootstrap round trip answers "what would a fleet scan cover" without
    a single per-topic handshake — the response's partition lists seed the
    admission scheduler's weights directly.

    Stateless and connection-per-call: discovery happens once per fleet
    startup (and on re-discovery polls), so caching connections here would
    only complicate the per-topic sources that follow.  Topics whose
    metadata carries an error are returned as-is (callers decide; fleet
    discovery skips them with a log line).  Raises `KafkaProtocolError`
    when no bootstrap server answers within ``retries`` attempts.
    """
    candidates = (12, 5, 1)  # mirror KafkaWireSource._CANDIDATES[METADATA]
    servers = parse_bootstrap(bootstrap_servers)
    if not servers:
        raise kc.KafkaProtocolError("no bootstrap servers given")
    last_error: "BaseException | None" = None
    for attempt in range(retries):
        host, port = servers[attempt % len(servers)]
        conn = None
        try:
            conn = BrokerConnection(host, port, timeout_s=timeout_s)
            # KIP-511 downgrade dance (see KafkaWireSource._version): offer
            # flexible v3 first; an UNSUPPORTED_VERSION v0-format answer
            # retries at v0; a broker with no ApiVersions at all gets the
            # legacy default (the last candidate).
            ranges: "Dict[int, tuple[int, int]]" = {}
            for av in (3, 0):
                try:
                    r = conn.request(
                        kc.API_VERSIONS, av,
                        kc.encode_api_versions_request(av),
                    )
                    ranges = kc.decode_api_versions_response(r, av)
                    break
                except kc.UnsupportedVersionError:
                    if av == 0:
                        ranges = {}
                    continue
                except kc.KafkaProtocolError:
                    ranges = {}
                    break
            v = candidates[-1]
            if ranges and kc.API_METADATA in ranges:
                lo, hi = ranges[kc.API_METADATA]
                v = next((c for c in candidates if lo <= c <= hi), None)
                if v is None:
                    raise kc.KafkaProtocolError(
                        f"broker supports Metadata versions [{lo}, {hi}] "
                        f"but this client implements {sorted(candidates)}"
                    )
            r = conn.request(
                kc.API_METADATA, v, kc.encode_metadata_request(None, v)
            )
            md = kc.decode_metadata_response(r, v)
            obs_metrics.FLEET_TOPICS_DISCOVERED.inc(len(md.topics))
            return md.topics
        except (OSError, kc.KafkaProtocolError) as e:
            last_error = e
            log.warning(
                "all-topics metadata from %s:%d failed (%s); retrying",
                host, port, e,
            )
        finally:
            if conn is not None:
                conn.close()
    raise kc.KafkaProtocolError(
        f"cluster topic discovery failed after {retries} attempts: "
        f"{last_error}"
    )


class DataLossError(kc.KafkaProtocolError):
    """The log mutated out from under the scan (retention race, truncation
    after an unclean election, resume below log-start) and the data-loss
    policy is ``fail``.  The loss is fully booked (metrics + lost span)
    BEFORE this raises, and the engine's fault path writes a
    fold-consistent checkpoint on the way out; the CLI maps it to
    ``EXIT_DATA_LOSS`` instead of the generic protocol-error exit."""

    def __init__(self, message: str, span: dict):
        super().__init__(message)
        #: The lost-span record ({partition, start, end, records, reason})
        #: that tripped the policy.
        self.span = span


class _TransportFailure:
    """Phase-1 fetch result when a leader's transport died mid-round: the
    serial phase books the failure against the leader's partitions instead
    of letting the exception abort the scan."""

    __slots__ = ("leader", "partitions", "error")

    def __init__(self, leader: int, partitions: List[int], error: BaseException):
        self.leader = leader
        self.partitions = partitions
        self.error = error


class KafkaWireSource(RecordSource):
    def __init__(
        self,
        bootstrap_servers: str,
        topic: str,
        overrides: Optional[Dict[str, str]] = None,
        timeout_s: float = 10.0,
        use_native_hashing: bool = True,
        corruption: Optional[CorruptionConfig] = None,
        data_loss: Optional[DataLossConfig] = None,
    ):
        self.topic = topic
        self.use_native_hashing = use_native_hashing
        overrides = dict(overrides or {})
        #: Poison-frame policy (--on-corruption / --quarantine-dir; also
        #: reachable as on.corruption / quarantine.dir overrides).  On-disk
        #: corruption is deterministic — every re-fetch returns the same
        #: bytes — so after ONE disambiguating re-fetch reproduces the
        #: failure, the policy applies: fail aborts (the default, today's
        #: behavior), skip/quarantine resume at the next batch boundary.
        policy_override = overrides.pop("on.corruption", "fail")
        qdir_override = overrides.pop("quarantine.dir", None)
        if corruption is not None:
            # Explicit config wins; the override keys are still popped so
            # they don't trip the unknown-property warning, but their
            # values (and their validation) are discarded.
            if policy_override != "fail" or qdir_override:
                log.warning(
                    "on.corruption/quarantine.dir overrides ignored: an "
                    "explicit corruption config (--on-corruption/"
                    "--quarantine-dir) takes precedence"
                )
            self.corruption = corruption
        else:
            self.corruption = CorruptionConfig(
                policy=policy_override, quarantine_dir=qdir_override
            )
        self._quarantine = None
        if self.corruption.policy == "quarantine":
            from kafka_topic_analyzer_tpu.io.quarantine import QuarantineStore

            self._quarantine = QuarantineStore(self.corruption.quarantine_dir)
        #: Log-mutation policy (--on-data-loss; also reachable as the
        #: on.data.loss override).  Unlike corruption, loss is ALWAYS
        #: booked — the policy only decides whether the scan keeps going.
        loss_override = overrides.pop("on.data.loss", "report")
        if data_loss is not None:
            if loss_override != "report":
                log.warning(
                    "on.data.loss override ignored: an explicit data-loss "
                    "config (--on-data-loss) takes precedence"
                )
            self.data_loss = data_loss
        else:
            self.data_loss = DataLossConfig(policy=loss_override)
        #: (partition, anchor) -> span record, for every poisoned span this
        #: scan skipped (or, seeded from a snapshot, a previous run
        #: skipped).  Guarded by _corrupt_lock: sharded scans run several
        #: batches() streams against one source.
        self._corrupt_spans: "Dict[Tuple[int, int], dict]" = {}
        #: partition -> (anchor, kind, rounds) of the span awaiting its
        #: disambiguating re-fetch.  ``rounds`` bounds the cycle: a link
        #: that corrupts every response *differently* at the same position
        #: (so the kind never matches) must not re-fetch forever.  A
        #: partition lives in exactly one stream, so entries are disjoint
        #: across workers — but the DICT is shared, so mutation stays
        #: under _corrupt_lock like the spans map.
        self._corrupt_suspects: "Dict[int, Tuple[int, str, int]]" = {}
        self._corrupt_lock = threading.Lock()
        #: (partition, start) -> lost-span record, for every offset range
        #: the log mutated out from under this scan (retention race,
        #: truncation after unclean election, resume below log-start) —
        #: or, seeded from a snapshot, out from under a previous run.
        #: Same sharing discipline as _corrupt_spans.
        self._lost_spans: "Dict[Tuple[int, int], dict]" = {}
        self._lost_lock = threading.Lock()
        #: partition -> highest partition_leader_epoch observed (record-batch
        #: headers, ListOffsets v4+ responses, checkpoint seeds).  Sent as
        #: current_leader_epoch on flexible Fetch/ListOffsets so a stale
        #: leader fences us instead of silently serving a truncated log;
        #: a REGRESSION in observed epochs triggers the OffsetForLeaderEpoch
        #: divergence check.  Guarded by _epoch_lock (shared across worker
        #: streams, same as the spans maps).
        self._leader_epochs: Dict[int, int] = {}
        #: partition -> highest broker-reported log_start_offset (Fetch v5+
        #: responses, ListOffsets earliest probes) — checkpointed so resume
        #: can detect a cursor below the live log start before fetch #1.
        self._log_starts: Dict[int, int] = {}
        self._epoch_lock = threading.Lock()
        # librdkafka-name knobs this client honors (others warned+ignored).
        self.max_wait_ms = int(overrides.pop("fetch.wait.max.ms", 100))
        self.min_bytes = int(overrides.pop("fetch.min.bytes", 1))
        self.max_bytes = int(overrides.pop("fetch.max.bytes", 64 << 20))
        # receive.message.max.bytes bounds whole responses in librdkafka;
        # honoring it as a response-budget cap keeps the operational intent.
        recv_max = overrides.pop("receive.message.max.bytes", None)
        if recv_max is not None:
            self.max_bytes = min(self.max_bytes, int(recv_max))
        self.partition_max_bytes = int(
            overrides.pop("max.partition.fetch.bytes", 8 << 20)
        )
        self.verify_crc = overrides.pop("check.crcs", "false").lower() == "true"
        self.timeout_s = (
            float(overrides.pop("socket.timeout.ms", timeout_s * 1000.0))
            / 1000.0
        )
        #: Pause between fetch rounds when nothing progressed (leader
        #: churn, budget starvation) — librdkafka's fetch.error.backoff.ms.
        self.error_backoff_ms = int(
            overrides.pop("fetch.error.backoff.ms", self.max_wait_ms)
        )
        #: Transport-fault recovery: reconnect pacing (retry.backoff.ms,
        #: reconnect.backoff.ms, reconnect.backoff.max.ms) and the
        #: per-partition retry budget (transport.retry.budget) that gates
        #: the degraded transition.
        self.retry_config = TransportRetryConfig.from_overrides(overrides)
        family_name = overrides.pop("broker.address.family", "any").lower()
        try:
            family = {
                "any": 0,
                "v4": socket.AF_INET,
                "v6": socket.AF_INET6,
            }[family_name]
        except KeyError:
            raise ValueError(
                f"broker.address.family {family_name!r} invalid "
                "(any, v4, v6)"
            ) from None
        self._sock_opts = SocketOptions(
            connect_timeout_s=float(
                overrides.pop("socket.connection.setup.timeout.ms", 30_000)
            ) / 1000.0,
            family=family,
            keepalive=(
                overrides.pop("socket.keepalive.enable", "false").lower()
                == "true"
            ),
            nodelay=(
                overrides.pop("socket.nagle.disable", "true").lower()
                == "true"
            ),
            sndbuf=int(overrides.pop("socket.send.buffer.bytes", 0)),
            rcvbuf=int(overrides.pop("socket.receive.buffer.bytes", 0)),
        )
        # TLS, via the same librdkafka property names the reference's --ssl
        # feature would use (Cargo.toml:19 features=["ssl"]).
        self._ssl_context = None
        protocol = overrides.pop("security.protocol", "plaintext").lower()
        ca_location = overrides.pop("ssl.ca.location", None)
        verify_certs = (
            overrides.pop("enable.ssl.certificate.verification", "true").lower()
            == "true"
        )
        self._sasl: "Optional[Tuple[str, str, str]]" = None
        mechanism = overrides.pop("sasl.mechanism", "PLAIN").upper()
        sasl_user = overrides.pop("sasl.username", None)
        sasl_pass = overrides.pop("sasl.password", None)
        if protocol in ("sasl_plaintext", "sasl_ssl"):
            if mechanism != "PLAIN" and mechanism not in kc.SCRAM_MECHANISMS:
                raise ValueError(
                    f"sasl.mechanism {mechanism!r} unsupported "
                    "(PLAIN, SCRAM-SHA-256, SCRAM-SHA-512)"
                )
            if sasl_user is None or sasl_pass is None:
                raise ValueError(
                    "sasl_plaintext/sasl_ssl require sasl.username and "
                    "sasl.password"
                )
            self._sasl = (mechanism, sasl_user, sasl_pass)
        elif sasl_user is not None or sasl_pass is not None:
            log.warning(
                "sasl.username/sasl.password ignored: security.protocol is "
                "%r (use sasl_plaintext or sasl_ssl)", protocol,
            )
        if protocol in ("ssl", "tls", "sasl_ssl"):
            import ssl as _ssl

            # ssl.ca.location REPLACES the trust store (librdkafka semantics:
            # pinning a private CA must not keep accepting public CAs).
            ctx = _ssl.create_default_context(cafile=ca_location)
            if not verify_certs:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
            self._ssl_context = ctx
        elif protocol not in ("plaintext", "sasl_plaintext"):
            raise ValueError(
                f"security.protocol {protocol!r} unsupported "
                "(plaintext, ssl, sasl_plaintext, sasl_ssl)"
            )
        for k in overrides:
            if k in KNOWN_NOOP_PROPERTIES:
                log.debug("property %r accepted (no effect in this client)", k)
            else:
                log.warning("ignoring unsupported consumer property %r", k)

        self._bootstrap = parse_bootstrap(bootstrap_servers)
        self._conn_lock = threading.Lock()
        self._conns: Dict[Tuple[str, int], BrokerConnection] = {}
        #: Hosts that slammed the connection on ApiVersions (pre-0.10): the
        #: reconnect skips the handshake instead of looping.
        self._assume_legacy: "set[Tuple[str, int]]" = set()
        self._brokers: Dict[int, Tuple[str, int]] = {}
        self._leaders: Dict[int, int] = {}
        self._watermarks: Optional[Tuple[Dict[int, int], Dict[int, int]]] = None
        #: partition -> reason, for every partition dropped from a scan
        #: after exhausting its transport/protocol retry budget.  Sharded
        #: scans AND parallel-ingest workers (parallel/ingest.py) run
        #: several batches() streams against one source, so this
        #: accumulates across streams (each partition belongs to exactly
        #: one stream, but the dict is shared: writes hold
        #: _degraded_lock); the engine snapshots it per scan.
        self.degraded: Dict[int, str] = {}
        self._degraded_lock = threading.Lock()
        #: Serializes the read-modify-write growth of partition_max_bytes:
        #: concurrent streams each learning "batch exceeds fetch size"
        #: must not lose each other's doubling.  Reads stay lock-free —
        #: a stale size only costs one extra growth round.
        self._fetch_grow_lock = threading.Lock()
        self._load_metadata()

    def degraded_partitions(self) -> Dict[int, str]:
        with self._degraded_lock:
            return dict(self.degraded)

    # -- corruption accounting ------------------------------------------------

    def corruption_spans(self) -> "list[dict]":
        """Every skipped poison span as a JSON-safe record (checkpoint
        metadata format; `seed_corrupt_spans` round-trips it)."""
        with self._corrupt_lock:
            return [dict(s) for s in self._corrupt_spans.values()]

    def corruption_stats(self) -> "Dict[int, dict]":
        """Per-partition corruption accounting: frame/record/byte counts,
        per-kind breakdown, and the span list — the engine snapshots this
        into `ScanResult.corrupt_partitions`."""
        out: "Dict[int, dict]" = {}
        for s in self.corruption_spans():
            d = out.setdefault(
                s["partition"],
                {
                    "frames": 0, "records": 0, "bytes": 0,
                    "quarantined": 0, "kinds": {}, "spans": [],
                },
            )
            d["frames"] += s.get("frames", 1)
            d["records"] += s.get("records", 0)
            d["bytes"] += s.get("bytes", 0)
            d["quarantined"] += 1 if s.get("quarantined") else 0
            d["kinds"][s["kind"]] = d["kinds"].get(s["kind"], 0) + 1
            d["spans"].append(s)
        return out

    def seed_corrupt_spans(self, spans: "list[dict]") -> None:
        """Pre-load spans a previous run already skipped (snapshot resume):
        re-encountering one skips it immediately — no disambiguating
        re-fetch, no re-count, no re-quarantine."""
        with self._corrupt_lock:
            for s in spans:
                key = (int(s["partition"]), int(s["anchor"]))
                if key not in self._corrupt_spans:
                    self._corrupt_spans[key] = dict(s, seeded=True)

    def _note_corrupt(
        self,
        p: int,
        anchor: int,
        err: "kc.CorruptFrameError",
        claimed_end: int,
        resume_offset: int,
        num_records: int,
        raw: bytes,
    ) -> Optional[int]:
        """Book one corrupt-frame sighting at scan position ``anchor``.

        Returns the offset to skip the partition to; ``None`` when the
        caller must stop this partition's round so the span is re-fetched
        once (first sighting — an in-flight bit flip would not reproduce);
        ``-1`` when the span is deterministically corrupt but gives no
        skip bound (the caller degrades the partition).  Raises the
        classified error under the ``fail`` policy once deterministic.
        """
        key = (p, anchor)
        with self._corrupt_lock:
            known = self._corrupt_spans.get(key)
            prev = self._corrupt_suspects.get(p)
        if known is not None:
            return int(known["skip_to"])  # seeded/already-skipped span
        rounds = prev[2] + 1 if prev is not None and prev[0] == anchor else 1
        deterministic = (
            prev is not None
            and prev[0] == anchor
            and (prev[1] == err.kind or rounds > _MAX_SUSPECT_ROUNDS)
        )
        if not deterministic:
            # Suspect an in-flight flip.  Leaving the partition's offset
            # untouched makes the next round re-fetch the identical span —
            # one extra fetch on a healthy connection, none of the
            # transport retry budget.  A matching kind on the re-fetch
            # (the common case) settles it in one round; a link that
            # mutates the damage differently every round is settled by the
            # rounds bound instead of re-fetching forever.
            with self._corrupt_lock:
                self._corrupt_suspects[p] = (anchor, err.kind, rounds)
            obs_metrics.CORRUPT_REFETCHES.inc()
            obs_events.emit(
                "corrupt_suspect", partition=p, anchor=anchor, kind=err.kind
            )
            log.warning(
                "partition %d: suspect corrupt frame at offset %d (%s); "
                "re-fetching once to rule out an in-flight bit flip",
                p, anchor, err.kind,
            )
            return None
        # Identical failure on the re-fetched bytes (or the re-fetch
        # budget ran out): deterministic corruption.  Apply policy.
        with self._corrupt_lock:
            self._corrupt_suspects.pop(p, None)
        err.partition = p
        if self.corruption.policy == "fail":
            raise err
        skip_to = kc.preferred_skip_offset(anchor, resume_offset, claimed_end)
        span_rec = {
            "partition": p,
            "anchor": anchor,
            "skip_to": int(skip_to),  # -1 when the span gave no bound
            "kind": err.kind,
            "base_offset": int(err.base_offset),
            "frames": 1,
            "records": int(max(num_records, 0)),
            "bytes": len(raw),
            "quarantined": False,
        }
        if self._quarantine is not None:
            sidecar = self._quarantine.spool(
                topic=self.topic,
                partition=p,
                anchor=anchor,
                raw=raw,
                classification=err.kind,
                base_offset=err.base_offset,
                offset_start=err.base_offset,
                offset_end=claimed_end,
                crc_expected=err.crc_expected,
                crc_actual=err.crc_actual,
                error=str(err),
            )
            span_rec["quarantined"] = True
            if sidecar is not None:
                obs_metrics.CORRUPT_QUARANTINED.inc()
        obs_metrics.CORRUPT_FRAMES.labels(kind=err.kind).inc()
        obs_metrics.CORRUPT_RECORDS.inc(span_rec["records"])
        obs_metrics.CORRUPT_BYTES.inc(len(raw))
        obs_events.emit(
            "corrupt_frame",
            partition=p,
            anchor=anchor,
            skip_to=span_rec["skip_to"],
            kind=err.kind,
            action=self.corruption.policy,
            quarantined=span_rec["quarantined"],
        )
        log.error(
            "partition %d: deterministically corrupt frame at offset %d "
            "(%s): %s — %s",
            p, anchor, err.kind, err,
            "quarantined + skipped"
            if span_rec["quarantined"] else "skipped",
        )
        with self._corrupt_lock:
            self._corrupt_spans[key] = span_rec
        return span_rec["skip_to"]

    # -- log-mutation (data-loss) accounting ---------------------------------

    def lost_spans(self) -> "List[dict]":
        """Every offset range the log mutated out from under this scan (or,
        seeded, a predecessor's scan), as JSON-safe dicts."""
        with self._lost_lock:
            return [dict(s) for s in self._lost_spans.values()]

    def loss_stats(self) -> Dict[int, dict]:
        """Per-partition data-loss rollup, shaped like corruption_stats():
        {partition: {records, ranges, reasons, authoritative, spans}}.
        ``authoritative`` is False when any span came from truncation —
        records already folded at those offsets were replaced, so the
        partition's counts describe a log that no longer exists."""
        out: Dict[int, dict] = {}
        with self._lost_lock:
            spans = [dict(s) for s in self._lost_spans.values()]
        for s in sorted(spans, key=lambda s: (s["partition"], s["start"])):
            d = out.setdefault(
                s["partition"],
                {
                    "records": 0,
                    "ranges": 0,
                    "reasons": {},
                    "authoritative": True,
                    "spans": [],
                },
            )
            d["records"] += s["records"]
            d["ranges"] += 1
            d["reasons"][s["reason"]] = d["reasons"].get(s["reason"], 0) + 1
            if s["reason"] == "truncation":
                d["authoritative"] = False
            d["spans"].append(s)
        return out

    def seed_lost_spans(self, spans: "List[dict]") -> None:
        """Adopt lost spans recorded by a previous run (snapshot resume) so
        the final report covers the whole logical scan.  Seeded spans are
        NOT re-booked to metrics — the run that lost them already counted
        them — and they never re-trip the fail policy."""
        with self._lost_lock:
            for s in spans:
                key = (int(s["partition"]), int(s["start"]))
                self._lost_spans.setdefault(key, dict(s, seeded=True))

    def _note_lost(
        self, p: int, start: int, end: int, reason: str
    ) -> None:
        """Book the lost range [start, end) on partition ``p``: per-reason
        metrics, a lost-span record, a ``log_lost`` event — and, under the
        ``fail`` policy, the classified abort.  Idempotent per (partition,
        start): a re-detected span (seeded from a checkpoint, or re-entered
        after a metadata reload) is never double-counted."""
        records = int(end) - int(start)
        if records <= 0:
            return
        span_rec = {
            "partition": int(p),
            "start": int(start),
            "end": int(end),
            "records": records,
            "reason": reason,
        }
        with self._lost_lock:
            key = (int(p), int(start))
            if key in self._lost_spans:
                return
            self._lost_spans[key] = span_rec
        obs_metrics.LOG_LOST_RECORDS.labels(reason=reason).inc(records)
        obs_metrics.LOG_LOST_RANGES.labels(reason=reason).inc()
        obs_events.emit(
            "log_lost",
            partition=int(p),
            start=int(start),
            end=int(end),
            records=records,
            reason=reason,
            action=self.data_loss.policy,
        )
        log.error(
            "partition %d: %d record(s) at [%d, %d) lost to %s — %s",
            p, records, start, end, reason,
            "aborting (--on-data-loss fail)"
            if self.data_loss.policy == "fail" else "continuing",
        )
        if self.data_loss.policy == "fail":
            raise DataLossError(
                f"partition {p}: {records} record(s) at [{start}, {end}) "
                f"lost to {reason} (--on-data-loss fail)",
                span_rec,
            )

    # -- leader-epoch fencing (KIP-320) --------------------------------------

    def _observe_epoch(self, p: int, epoch: int) -> bool:
        """Track the highest leader epoch seen for ``p``.  Returns True when
        ``epoch`` REGRESSES below the tracked one — data from a stale
        replica / pre-election log, which callers answer with the
        OffsetForLeaderEpoch divergence check."""
        if epoch < 0:
            return False
        with self._epoch_lock:
            cur = self._leader_epochs.get(p, -1)
            if epoch > cur:
                self._leader_epochs[p] = epoch
            return epoch < cur

    def _observe_log_start(self, p: int, offset: int) -> None:
        """Track the highest broker-reported log start (retention floor)."""
        if offset < 0:
            return
        with self._epoch_lock:
            if offset > self._log_starts.get(p, -1):
                self._log_starts[p] = offset

    def _epoch_for(self, p: int) -> int:
        """Tracked epoch to send as current_leader_epoch (-1 = unknown)."""
        with self._epoch_lock:
            return self._leader_epochs.get(p, -1)

    def _clear_epoch(self, p: int) -> None:
        """Forget a fenced epoch so the next fetch sends -1 (unfenced) and
        re-learns the post-election epoch from the data it returns."""
        with self._epoch_lock:
            self._leader_epochs.pop(p, None)

    def partition_meta(self) -> Dict[int, dict]:
        """Per-partition durable-fencing facts for checkpoints:
        {partition: {leader_epoch, log_start_offset}}."""
        with self._epoch_lock:
            parts = set(self._leader_epochs) | set(self._log_starts)
            return {
                int(p): {
                    "leader_epoch": int(self._leader_epochs.get(p, -1)),
                    "log_start_offset": int(self._log_starts.get(p, -1)),
                }
                for p in parts
            }

    def check_divergence(
        self, p: int, cursor: int, ask_epoch: int
    ) -> Optional[int]:
        """OffsetForLeaderEpoch (API 23) probe: where does the broker's log
        for ``ask_epoch`` end?  Returns that end offset when it falls BELOW
        ``cursor`` (the log we scanned was truncated there), else None —
        also None when the probe cannot run (broker predates API 23, or the
        round trip fails): an unverifiable cursor is reported, not guessed
        at."""
        if ask_epoch < 0 or p not in self._leaders:
            return None
        obs_metrics.LOG_DIVERGENCE_CHECKS.inc()
        try:
            conn = self._leader_conn(p)
            v = self._version(conn, kc.API_OFFSET_FOR_LEADER_EPOCH)
            if (
                conn.api_versions is not None
                and kc.API_OFFSET_FOR_LEADER_EPOCH not in conn.api_versions
            ):
                log.warning(
                    "partition %d: broker does not speak "
                    "OffsetForLeaderEpoch; cannot verify cursor %d against "
                    "epoch %d", p, cursor, ask_epoch,
                )
                return None
            r = conn.request(
                kc.API_OFFSET_FOR_LEADER_EPOCH,
                v,
                kc.encode_offset_for_leader_epoch_request(
                    self.topic,
                    [(p, self._epoch_for(p), ask_epoch)],
                    v,
                ),
            )
            decoded = kc.decode_offset_for_leader_epoch_response(r, v)
        except (OSError, kc.KafkaProtocolError) as e:
            log.warning(
                "partition %d: OffsetForLeaderEpoch probe failed: %s", p, e
            )
            return None
        got = decoded.get(p)
        if got is None:
            return None
        err, end_epoch, end_offset = got
        if err or end_offset < 0:
            log.warning(
                "partition %d: OffsetForLeaderEpoch error %d "
                "(epoch %d)", p, err, ask_epoch,
            )
            return None
        obs_events.emit(
            "divergence_check",
            partition=int(p),
            ask_epoch=int(ask_epoch),
            end_epoch=int(end_epoch),
            end_offset=int(end_offset),
            cursor=int(cursor),
            diverged=bool(end_offset < cursor),
        )
        if end_offset < cursor:
            return int(end_offset)
        return None

    def validate_resume(
        self, offsets: Dict[int, int], saved_meta: Dict[int, dict]
    ) -> None:
        """Resumed-scan honesty gate, run before fetch #1.  Seeds the
        tracked epochs/log-starts from the checkpoint, then checks each
        saved cursor against the live log: a cursor below the live log
        start is a named retention loss (and the cursor re-anchors forward,
        in place, so the first fetch doesn't re-detect it); a leader epoch
        that moved since the checkpoint runs the OffsetForLeaderEpoch
        divergence check, and truncation below the cursor is a named
        truncation loss with the fold marked non-authoritative."""
        saved_epochs: Dict[int, int] = {}
        for p, m in (saved_meta or {}).items():
            saved_epochs[int(p)] = int(m.get("leader_epoch", -1))
        live_start, _live_end = self.watermarks()
        live_epochs = dict(self._leader_epochs)
        for p in sorted(offsets):
            cursor = int(offsets[p])
            start = live_start.get(p)
            if start is not None and cursor < start:
                self._note_lost(p, cursor, start, "resume-below-log-start")
                offsets[p] = start
                continue
            saved_epoch = saved_epochs.get(p, -1)
            if saved_epoch < 0:
                continue
            live_epoch = live_epochs.get(p, -1)
            if live_epoch >= 0 and live_epoch != saved_epoch:
                div = self.check_divergence(p, cursor, saved_epoch)
                if div is not None:
                    self._note_lost(p, div, cursor, "truncation")
            else:
                # Broker didn't report an epoch at watermark time (classic
                # wire): trust the checkpoint's view until data says more.
                self._observe_epoch(p, saved_epoch)

    # -- connections ---------------------------------------------------------

    def _connect(self, host: str, port: int) -> BrokerConnection:
        key = (host, port)
        with self._conn_lock:
            conn = self._conns.get(key)
            if conn is None:
                conn = BrokerConnection(
                    host,
                    port,
                    self.timeout_s,
                    ssl_context=self._ssl_context,
                    sasl=self._sasl,
                    sock_opts=self._sock_opts,
                )
                self._conns[key] = conn
            return conn

    def _any_conn(self) -> BrokerConnection:
        errors = []
        for host, port in self._bootstrap:
            try:
                return self._connect(host, port)
            except OSError as e:
                errors.append(f"{host}:{port}: {e}")
        raise kc.KafkaProtocolError(
            "could not reach any bootstrap server: " + "; ".join(errors)
        )

    def _leader_conn(self, partition: int) -> BrokerConnection:
        node = self._leaders[partition]
        host, port = self._brokers[node]
        return self._connect(host, port)

    def close(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()

    # -- protocol version negotiation ---------------------------------------

    #: Preferred-first version candidates per API.  Metadata v5 is the floor
    #: on Kafka 4.0 brokers (KIP-896 removed pre-2.1 versions); v1 keeps
    #: very old brokers working.  The last entry doubles as the legacy
    #: default when the broker predates ApiVersions.  The leading entries
    #: are the flexible (KIP-482 tagged/compact) versions — preferred when
    #: the broker's advertised range covers them, and required once a
    #: future KIP-896-style floor raise drops the classic encodings.
    _CANDIDATES = {
        kc.API_METADATA: ("Metadata", (12, 5, 1)),
        kc.API_LIST_OFFSETS: ("ListOffsets", (7, 1)),
        kc.API_FETCH: ("Fetch", (12, 4)),
        kc.API_OFFSET_FOR_LEADER_EPOCH: ("OffsetForLeaderEpoch", (4, 3)),
    }

    def _evict(self, conn: BrokerConnection) -> None:
        """Close and forget a connection whose stream may be dead/desynced
        so the next use reconnects fresh."""
        conn.close()
        with self._conn_lock:
            if self._conns.get((conn.host, conn.port)) is conn:
                del self._conns[(conn.host, conn.port)]
        obs_metrics.CONNECTION_EVICTIONS.inc()
        obs_events.emit(
            "connection_evicted", host=conn.host, port=conn.port
        )

    def _version(self, conn: BrokerConnection, api_key: int) -> int:
        if conn.api_versions is None:
            if (conn.host, conn.port) in self._assume_legacy:
                conn.api_versions = {}
            else:
                # KIP-511 downgrade dance: offer the flexible v3 first; a
                # broker that doesn't speak it answers UNSUPPORTED_VERSION
                # in v0 format (brokers parse only the first two header
                # fields of an unknown-version ApiVersions request), and
                # the client retries at v0.  This is what survives a
                # future floor raise that drops ApiVersions v0.
                for av in (3, 0):
                    try:
                        r = conn.request(
                            kc.API_VERSIONS, av,
                            kc.encode_api_versions_request(av),
                        )
                    except kc.KafkaProtocolError as e:
                        # Pre-0.10 brokers slam the connection on the
                        # unknown request: remember the host as legacy (so
                        # the caller's retry skips the handshake) and
                        # surface the failure — the stream is dead either
                        # way.
                        self._evict(conn)
                        if "closed the connection" in str(e):
                            self._assume_legacy.add((conn.host, conn.port))
                        raise
                    except OSError as e:
                        # Transient socket failure: evict (dead/desynced
                        # stream) but do NOT guess legacy — the retry
                        # re-handshakes.
                        self._evict(conn)
                        raise kc.KafkaProtocolError(
                            f"ApiVersions handshake failed: {e}"
                        ) from e
                    try:
                        conn.api_versions = kc.decode_api_versions_response(
                            r, av
                        )
                        break
                    except kc.UnsupportedVersionError:
                        if av == 0:
                            # v0 itself rejected: genuinely ancient broker.
                            log.warning(
                                "ApiVersions rejected; assuming legacy broker"
                            )
                            conn.api_versions = {}
                        continue  # downgrade v3 -> v0
                    except kc.KafkaProtocolError as e:
                        # A cleanly-decoded non-version error: old broker.
                        log.warning(
                            "ApiVersions rejected (%s); assuming legacy broker", e
                        )
                        conn.api_versions = {}
                        break
        name, candidates = self._CANDIDATES[api_key]
        ranges = conn.api_versions
        if not ranges or api_key not in ranges:
            return candidates[-1]
        lo, hi = ranges[api_key]
        for v in candidates:
            if lo <= v <= hi:
                return v
        raise kc.KafkaProtocolError(
            f"broker supports {name} versions [{lo}, {hi}] but this client "
            f"implements {sorted(candidates)}"
        )

    # -- topology (src/kafka.rs:60-72) --------------------------------------

    def _load_metadata(self, retries: int = 5) -> None:
        import time

        last_issue = ""
        for attempt in range(retries):
            conn = self._any_conn()
            try:
                v = self._version(conn, kc.API_METADATA)
            except kc.KafkaProtocolError as e:
                # A pre-0.10 broker slams the connection on ApiVersions;
                # _version evicted it and remembered the host as legacy, so
                # the retry reconnects and skips the handshake.
                if attempt + 1 >= retries:
                    raise
                log.warning("ApiVersions handshake failed (%s); retrying", e)
                continue
            try:
                r = conn.request(
                    kc.API_METADATA, v,
                    kc.encode_metadata_request([self.topic], v),
                )
                md = kc.decode_metadata_response(r, v)
            except (OSError, kc.KafkaProtocolError) as e:
                # A cached bootstrap connection died (broker restart) or
                # the stream desynced: evict so the retry reconnects fresh
                # instead of hitting the same dead socket forever.
                self._evict(conn)
                if attempt + 1 >= retries:
                    raise kc.KafkaProtocolError(
                        f"metadata request failed: {e}"
                    ) from e
                log.warning("metadata request failed (%s); retrying", e)
                continue
            topic_md = next((t for t in md.topics if t.name == self.topic), None)
            if topic_md is None or topic_md.error == kc.ERR_UNKNOWN_TOPIC_OR_PARTITION:
                raise SystemExit("Topic not found!")  # src/kafka.rs:62
            if topic_md.error:
                raise kc.KafkaProtocolError(
                    f"metadata error {topic_md.error} for topic {self.topic!r}"
                )
            # Leaderless partitions (error set or leader == -1) happen during
            # elections; retry briefly instead of failing later with KeyError.
            bad = [
                p for p in topic_md.partitions
                if p.error or p.leader < 0 or p.leader not in md.brokers
            ]
            if not bad:
                # Commit brokers+leaders together, and only on full
                # success: a recovery-path reload that fails partway (half
                # -up broker, leaderless election) must leave the previous
                # topology fully intact, not a half-new brokers table that
                # routes still-healthy partitions into transport failures.
                self._brokers = md.brokers
                self._leaders = {p.partition: p.leader for p in topic_md.partitions}
                return
            last_issue = ", ".join(
                f"partition {p.partition} (error={p.error}, leader={p.leader})"
                for p in bad
            )
            log.warning("metadata not ready (%s), retry %d", last_issue, attempt + 1)
            time.sleep(min(0.2 * (attempt + 1), 1.0))
        raise kc.KafkaProtocolError(
            f"no usable leader for topic {self.topic!r}: {last_issue}"
        )

    def _reload_metadata(self) -> bool:
        """Metadata refresh that tolerates an unreachable cluster: during
        transport recovery a failed reload must not abort the scan — the
        next round retries against the stale topology, and the per-partition
        retry budget bounds how long that can go on."""
        obs_metrics.METADATA_RELOADS.inc()
        try:
            self._load_metadata()
            obs_events.emit("metadata_reload", ok=True)
            return True
        except (OSError, kc.KafkaProtocolError) as e:
            log.warning(
                "metadata reload failed (%s); keeping stale topology", e
            )
            obs_events.emit("metadata_reload", ok=False, error=str(e))
            return False
        except SystemExit:
            # _load_metadata's "Topic not found!" exit is an init-time
            # contract (src/kafka.rs:62).  Mid-scan it is a transient: a
            # restarting broker can answer metadata with
            # UNKNOWN_TOPIC_OR_PARTITION before it re-syncs topic state,
            # and the scan already proved the topic exists.
            log.warning(
                "metadata reload says topic %r unknown (broker still "
                "syncing?); keeping stale topology", self.topic,
            )
            return False

    def partitions(self) -> List[int]:
        return sorted(self._leaders)

    def _list_offsets(self, ts: int) -> Dict[int, int]:
        """One ListOffsets query (timestamp or earliest/latest sentinel)
        across all partitions, grouped by leader."""
        out: Dict[int, int] = {}
        by_leader: Dict[int, List[int]] = {}
        for p, leader in self._leaders.items():
            by_leader.setdefault(leader, []).append(p)
        for leader, parts in by_leader.items():
            host, port = self._brokers[leader]
            conn = self._connect(host, port)
            lo_v = self._version(conn, kc.API_LIST_OFFSETS)
            try:
                r = conn.request(
                    kc.API_LIST_OFFSETS,
                    lo_v,
                    kc.encode_list_offsets_request(
                        self.topic, [(p, ts) for p in parts], lo_v
                    ),
                )
                decoded = kc.decode_list_offsets_response(r, lo_v)
            except (OSError, kc.KafkaProtocolError) as e:
                # Evict the dead/desynced cached connection before
                # surfacing the failure so a caller's retry reconnects.
                self._evict(conn)
                raise kc.KafkaProtocolError(
                    f"ListOffsets on {host}:{port} failed: {e}"
                ) from e
            for pid, (err, off, epoch) in decoded.items():
                if err:
                    raise kc.KafkaProtocolError(
                        f"ListOffsets error {err} for partition {pid}"
                    )
                self._observe_epoch(pid, epoch)
                if ts == kc.EARLIEST_TIMESTAMP:
                    self._observe_log_start(pid, off)
                out[pid] = off
        return out

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        if self._watermarks is not None:
            return self._watermarks
        self._watermarks = (
            self._list_offsets(kc.EARLIEST_TIMESTAMP),
            self._list_offsets(kc.LATEST_TIMESTAMP),
        )
        return self._watermarks

    def refresh_watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        """Follow-mode watermark re-poll, routed through the transport
        retry/backoff budget (io/retry.Backoff — the same schedule fetch
        recovery runs): a metadata hiccup at the head must pace and retry,
        never take down a service that has been running for days.  Each
        failed attempt reloads cluster metadata (the usual cause is a
        moved leader) before backing off.  When the whole budget is
        exhausted the PREVIOUS snapshot is kept — the service simply polls
        again next round — and the give-up is booked
        (kta_watermark_refresh_failures_total) and emitted
        (``watermark_refresh_failed``), never silent."""
        backoff = Backoff(self.retry_config)
        last_error: "BaseException | None" = None
        for attempt in range(1, self.retry_config.retry_budget + 1):
            try:
                fresh = (
                    self._list_offsets(kc.EARLIEST_TIMESTAMP),
                    self._list_offsets(kc.LATEST_TIMESTAMP),
                )
                self._watermarks = fresh
                return fresh
            except (OSError, kc.KafkaProtocolError) as e:
                last_error = e
                log.warning(
                    "watermark refresh attempt %d/%d failed: %s",
                    attempt, self.retry_config.retry_budget, e,
                )
                if attempt < self.retry_config.retry_budget:
                    self._reload_metadata()
                    backoff.sleep_for(attempt)
        obs_metrics.WATERMARK_REFRESH_FAILURES.inc()
        obs_events.emit(
            "watermark_refresh_failed",
            attempts=self.retry_config.retry_budget,
            error=str(last_error),
        )
        return self.watermarks()

    def heal_degraded(self, partitions: "List[int]") -> None:
        """Clear the degraded flag for partitions a later follow pass
        caught up to the head (serve/follow.py): the degraded transition
        marks an UNDERCOUNT, and once the tail is re-read there is no
        undercount left to report.  Batch scans never call this — their
        degraded set is final by construction."""
        if not partitions:
            return
        with self._degraded_lock:
            for p in partitions:
                if self.degraded.pop(p, None) is not None:
                    obs_events.emit("partition_healed", partition=int(p))

    def offsets_for_timestamp(self, ts_ms: int) -> Dict[int, int]:
        """Per-partition earliest offset whose record timestamp >= ts_ms
        (ListOffsets timestamp lookup); partitions with no such record map
        to their end watermark, so a subsequent scan reads nothing there."""
        _, end = self.watermarks()
        return {
            pid: (off if off >= 0 else end[pid])
            for pid, off in self._list_offsets(ts_ms).items()
        }

    def _earliest_offset(self, partition: int) -> int:
        """Fresh earliest offset for one partition (OFFSET_OUT_OF_RANGE
        recovery when retention advances mid-scan)."""
        return self._list_offsets(kc.EARLIEST_TIMESTAMP)[partition]

    # -- the read loop (src/kafka.rs:74-137, batched) ------------------------

    #: The engine may hand this source a packing.FusedPackSink: accepted
    #: record sets then decode→pack straight into wire-v4 rows (yielded as
    #: packing.PackedRow) instead of materializing RecordBatch columns.
    supports_fused_sink = True

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
        sink=None,
    ) -> Iterator[RecordBatch]:
        # Fetch connections are private to this iterator: sharded scans
        # and parallel ingest (parallel/ingest.py) run one batches()
        # stream per shard/worker from worker threads, and the pipelined
        # send/read halves cannot share a socket with another stream
        # (responses would be claimed by the wrong reader).  Everything
        # scan-shared that a stream can mutate — degraded, the corruption
        # spans/suspects, partition_max_bytes growth — is lock-guarded;
        # per-stream state (offsets, streaks, inflight) lives below.
        own_conns: Dict[int, BrokerConnection] = {}
        pools: "list" = []
        try:
            yield from self._batches_impl(
                batch_size, partitions, start_at, own_conns, pools, sink
            )
        finally:
            # Drain worker threads BEFORE closing their sockets: a close
            # under an active reader is a fd-reuse race (and outright
            # thread-unsafe on SSLSocket).  Workers unblock within the
            # socket timeout at worst.
            for pl in pools:
                pl.shutdown(wait=True, cancel_futures=True)
            for c in own_conns.values():
                c.close()

    def _batches_impl(
        self,
        batch_size: int,
        partitions: Optional[List[int]],
        start_at: Optional[Dict[int, int]],
        own_conns: "Dict[int, BrokerConnection]",
        pools: "list",
        sink=None,
    ) -> Iterator[RecordBatch]:
        start, end = self.watermarks()
        parts = sorted(partitions) if partitions is not None else self.partitions()
        next_offset = {p: start[p] for p in parts}
        if start_at:
            for p in parts:
                if p in start_at:
                    cursor = int(start_at[p])
                    if cursor < next_offset[p]:
                        # The log start passed the caller's cursor before
                        # this stream's first fetch (retention between
                        # follow polls, or between cursor save and stream
                        # open).  The gap [cursor, start) was never
                        # readable here — book it, never skip silently.
                        # Idempotent with the resume gate: validate_resume
                        # re-anchors its offsets in place, and _note_lost
                        # dedups on (partition, start) regardless.
                        self._note_lost(
                            p, cursor, next_offset[p], "retention"
                        )
                    else:
                        next_offset[p] = cursor
        remaining = {p for p in parts if next_offset[p] < end[p]}

        # Accumulate RecordBatch *chunks* (one per accepted wire frame) and
        # re-split to batch_size at flush; offsets ride along for snapshot
        # resume.  Chunks come from the native frame decoder when available
        # (the Python per-record generator is ~100x slower).
        #
        # With a fused ``sink`` installed (and the native shim loaded) the
        # pend/resplit chain is replaced wholesale: accepted record sets
        # decode→pack straight into the sink's wire-v4 rows
        # (sink.append_record_set — no SoA columns, no re-batching copy),
        # fallback chunks (compressed/legacy/salvaged/python-decoded
        # frames) enter the SAME rows through sink.append_batch so the
        # greedy batch_size boundaries — and therefore the packed bytes —
        # stay byte-identical to the chained path, and ``flush`` yields
        # completed packing.PackedRow items instead of RecordBatches.
        pend: List[RecordBatch] = []
        pend_count = 0

        def flush(force: bool):
            nonlocal pend, pend_count
            if sink is not None:
                if force:
                    sink.flush()
                yield from sink.take_completed()
                return
            if not (pend_count >= batch_size or (force and pend_count)):
                return
            out, pend, pend_count = RecordBatch.resplit(
                pend, batch_size, force
            )
            yield from out

        def push_chunk(chunk: RecordBatch, reason: str = "frame-fallback") -> None:
            nonlocal pend_count
            if not len(chunk):
                return
            if sink is not None:
                sink.append_batch(chunk, reason)
                return
            pend.append(chunk)
            pend_count += len(chunk)

        def accept_records(soa: "dict[str, np.ndarray]", p: int) -> int:
            """Push the records of a decoded SoA chunk that fall in
            [next_offset[p], end[p]) and advance next_offset; returns the
            accepted count.  Offsets increase within a Kafka record set, so
            the in-range run is a contiguous slice found by searchsorted
            (columns become views, no per-column mask copies); a broker
            violating the ordering contract falls back to a boolean mask."""
            offs = soa["offsets"]
            if len(offs) == 0:
                return 0
            a, b = next_offset[p], end[p]
            if bool((offs[1:] > offs[:-1]).all()):
                lo = int(np.searchsorted(offs, a, "left"))
                hi = int(np.searchsorted(offs, b, "left"))
                if hi <= lo:
                    return 0
                sel: "slice | np.ndarray" = slice(lo, hi)
                cnt = hi - lo
                last = int(offs[hi - 1])
            else:
                idx = np.flatnonzero((offs >= a) & (offs < b))
                if len(idx) == 0:
                    return 0
                sel = idx
                cnt = len(idx)
                last = int(offs[idx[-1]])
            push_chunk(_chunk_to_batch(soa, sel, p))
            next_offset[p] = last + 1
            return cnt

        use_native_decode = self.use_native_hashing
        if use_native_decode:
            try:
                from kafka_topic_analyzer_tpu.io.native import (
                    decode_record_set_native,
                    decode_records_native,
                    native_available,
                    scan_record_set_native,
                )

                use_native_decode = native_available()
            except ImportError:
                use_native_decode = False
        if sink is not None and not use_native_decode:
            # Fused sink requested but the native decoder is off: the whole
            # stream degrades to the decoded-batch python chain.  Book the
            # bypass ONCE with the cached load reason — never silently.
            if self.use_native_hashing:
                from kafka_topic_analyzer_tpu.io.native import native_status

                reason = f"native-{native_status()[1]}"
            else:
                reason = "native-off"
            obs_metrics.FUSED_FALLBACK.labels(reason=reason).inc()
            log.warning(
                "fused decode→pack unavailable (%s); falling back to the "
                "python decode chain", reason,
            )
            sink = None

        import time

        error_streak: Dict[int, int] = {p: 0 for p in parts}
        max_error_streak = 100
        # Transport-fault recovery: reconnect pacing shared by the whole
        # stream, budget per partition.  A partition whose budget runs out
        # DEGRADES (dropped + reported via self.degraded) instead of
        # aborting the scan and discarding every other partition's work.
        backoff = Backoff(self.retry_config)
        budget = PartitionRetryBudget(self.retry_config.retry_budget)
        # Backoff is PER LEADER, not per round: one dead broker must not
        # throttle the still-healthy leaders' throughput, so its partitions
        # are deferred past a retry deadline while everyone else streams.
        leader_fail_streak: Dict[int, int] = {}
        leader_retry_at: Dict[int, float] = {}

        def degrade(p: int, reason: str) -> None:
            if p not in remaining:
                return
            log.error("partition %d degraded: %s", p, reason)
            remaining.discard(p)  # stream-local (this worker's partitions)
            with self._degraded_lock:  # scan-shared across worker streams
                self.degraded[p] = reason
            obs_events.emit("partition_degraded", partition=p, reason=reason)
        # Consecutive fetches for a partition that neither consumed records
        # nor advanced the offset (possible under response-budget pressure
        # from sibling partitions) — bounded so a pathological broker can't
        # livelock the scan.  The bound scales with partition count: the
        # rotated fetch order guarantees a starved partition heads the
        # request within len(parts) rounds.
        stall_streak: Dict[int, int] = {p: 0 for p in parts}
        max_stall = max(max_error_streak, 4 * len(parts))

        inflight: "Dict[int, tuple]" = {}
        conn_lock = threading.Lock()

        def own_conn(leader: int) -> BrokerConnection:
            # Keyed by LEADER id, not (host, port): fetch_leader threads run
            # per leader, and two leader ids advertising the same address
            # (load balancer, port forward) must NOT share a socket — the
            # pipelined send/read halves from two threads would race for
            # each other's response bytes.
            addr = self._brokers.get(leader)
            if addr is None:
                # A recovery-path metadata reload can drop a broker while
                # its partitions still point at it (leaderless election
                # window): a protocol error here books as a transport
                # failure instead of a KeyError aborting the scan.
                raise kc.KafkaProtocolError(
                    f"leader {leader} missing from cluster metadata"
                )
            host, port = addr
            with conn_lock:
                c = own_conns.get(leader)
                if c is not None and (c.host, c.port) != (host, port):
                    # Leader moved (metadata reload): reconnect.
                    c.close()
                    own_conns.pop(leader, None)
                    c = None
                if c is not None:
                    return c
            # Connect OUTSIDE the lock: TCP+TLS+SASL setup can block up to
            # the socket timeout, and one slow broker must not serialize
            # every other leader thread's first round.
            c = BrokerConnection(
                host,
                port,
                self.timeout_s,
                ssl_context=self._ssl_context,
                sasl=self._sasl,
                sock_opts=self._sock_opts,
            )
            with conn_lock:
                winner = own_conns.get(leader)
                if winner is not None:  # lost a (same-leader) race
                    c.close()
                    return winner
                own_conns[leader] = c
            return c

        def fetch_leader(leader: int, lparts: List[int], fetch_round: int):
            """Phase 1 of a round, one leader: (re)send, read, decode —
            ALL the heavy work (socket IO, native scan + record-set
            decode) with no shared-state mutation beyond this leader's
            own connection and inflight slot.  Runs concurrently across
            leaders; phase 2 (the serial loop below) does bookkeeping."""
            conn = own_conn(leader)
            # KIP-74: brokers fill the response budget in request order,
            # so rotate the partition list each round — without this,
            # partitions at the tail of a large sorted list can be
            # starved of response bytes indefinitely.
            lp = sorted(lparts)
            k = fetch_round % len(lp)
            order = lp[k:] + lp[:k]
            # Pipelining: if last round sent ahead for this leader, its
            # response is already in flight.  A stale in-flight
            # (connection changed, or it no longer covers this round's
            # partitions) is drained and discarded — the stream stays
            # ordered either way.
            fl = inflight.pop(leader, None)
            if fl is not None and (
                fl[0] is not conn or not set(lp) <= set(fl[3])
            ):
                try:
                    fl[0].read_response(fl[1])
                except Exception:
                    fl[0].close()
                    with conn_lock:
                        if own_conns.get(leader) is fl[0]:
                            own_conns.pop(leader, None)
                    conn = own_conn(leader)
                fl = None
            if fl is None:
                pmax_sent = self.partition_max_bytes
                fetch_v = self._version(conn, kc.API_FETCH)
                corr = conn.send_request(
                    kc.API_FETCH,
                    fetch_v,
                    kc.encode_fetch_request(
                        self.topic,
                        [
                            (p, next_offset[p], self._epoch_for(p))
                            for p in order
                        ],
                        self.max_wait_ms,
                        self.min_bytes,
                        self.max_bytes,
                        pmax_sent,
                        fetch_v,
                    ),
                )
                fl = (
                    conn,
                    corr,
                    {p: next_offset[p] for p in order},
                    order,
                    pmax_sent,
                )
            conn, corr, sent_offsets, order, pmax_sent = fl
            _t_fetch = _perf_counter()
            with obs_trace.maybe_span("fetch", cat="io"):
                r = conn.read_response(corr)
            # Same window as the span, booked per fetch round — the
            # flight recorder's source-wait track (obs/doctor.py).
            obs_metrics.FETCH_SECONDS.inc(_perf_counter() - _t_fetch)
            fps = kc.decode_fetch_response(r, self._version(conn, kc.API_FETCH))
            obs_metrics.FETCH_REQUESTS.inc()
            obs_metrics.FETCH_BYTES.inc(
                sum(len(fp.records) for fp in fps)
            )
            # Send-ahead: while this response's records decode, let the
            # broker build the NEXT one.  A cheap native header scan of
            # each partition's record set yields the exact offsets
            # processing will arrive at (covered_end, compaction-aware);
            # only clean all-native responses qualify, and a
            # post-processing mismatch discards the speculative response
            # (correctness never depends on the speculation being right).
            spec_sent = False
            #: Clean full-prefix scan results, reused by the decode so
            #: the header (and CRC) walk isn't paid twice.
            scans: "Dict[int, tuple[int, int, int]]" = {}
            if use_native_decode:
                clean = True
                spec: Dict[int, int] = {}
                for fp in fps:
                    p = fp.partition
                    if p not in remaining:
                        continue
                    if fp.error or len(fp.records) == 0:
                        clean = False
                        break
                    nrec, used, covered = scan_record_set_native(
                        fp.records, self.verify_crc
                    )
                    # nrec may be 0 with the whole set consumed — a
                    # marker-only (transaction control) stretch still
                    # speculates: covered advances past it.
                    if used != len(fp.records):
                        clean = False
                        break
                    scans[p] = (nrec, used, covered)
                    if covered <= next_offset[p]:
                        clean = False
                        break
                    spec[p] = min(covered, end[p])
                if clean and spec:
                    lp2 = sorted(
                        p for p in order if p in spec and spec[p] < end[p]
                    )
                    if lp2:
                        k2 = (fetch_round + 1) % len(lp2)
                        order2 = lp2[k2:] + lp2[:k2]
                        pmax2 = self.partition_max_bytes
                        fetch_v2 = self._version(conn, kc.API_FETCH)
                        corr2 = conn.send_request(
                            kc.API_FETCH,
                            fetch_v2,
                            kc.encode_fetch_request(
                                self.topic,
                                [
                                    (p, spec[p], self._epoch_for(p))
                                    for p in order2
                                ],
                                self.max_wait_ms,
                                self.min_bytes,
                                self.max_bytes,
                                pmax2,
                                fetch_v2,
                            ),
                        )
                        inflight[leader] = (
                            conn,
                            corr2,
                            {p: spec[p] for p in order2},
                            order2,
                            pmax2,
                        )
                        spec_sent = True
            # Pre-decode the clean full-prefix record sets here (the
            # expensive, GIL-releasing half); masking and state updates
            # stay in phase 2.  Fused-sink streams skip this: their decode
            # IS the pack, and sink appends must run serially in phase-2
            # order (the scan above still powers the send-ahead).
            soas: "Dict[int, tuple]" = {}
            if scans and sink is None:
                _t_dec = _perf_counter()
                with obs_trace.maybe_span("decode", cat="io"):
                    for fp in fps:
                        p = fp.partition
                        if p in scans:
                            soas[p] = decode_record_set_native(
                                fp.records, self.verify_crc, prescan=scans[p]
                            )
                obs_metrics.DECODE_SECONDS.inc(_perf_counter() - _t_dec)
            return (leader, fps, scans, soas, spec_sent, order, pmax_sent)

        def fetch_leader_guarded(leader: int, lparts: List[int], fetch_round: int):
            """fetch_leader with transport-failure capture: a reset, hang
            (socket timeout), refused reconnect, or truncated/desynced
            stream tears down this leader's connection — including any
            speculative in-flight fetch riding on it — and returns a
            `_TransportFailure` for phase 2 to book, rather than killing
            the scan."""
            try:
                return fetch_leader(leader, lparts, fetch_round)
            except (OSError, kc.KafkaProtocolError) as e:
                inflight.pop(leader, None)
                with conn_lock:
                    c = own_conns.pop(leader, None)
                if c is not None:
                    c.close()
                log.warning(
                    "transport failure on leader %d (%s): %s",
                    leader, type(e).__name__, e,
                )
                obs_metrics.TRANSPORT_FAILURES.inc()
                obs_events.emit(
                    "transport_failure",
                    leader=leader,
                    partitions=sorted(lparts),
                    error=f"{type(e).__name__}: {e}",
                )
                return _TransportFailure(leader, list(lparts), e)

        pool: "object | None" = None

        fetch_round = 0
        while remaining:
            now = time.monotonic()
            by_leader: Dict[int, List[int]] = {}
            deferred: "List[float]" = []
            for p in remaining:
                leader = self._leaders[p]
                retry_at = leader_retry_at.get(leader)
                if retry_at is not None and retry_at > now:
                    deferred.append(retry_at)
                    continue
                by_leader.setdefault(leader, []).append(p)
            if not by_leader:
                # Every remaining partition's leader is inside its backoff
                # window: sleep to the earliest retry deadline instead of
                # spinning the loop.
                sleep_s = min(deferred) - time.monotonic()
                if sleep_s > 0:
                    note_backoff_sleep(sleep_s)
                    time.sleep(sleep_s)
                continue
            progressed = False
            fetch_round += 1
            if len(by_leader) > 1 and pool is None:
                from concurrent.futures import ThreadPoolExecutor

                # max_workers is a CAP, not a pre-spawn: the executor
                # creates threads lazily up to the concurrent task count,
                # so leaders discovered later (metadata reload) still get
                # full parallelism without resizing.
                pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix="kta-fetch"
                )
                pools.append(pool)
            if pool is not None and len(by_leader) > 1:
                results = list(
                    pool.map(
                        lambda kv: fetch_leader_guarded(kv[0], kv[1], fetch_round),
                        by_leader.items(),
                    )
                )
            else:
                results = [
                    fetch_leader_guarded(leader, lparts, fetch_round)
                    for leader, lparts in by_leader.items()
                ]
            transport_failed = False
            for result in results:
                if isinstance(result, _TransportFailure):
                    transport_failed = True
                    streak = leader_fail_streak.get(result.leader, 0) + 1
                    leader_fail_streak[result.leader] = streak
                    # Capped exponential + jitter, paced per leader.  A
                    # post-reload migration hands the partitions a new
                    # leader id with no pending deadline, so they refetch
                    # immediately.
                    leader_retry_at[result.leader] = (
                        time.monotonic() + backoff.delay_ms(streak) / 1000.0
                    )
                    reason = (
                        f"{type(result.error).__name__}: {result.error}"
                    )
                    for p in result.partitions:
                        if p not in remaining:
                            continue
                        if budget.record_failure(p, reason):
                            degrade(p, budget.degraded[p])
                    continue
                leader, fps, scans, soas, spec_sent, order, pmax_sent = result
                leader_fail_streak.pop(leader, None)
                leader_retry_at.pop(leader, None)
                for fp in fps:
                    p = fp.partition
                    if p not in remaining:
                        continue
                    # A response arrived for this partition: its transport
                    # is alive again (protocol errors are tracked by
                    # error_streak separately).
                    budget.record_success(p)
                    if fp.error:
                        # Warn and re-poll, like the reference's poll loop
                        # (src/kafka.rs:95-97) — but with recovery for the
                        # known-persistent errors and a bounded retry budget.
                        log.warning("fetch error %d on partition %d", fp.error, p)
                        obs_metrics.FETCH_ERRORS.inc()
                        obs_events.emit(
                            "fetch_error", partition=p, code=fp.error
                        )
                        error_streak[p] += 1
                        if fp.error == kc.ERR_NOT_LEADER_FOR_PARTITION:
                            self._reload_metadata()
                        elif fp.error in (
                            kc.ERR_FENCED_LEADER_EPOCH,
                            kc.ERR_UNKNOWN_LEADER_EPOCH,
                        ):
                            # KIP-320 fence: the leader's epoch moved past
                            # the one we tracked (election).  Verify the
                            # cursor against the post-election log before
                            # fetching on.
                            obs_metrics.LOG_EPOCH_FENCES.inc()
                            fenced_epoch = self._epoch_for(p)
                            obs_events.emit(
                                "epoch_fence",
                                partition=p,
                                code=fp.error,
                                epoch=fenced_epoch,
                            )
                            # Unfence first: neither the divergence probe
                            # nor the next fetch may re-fence on the stale
                            # epoch (it re-learns the new one from the
                            # next response's batch headers).
                            self._clear_epoch(p)
                            self._reload_metadata()
                            div = self.check_divergence(
                                p, next_offset[p], fenced_epoch
                            )
                            if div is not None:
                                # The log diverged BELOW the cursor: the
                                # folded prefix [div, cursor) described
                                # batches the election threw away (the
                                # span marks the fold non-authoritative),
                                # and the window tail [cursor, end) no
                                # longer exists to read.  Book the whole
                                # destroyed range and finish the partition
                                # — never rewind the cursor into the
                                # replacement log, which would
                                # double-count offsets [div, cursor).
                                self._note_lost(
                                    p, div, end[p], "truncation"
                                )
                                next_offset[p] = end[p]
                                remaining.discard(p)
                            progressed = True
                        elif fp.error == kc.ERR_OFFSET_OUT_OF_RANGE:
                            # Retention advanced past our offset: account
                            # for the lost range [old_next, new_earliest),
                            # then resume there (window stays [.., end)).
                            try:
                                new_start = self._earliest_offset(p)
                            except (OSError, kc.KafkaProtocolError) as e:
                                # Leader unreachable for the re-anchor
                                # lookup: stay put; the streak/budget
                                # bounds the retries.
                                log.warning(
                                    "re-anchor lookup for partition %d "
                                    "failed: %s", p, e,
                                )
                                new_start = next_offset[p]
                            if new_start > next_offset[p]:
                                self._note_lost(
                                    p, next_offset[p], new_start,
                                    "retention",
                                )
                                next_offset[p] = new_start
                                progressed = True
                            else:
                                # Lookup failed, or the broker answered a
                                # log start at/below our cursor (stale
                                # replica, or out-of-range from the HEAD
                                # side after a truncation).  Clamp
                                # monotone — never rewind — book the
                                # non-advance, and leave the round
                                # non-progressing so the streak/budget
                                # bounds engage deterministically.
                                obs_metrics.LOG_LOST_RANGES.labels(
                                    reason="re-anchor-regressed"
                                ).inc()
                                obs_events.emit(
                                    "re_anchor_regressed",
                                    partition=p,
                                    cursor=next_offset[p],
                                    answered=new_start,
                                )
                        if error_streak[p] >= max_error_streak:
                            degrade(
                                p,
                                f"{error_streak[p]} consecutive fetch "
                                f"errors (last: {fp.error})",
                            )
                        continue
                    error_streak[p] = 0
                    self._observe_log_start(p, fp.log_start_offset)
                    # KIP-320: peek the leading batch header's
                    # partition_leader_epoch (fixed at byte 12 of a v2
                    # frame, independent of the native/python decode
                    # split).  A REGRESSION means this response came from
                    # a pre-election log — verify the cursor before
                    # folding past it.
                    if len(fp.records) >= 17 and fp.records[16] == 2:
                        frame_epoch = struct.unpack_from(
                            ">i", fp.records, 12
                        )[0]
                        if self._observe_epoch(p, frame_epoch):
                            div = self.check_divergence(
                                p, next_offset[p], frame_epoch
                            )
                            if div is not None:
                                self._note_lost(
                                    p, div, next_offset[p], "truncation"
                                )
                    consumed = 0
                    # One past the highest offset COVERED by a complete
                    # frame (batch headers keep last_offset_delta across
                    # compaction, so this advances past removed ranges).
                    max_frame_end = -1
                    data = fp.records
                    pre = soas.get(p)
                    if sink is not None and use_native_decode and data:
                        # Fused fast path: the record set's native prefix
                        # decodes→packs straight into the sink's wire-v4
                        # rows in ONE GIL-released C++ pass — the same
                        # acceptance window and next_offset rule as
                        # accept_records, with no SoA intermediate.  The
                        # remainder (compressed/legacy/truncated/
                        # malformed) takes the per-frame chain below,
                        # entering the same rows via push_chunk.
                        _t_dec = _perf_counter()
                        n_acc, used, covered, last = sink.append_record_set(
                            data, next_offset[p], end[p], p,
                            self.verify_crc, prescan=scans.get(p),
                        )
                        # Fused streams skip the phase-1 pre-decode; their
                        # decode IS this pack, booked on the same counter.
                        obs_metrics.DECODE_SECONDS.inc(
                            _perf_counter() - _t_dec
                        )
                        if used:
                            max_frame_end = max(max_frame_end, covered)
                            if n_acc:
                                next_offset[p] = last + 1
                                consumed += n_acc
                                progressed = True
                            data = data[used:] if used < len(data) else b""
                    elif pre is not None or (use_native_decode and data):
                        # Whole-response fast path: every leading complete
                        # uncompressed v2 frame decoded in ONE native call
                        # (already done in phase 1 for clean prefixes);
                        # only the remainder (compressed/legacy/truncated)
                        # takes the per-frame loop below.
                        if pre is not None:
                            soa, used, covered = pre
                        else:
                            # Lazy whole-response decode (no phase-1
                            # prescan): booked on the same counter as the
                            # pre-decode pass — the doctor's decode
                            # evidence must see this path too.
                            _t_dec = _perf_counter()
                            soa, used, covered = decode_record_set_native(
                                data, self.verify_crc, prescan=scans.get(p)
                            )
                            obs_metrics.DECODE_SECONDS.inc(
                                _perf_counter() - _t_dec
                            )
                        if used:
                            max_frame_end = max(max_frame_end, covered)
                            cnt = accept_records(soa, p)
                            if cnt:
                                consumed += cnt
                                progressed = True
                            data = data[used:] if used < len(data) else b""
                    if not isinstance(data, (bytes, bytearray)):
                        # The remainder (compressed/legacy/truncated frames)
                        # goes through the per-frame Python decoders, which
                        # expect a real bytes-like (str decode, hashing).
                        data = bytes(data)
                    corrupt_stop = False
                    corrupt_skipped = False

                    def book_corruption(
                        err, claimed_end, resume_offset, n_records, raw
                    ) -> bool:
                        """One corrupt-frame sighting for partition ``p``:
                        True to keep salvaging this record set, False to
                        stop the partition's round (the span's identical
                        re-fetch is pending, or the partition degraded).
                        Raises under the ``fail`` policy once the damage
                        proves deterministic."""
                        nonlocal progressed, corrupt_skipped
                        anchor = next_offset[p]
                        skip_to = self._note_corrupt(
                            p, anchor, err, claimed_end, resume_offset,
                            n_records, raw,
                        )
                        if skip_to is None:
                            return False  # disambiguating re-fetch pending
                        if skip_to <= anchor:
                            # No usable skip bound (mangled header at the
                            # response tail): retrying would loop on the
                            # same bytes forever, so drop the partition.
                            degrade(
                                p,
                                "unskippable corrupt frame at offset "
                                f"{anchor} ({err.kind})",
                            )
                            return False
                        next_offset[p] = min(skip_to, end[p])
                        corrupt_skipped = True
                        progressed = True
                        return True

                    for item in kc.salvage_batch_frames(
                        data, verify_crc=self.verify_crc
                    ):
                        if isinstance(item, kc.CorruptSpan):
                            if not book_corruption(
                                item.error,
                                item.claimed_end,
                                item.resume_offset,
                                item.num_records,
                                bytes(data[item.start : item.end]),
                            ):
                                corrupt_stop = True
                                break
                            continue
                        frame = item
                        max_frame_end = max(max_frame_end, frame.end_offset)
                        chunk = (
                            decode_records_native(frame)
                            if use_native_decode
                            else None
                        )
                        if chunk is not None:
                            # Keep records in [next_offset, end): compressed
                            # batches can start earlier; records past the
                            # snapshot watermark are out of scope.
                            cnt = accept_records(chunk, p)
                            if cnt:
                                consumed += cnt
                                progressed = True
                            continue
                        # Python fallback (no shim, or malformed frame — the
                        # reference decoder raises the precise error).
                        # Rows commit only after the frame decodes fully, so
                        # a record-body corruption mid-frame cannot leave a
                        # half-accepted frame behind.
                        rows = []
                        row_offs = []
                        frame_next = next_offset[p]
                        try:
                            for off, (ts_ms, key, value) in kc.decode_frame_records(
                                frame
                            ):
                                if off < frame_next:
                                    continue
                                if off >= end[p]:
                                    break
                                rows.append((p, ts_ms, key, value))
                                row_offs.append(off)
                                frame_next = off + 1
                        except kc.CorruptFrameError as ce:
                            raw = (
                                bytes(data[frame.byte_start : frame.byte_end])
                                if frame.byte_start >= 0
                                else b""
                            )
                            if not book_corruption(
                                ce, frame.end_offset, -1,
                                frame.num_records, raw,
                            ):
                                corrupt_stop = True
                                break
                            continue  # poisoned frame's rows are dropped
                        if rows:
                            batch = records_to_batch(
                                rows, use_native=self.use_native_hashing
                            )
                            batch.offsets = np.array(row_offs, dtype=np.int64)
                            push_chunk(batch, reason="python-decode")
                            next_offset[p] = frame_next
                            consumed += len(rows)
                            progressed = True
                    if corrupt_stop:
                        # The partition's round ended at a poisoned span:
                        # either its identical re-fetch happens next round,
                        # or the partition just degraded.  Skip the stall/
                        # fetch-size heuristics — they reason about byte
                        # limits, not poison.
                        stall_streak[p] = 0
                        if next_offset[p] >= end[p]:
                            remaining.discard(p)
                        continue
                    if consumed:
                        stall_streak[p] = 0
                        if max_frame_end > next_offset[p]:
                            # The consumed batch's covered range extends
                            # past its last retained record (tail
                            # compaction): advance to the covered end so
                            # the next fetch doesn't re-serve this batch
                            # just to discard it.
                            next_offset[p] = min(max_frame_end, end[p])
                    elif corrupt_skipped:
                        # Poison skipped but nothing accepted this round
                        # (the skipped frame was the only in-range one):
                        # the skip itself is the progress.
                        stall_streak[p] = 0
                        if max_frame_end > next_offset[p]:
                            next_offset[p] = min(max_frame_end, end[p])
                    elif next_offset[p] < end[p]:
                        if max_frame_end > next_offset[p]:
                            # Complete frames cover our fetch position but
                            # every retained record is out of range —
                            # compaction removed the rest of the covered
                            # span.  Batch headers keep last_offset_delta
                            # across compaction, so skip to one past it.
                            next_offset[p] = min(max_frame_end, end[p])
                            stall_streak[p] = 0
                            progressed = True
                        elif len(fp.records) == 0:
                            if p == order[0]:
                                # We led this request, and brokers return
                                # at least one complete batch for the first
                                # partition with data (KIP-74
                                # minOneMessage) — empty is authoritative:
                                # nothing retained in [next_offset, end).
                                next_offset[p] = end[p]
                                progressed = True
                            else:
                                # A non-leading partition can be starved by
                                # siblings (response budget) or by its own
                                # batch exceeding the per-partition limit;
                                # rotation brings it to the front within
                                # len(parts) rounds for the authoritative
                                # answer.
                                stall_streak[p] += 1
                                if stall_streak[p] >= max_stall:
                                    degrade(
                                        p,
                                        f"{stall_streak[p]} consecutive "
                                        "empty fetches",
                                    )
                        else:
                            # Frames present but none complete at/past our
                            # position: the response was truncated by a byte
                            # limit.  If the per-partition limit was binding
                            # (response filled it), grow it; otherwise the
                            # response-level budget cut us short — refetch,
                            # budget frees as other partitions drain.
                            if len(fp.records) >= pmax_sent:
                                if pmax_sent >= MAX_PARTITION_FETCH_BYTES:
                                    degrade(
                                        p,
                                        "cannot decode fetch response even "
                                        "at max.partition.fetch.bytes="
                                        f"{pmax_sent}",
                                    )
                                    continue
                                with self._fetch_grow_lock:
                                    self.partition_max_bytes = min(
                                        max(
                                            self.partition_max_bytes,
                                            pmax_sent * 2,
                                        ),
                                        MAX_PARTITION_FETCH_BYTES,
                                    )
                                log.warning(
                                    "partition %d: batch exceeds fetch size,"
                                    " growing max.partition.fetch.bytes to %d",
                                    p,
                                    self.partition_max_bytes,
                                )
                                stall_streak[p] = 0
                                progressed = True
                            else:
                                stall_streak[p] += 1
                                if stall_streak[p] >= max_stall:
                                    degrade(
                                        p,
                                        f"{stall_streak[p]} consecutive "
                                        "fetches with no progress "
                                        "(truncated responses)",
                                    )
                    if next_offset[p] >= end[p]:
                        remaining.discard(p)
                if spec_sent:
                    fl2 = inflight.get(leader)
                    if fl2 is not None and any(
                        p in remaining and next_offset[p] != off
                        for p, off in fl2[2].items()
                    ):
                        # Speculation missed (compressed tail, error,
                        # truncation): drain and discard so the next round
                        # fetches from the authoritative offsets.
                        inflight.pop(leader, None)
                        try:
                            fl2[0].read_response(fl2[1])
                        except Exception:
                            fl2[0].close()
                            with conn_lock:
                                if own_conns.get(leader) is fl2[0]:
                                    own_conns.pop(leader, None)
                yield from flush(force=False)
            if transport_failed and remaining:
                # Dead/reset connections this round: refresh the topology
                # (a restarted broker or migrated leader shows up in fresh
                # metadata; partitions re-route via by_leader next round,
                # reconnection happens lazily in own_conn).  Retry pacing
                # is the failed leader's per-leader deadline above — the
                # healthy leaders keep streaming unthrottled.
                self._reload_metadata()
            elif not progressed and remaining:
                # Nothing moved this round (e.g. leader churn): brief
                # pause so error responses don't busy-spin the broker.
                time.sleep(self.error_backoff_ms / 1000.0)
        yield from flush(force=True)

    def _records_to_batch(
        self, rows: List[Tuple[int, int, Optional[bytes], Optional[bytes]]]
    ) -> RecordBatch:
        return records_to_batch(rows, use_native=self.use_native_hashing)


def _chunk_to_batch(
    chunk: "dict[str, np.ndarray]", sel, partition: int
) -> RecordBatch:
    """Native-decoded SoA frame (io/native.py::decode_records_native) →
    RecordBatch for the selected records.

    ``sel`` is a slice (the hot path: in-range records are a contiguous run
    because offsets increase within a record set — columns become zero-copy
    VIEWS of the freshly-allocated SoA buffers) or an index array (the
    fallback when a broker violates the ordering contract).  Bool columns
    are reinterpreted with ``.view``, not ``astype`` — the decoder writes
    0/1 uint8."""
    offs = chunk["offsets"][sel]
    n = len(offs)
    ts_ms = chunk["ts_ms"][sel]
    if isinstance(sel, slice):
        ts_ms = ts_ms.copy()  # about to clamp in place; don't mutate the SoA
    # Missing timestamps (-1) report as 0 ms (``to_millis().unwrap_or(0)``,
    # src/metric.rs:209) — matching records_to_batch.
    np.maximum(ts_ms, 0, out=ts_ms)
    batch = RecordBatch(
        partition=np.full(n, partition, dtype=np.int32),
        key_len=chunk["key_len"][sel],
        value_len=chunk["value_len"][sel],
        key_null=chunk["key_null"][sel].view(np.bool_),
        value_null=chunk["value_null"][sel].view(np.bool_),
        ts_s=ts_ms // 1000,
        key_hash32=chunk["key_hash32"][sel],
        key_hash64=chunk["key_hash64"][sel],
        valid=np.ones(n, dtype=np.bool_),
    )
    batch.offsets = offs
    return batch


def records_to_batch(
    rows: List[Tuple[int, int, Optional[bytes], Optional[bytes]]],
    use_native: bool = True,
) -> RecordBatch:
    """(partition, ts_ms, key, value) rows → RecordBatch with hashes."""
    n = len(rows)
    partition = np.fromiter((r[0] for r in rows), dtype=np.int32, count=n)
    ts_ms = np.fromiter(
        # Missing timestamps (-1) report as 0 ms, like
        # ``to_millis().unwrap_or(0)`` (src/metric.rs:209).
        ((r[1] if r[1] >= 0 else 0) for r in rows),
        dtype=np.int64,
        count=n,
    )
    keys = [r[2] for r in rows]
    values = [r[3] for r in rows]
    key_null = np.fromiter((k is None for k in keys), dtype=np.bool_, count=n)
    value_null = np.fromiter((v is None for v in values), dtype=np.bool_, count=n)
    key_len = np.fromiter(
        (len(k) if k is not None else 0 for k in keys), dtype=np.int32, count=n
    )
    value_len = np.fromiter(
        (len(v) if v is not None else 0 for v in values), dtype=np.int32, count=n
    )
    h32, h64 = _hash_keys(keys, use_native=use_native)
    h32 = np.where(key_null, np.uint32(0), h32)
    h64 = np.where(key_null, np.uint64(0), h64)
    # Truncate toward zero like Rust integer division (src/metric.rs:210).
    ts_s = (np.abs(ts_ms) // 1000) * np.sign(ts_ms)
    return RecordBatch(
        partition=partition,
        key_len=key_len,
        value_len=value_len,
        key_null=key_null,
        value_null=value_null,
        ts_s=ts_s,
        key_hash32=h32,
        key_hash64=h64,
        valid=np.ones(n, dtype=np.bool_),
    )
