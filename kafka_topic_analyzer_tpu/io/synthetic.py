"""Deterministic synthetic topic generator (counter-based RNG).

Benchmark and test workloads (BASELINE.json configs) need reproducible topics
without a live cluster.  Every field of record ``(partition p, offset o)`` is
derived from ``x = splitmix64(splitmix64(seed ^ (p << 40)) + o * GAMMA)`` —
i.e. record o of a partition is the o-th output of a SplitMix64 stream whose
base is itself well mixed.  (A naive ``splitmix64(seed ^ o)`` would make
nearby seeds produce *permutations* of the same record multiset, since
``{seed ^ o}`` ranges over the same block.)  Pure integer bit-fiddling, no
stateful RNG, so the generator is:

- order-independent (any shard can generate any slice),
- trivially vectorizable in numpy,
- mirrored bit-for-bit by the native C++ shim (native/ingest.cpp), which the
  parity tests assert.

Key scheme: keys are fixed-width decimal strings ``k%0*d`` of a *per-partition
disjoint* key id (``key_id = p + P * local``), matching Kafka's invariant that
a key lives in exactly one partition — which is what makes per-shard
last-writer-wins alive tracking exact (records.py ordering contract).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from kafka_topic_analyzer_tpu.io.source import RecordSource
from kafka_topic_analyzer_tpu.ops.fnv import (
    fnv1a32_ref_batch,
    fnv1a64_batch,
    splitmix64_np,
)
from kafka_topic_analyzer_tpu.records import RecordBatch


@dataclasses.dataclass(frozen=True)
class SyntheticSpec:
    num_partitions: int = 1
    messages_per_partition: int = 1_000_000
    #: Distinct keys *per partition* (key ids are partition-disjoint).
    keys_per_partition: int = 10_000
    #: Per-mille of records with a null key.
    key_null_permille: int = 50
    #: Per-mille of records with a null value (tombstones).
    tombstone_permille: int = 100
    value_len_min: int = 100
    #: NOTE: the value-length draw uses 24 bits of the record hash, so the
    #: effective spread (value_len_max - value_len_min + 1) caps at 2^24.
    value_len_max: int = 400
    #: Fixed decimal width of the key id inside the key string "k%0*d".
    key_digits: int = 11
    ts_start_ms: int = 1_600_000_000_000
    ts_step_ms: int = 1
    seed: int = 0x5EED

    @property
    def key_len(self) -> int:
        return 1 + self.key_digits

    def describe(self) -> str:
        return (
            f"synthetic(P={self.num_partitions}, N/p={self.messages_per_partition}, "
            f"K/p={self.keys_per_partition}, seed={self.seed:#x})"
        )

    #: --synthetic key → expected-form hint (doubles as the valid-key set).
    KV_KEYS = {
        "partitions": "a positive integer partition count",
        "messages": "a non-negative integer message count per partition",
        "keys": "a positive integer distinct-key count per partition",
        "key_null": "an integer per-mille in 0..1000 (e.g. 50 = 5%)",
        "tombstones": "an integer per-mille in 0..1000 (e.g. 100 = 10%)",
        "vmin": "a non-negative integer minimum value length in bytes",
        "vmax": "an integer maximum value length in bytes, >= vmin",
        "seed": "an integer (0x… hex accepted)",
    }

    @classmethod
    def from_kv(cls, kv: "dict[str, str]", seed_salt: int = 0) -> "SyntheticSpec":
        """Build a spec from the CLI's comma-separated k=v surface (shared
        by the analyzer CLI and tools/make_segments).  Every rejection
        names the offending key and the expected form (VERDICT r2 weak #3:
        a bare ``invalid literal for int(): '0.05'`` cost real debugging
        time)."""
        for key in kv:
            if key and key not in cls.KV_KEYS:  # "" = trailing comma, ignore
                raise ValueError(
                    f"unknown --synthetic key '{key}': valid keys are "
                    + ", ".join(sorted(cls.KV_KEYS))
                )

        def geti(
            key: str, default: int, base: int = 10,
            lo: "int | None" = None, hi: "int | None" = None,
        ) -> int:
            raw = kv.get(key)
            if raw is None:
                return default
            try:
                val = int(raw, base)
            except ValueError:
                val = None
            if val is None or (lo is not None and val < lo) or (
                hi is not None and val > hi
            ):
                raise ValueError(
                    f"bad --synthetic key '{key}': expected "
                    f"{cls.KV_KEYS[key]}, got '{raw}'"
                )
            return val

        vmin = geti("vmin", 100, lo=0)
        # Default vmax tracks a raised vmin (vmin=500 alone means fixed-size
        # 500 B values, not an error against the stale 400 default).
        vmax = geti("vmax", max(400, vmin), lo=vmin)
        return cls(
            num_partitions=geti("partitions", 1, lo=1),
            messages_per_partition=geti("messages", 1_000_000, lo=0),
            keys_per_partition=geti("keys", 10_000, lo=1),
            key_null_permille=geti("key_null", 50, lo=0, hi=1000),
            tombstone_permille=geti("tombstones", 100, lo=0, hi=1000),
            value_len_min=vmin,
            value_len_max=vmax,
            seed=geti("seed", 0x5EED, base=0) + seed_salt,
        )


def synth_fields(
    spec: SyntheticSpec, partition: np.ndarray, offset: np.ndarray
) -> Dict[str, np.ndarray]:
    """Vectorized field derivation for records (partition[i], offset[i]).

    The exact bit-field layout below is the generator's wire contract; the
    C++ mirror in native/ingest.cpp implements the same expressions.
    """
    p64 = partition.astype(np.uint64)
    o64 = offset.astype(np.uint64)
    # The stream base depends only on the partition: mix once per distinct
    # partition, then gather (halves the hash work per record).
    parts_u, inv = np.unique(p64, return_inverse=True)
    bases = splitmix64_np(np.uint64(spec.seed) ^ (parts_u << np.uint64(40)))
    with np.errstate(over="ignore"):
        x = splitmix64_np(bases[inv] + o64 * np.uint64(0x9E3779B97F4A7C15))

    key_null = (x % np.uint64(1000)).astype(np.int64) < spec.key_null_permille
    value_null = (
        ((x >> np.uint64(10)) % np.uint64(1000)).astype(np.int64)
        < spec.tombstone_permille
    )
    local = ((x >> np.uint64(20)) % np.uint64(spec.keys_per_partition)).astype(
        np.uint64
    )
    key_id = p64 + np.uint64(spec.num_partitions) * local
    vspread = np.uint64(spec.value_len_max - spec.value_len_min + 1)
    value_len = (
        spec.value_len_min + ((x >> np.uint64(40)) % vspread).astype(np.int64)
    ).astype(np.int32)
    value_len = np.where(value_null, 0, value_len).astype(np.int32)

    ts_ms = np.int64(spec.ts_start_ms) + offset.astype(np.int64) * np.int64(
        spec.ts_step_ms
    )
    ts_s = ts_ms // 1000  # second granularity, like src/metric.rs:209-211

    # Key bytes: b"k" + fixed-width decimal of key_id.
    n = partition.shape[0]
    padded = np.zeros((n, spec.key_len), dtype=np.uint8)
    padded[:, 0] = ord("k")
    rem = key_id.copy()
    for d in range(spec.key_digits - 1, -1, -1):
        padded[:, 1 + d] = (rem % np.uint64(10)).astype(np.uint8) + ord("0")
        rem //= np.uint64(10)
    lengths = np.full(n, spec.key_len, dtype=np.int64)
    h32 = fnv1a32_ref_batch(padded, lengths)
    h64 = fnv1a64_batch(padded, lengths)

    key_len = np.where(key_null, 0, spec.key_len).astype(np.int32)
    h32 = np.where(key_null, np.uint32(0), h32)
    h64 = np.where(key_null, np.uint64(0), h64)

    return {
        "partition": partition.astype(np.int32),
        "key_len": key_len,
        "value_len": value_len,
        "key_null": key_null,
        "value_null": value_null,
        "ts_s": ts_s,
        "key_hash32": h32,
        "key_hash64": h64,
        "valid": np.ones(n, dtype=np.bool_),
    }


def synth_key_bytes(spec: SyntheticSpec, key_id: int) -> bytes:
    """Scalar reference for tests: the key byte string for a key id."""
    return b"k" + str(key_id).zfill(spec.key_digits).encode()


class SyntheticSource(RecordSource):
    """Round-robin multiplex of the partitions, like a balanced consumer:
    global index ``g`` maps to partition ``S[g % |S|]`` at offset
    ``g // |S|`` — per-partition offset order by construction."""

    def __init__(self, spec: SyntheticSpec):
        self.spec = spec

    def partitions(self) -> List[int]:
        return list(range(self.spec.num_partitions))

    def watermarks(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        start = {p: 0 for p in self.partitions()}
        end = {p: self.spec.messages_per_partition for p in self.partitions()}
        return start, end

    def batches(
        self,
        batch_size: int,
        partitions: Optional[List[int]] = None,
        start_at: Optional[Dict[int, int]] = None,
    ) -> Iterator[RecordBatch]:
        parts = np.array(
            sorted(partitions) if partitions is not None else self.partitions(),
            dtype=np.int64,
        )
        s = len(parts)
        if s == 0:
            return
        n = self.spec.messages_per_partition
        if start_at:
            # Resumed scans run partition-sequential (the order contract is
            # per-partition only).
            for p in parts.tolist():
                for lo in range(min(start_at.get(p, 0), n), n, batch_size):
                    offset = np.arange(lo, min(lo + batch_size, n), dtype=np.int64)
                    partition = np.full(len(offset), p, dtype=np.int64)
                    yield RecordBatch(**synth_fields(self.spec, partition, offset))
            return
        total = n * s
        for lo in range(0, total, batch_size):
            g = np.arange(lo, min(lo + batch_size, total), dtype=np.int64)
            partition = parts[g % s]
            offset = g // s
            yield RecordBatch(**synth_fields(self.spec, partition, offset))
