// Native ingest shim — the TPU build's counterpart of the reference's only
// native component, librdkafka (Cargo.toml:19; SURVEY.md §2.2).  The
// reference leans on librdkafka's C threads for all wire-level work and then
// processes messages one at a time in Rust; here the native layer's job is
// the *batch extraction* hot path (SURVEY.md §7 hard parts (a)/(b)): produce
// fixed-width record-metadata columns (lengths, null flags, timestamps, key
// hashes) at memory bandwidth so only numeric tensors ever cross into JAX.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image):
//   - kta_synth_batch:   deterministic synthetic workload generation,
//                        bit-identical to io/synthetic.py::synth_fields
//   - kta_hash_batch:    fnv32(reference variant, src/fnv32.rs:92-101) +
//                        standard fnv64 over packed variable-length keys
//   - kta_version:       ABI version stamp
//
// Build: `make -C native` → libkta_ingest.so (g++ -O3, pthreads).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kFnv32Offset = 0x811c9dc5u;
// The reference multiplies by the offset basis, NOT the FNV prime —
// reproduced on purpose (src/fnv32.rs:92-101).
constexpr uint32_t kFnv32Mult = 0x811c9dc5u;
constexpr uint64_t kFnv64Offset = 0xcbf29ce484222325ull;
constexpr uint64_t kFnv64Prime = 0x100000001b3ull;

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

inline uint32_t fnv1a32_ref(const uint8_t* p, int64_t n) {
  uint32_t h = kFnv32Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv32Mult;
  return h;
}

inline uint64_t fnv1a64(const uint8_t* p, int64_t n) {
  uint64_t h = kFnv64Offset;
  for (int64_t i = 0; i < n; ++i) h = (h ^ p[i]) * kFnv64Prime;
  return h;
}

// Parallel-for over [0, n) in contiguous chunks.
template <typename F>
void parallel_for(int64_t n, int threads, F&& body) {
  if (threads <= 1 || n < (1 << 14)) {
    body(0, n);
    return;
  }
  std::vector<std::thread> pool;
  int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Mirrors io/synthetic.py::SyntheticSpec (wire contract — keep in sync).
struct KtaSynthSpec {
  uint64_t seed;
  int32_t num_partitions;
  int64_t messages_per_partition;
  uint64_t keys_per_partition;
  int32_t key_null_permille;
  int32_t tombstone_permille;
  int32_t value_len_min;
  int32_t value_len_max;
  int32_t key_digits;
  int64_t ts_start_ms;
  int64_t ts_step_ms;
};

int32_t kta_version() { return 2; }

// Last-writer-wins dedupe of alive-bitmap updates for one batch
// (the host half of the packed transfer's pre-reduction; see
// kafka_topic_analyzer_tpu/packing.py).  For each slot = h32 & (2^bits - 1)
// of an active record, only the LAST record's aliveness survives —
// equivalent to replaying insert/remove in record order.  Open-addressing
// hash table over the batch (capacity = next pow2 >= 2n), single pass.
// Outputs at most n (slot, alive) pairs; returns the pair count, or -1 on
// bad arguments.
int64_t kta_dedupe_slots(const uint32_t* h32, const uint8_t* active,
                         const uint8_t* alive, int64_t n, int32_t bits,
                         uint32_t* slot_out, uint8_t* alive_out) {
  if (!h32 || !active || !alive || !slot_out || !alive_out || n < 0 ||
      bits < 1 || bits > 32)
    return -1;
  const uint32_t mask =
      bits == 32 ? 0xffffffffu : ((1u << bits) - 1u);
  size_t cap = 16;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  const size_t cap_mask = cap - 1;
  // table: index into out arrays + 1; 0 = empty.
  std::vector<int64_t> table(cap, 0);
  int64_t count = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!active[i]) continue;
    const uint32_t slot = h32[i] & mask;
    size_t pos = (splitmix64(slot) & cap_mask);
    for (;;) {
      int64_t entry = table[pos];
      if (entry == 0) {
        table[pos] = count + 1;
        slot_out[count] = slot;
        alive_out[count] = alive[i];
        ++count;
        break;
      }
      if (slot_out[entry - 1] == slot) {
        alive_out[entry - 1] = alive[i];  // later record wins
        break;
      }
      pos = (pos + 1) & cap_mask;
    }
  }
  return count;
}

// Generate records for global indices [lo, hi) over the partition list
// `parts` (round-robin: g -> parts[g % nparts] at offset g / nparts),
// exactly like SyntheticSource.batches.  All output arrays have hi-lo
// elements.  Returns 0 on success.
int32_t kta_synth_batch(const KtaSynthSpec* spec,
                        const int32_t* parts, int32_t nparts,
                        int64_t lo, int64_t hi, int32_t threads,
                        int32_t* partition_out, int32_t* key_len_out,
                        int32_t* value_len_out, uint8_t* key_null_out,
                        uint8_t* value_null_out, int64_t* ts_s_out,
                        uint32_t* h32_out, uint64_t* h64_out,
                        uint8_t* valid_out) {
  if (!spec || !parts || nparts <= 0 || hi < lo) return -1;
  const int64_t n = hi - lo;
  const KtaSynthSpec s = *spec;
  const int key_len_total = 1 + s.key_digits;

  // Stream bases depend only on the partition — mix once per slot of the
  // round-robin, not once per record.
  std::vector<uint64_t> bases(nparts);
  for (int32_t j = 0; j < nparts; ++j)
    bases[j] = splitmix64(s.seed ^ (static_cast<uint64_t>(parts[j]) << 40));

  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    uint8_t keybuf[64];
    keybuf[0] = 'k';
    for (int64_t i = a; i < b; ++i) {
      const int64_t g = lo + i;
      const int32_t p = parts[g % nparts];
      const int64_t o = g / nparts;
      // Record o is the o-th output of a SplitMix64 stream with a mixed
      // per-partition base (see io/synthetic.py — wire contract).
      const uint64_t x = splitmix64(bases[g % nparts] +
                                    static_cast<uint64_t>(o) * 0x9e3779b97f4a7c15ull);

      const bool key_null =
          static_cast<int64_t>(x % 1000ull) < s.key_null_permille;
      const bool value_null =
          static_cast<int64_t>((x >> 10) % 1000ull) < s.tombstone_permille;
      const uint64_t local = (x >> 20) % s.keys_per_partition;
      const uint64_t key_id =
          static_cast<uint64_t>(p) +
          static_cast<uint64_t>(s.num_partitions) * local;
      const uint64_t vspread =
          static_cast<uint64_t>(s.value_len_max - s.value_len_min + 1);
      const int32_t vlen =
          value_null ? 0
                     : s.value_len_min +
                           static_cast<int32_t>((x >> 40) % vspread);

      partition_out[i] = p;
      value_len_out[i] = vlen;
      key_null_out[i] = key_null ? 1 : 0;
      value_null_out[i] = value_null ? 1 : 0;
      // floor division like numpy (`//`): values are non-negative here.
      ts_s_out[i] = (s.ts_start_ms + o * s.ts_step_ms) / 1000;
      valid_out[i] = 1;

      if (key_null) {
        key_len_out[i] = 0;
        h32_out[i] = 0;
        h64_out[i] = 0;
      } else {
        key_len_out[i] = key_len_total;
        uint64_t rem = key_id;
        for (int d = s.key_digits - 1; d >= 0; --d) {
          keybuf[1 + d] = static_cast<uint8_t>('0' + (rem % 10));
          rem /= 10;
        }
        h32_out[i] = fnv1a32_ref(keybuf, key_len_total);
        h64_out[i] = fnv1a64(keybuf, key_len_total);
      }
    }
  });
  return 0;
}

// Hash n variable-length byte slices packed in `data` at `offsets`
// (offsets[n] marks the end).  Used by the Kafka wire source to hash real
// key bytes off the fetch path.
int32_t kta_hash_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       int32_t threads, uint32_t* h32_out, uint64_t* h64_out) {
  if (!data || !offsets || n < 0) return -1;
  parallel_for(n, threads, [&](int64_t a, int64_t b) {
    for (int64_t i = a; i < b; ++i) {
      const int64_t off = offsets[i];
      const int64_t len = offsets[i + 1] - off;
      h32_out[i] = fnv1a32_ref(data + off, len);
      h64_out[i] = fnv1a64(data + off, len);
    }
  });
  return 0;
}

}  // extern "C"
