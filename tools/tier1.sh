#!/usr/bin/env bash
# Run the ROADMAP.md tier-1 verify line, verbatim.  This is the gate every
# PR must keep green: the fast (`-m 'not slow'`) suite on the CPU backend,
# with a hard wall-clock budget and a stable pass-count readout
# (DOTS_PASSED) that survives pytest's output quirks.  Run from the repo
# root: `bash tools/tier1.sh` (or `make tier1` if you add a Makefile).
cd "$(dirname "$0")/.." || exit 1
bash tools/lint.sh || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
