#!/usr/bin/env bash
# Static lint pass, run as part of tools/tier1.sh.
#
# Rule: library modules never call print().  User-facing output must route
# through report.py (the renderer), the spinner (utils/progress.py), or the
# obs exporters — a print() buried in a library module corrupts --json
# stdout and bypasses the quiet/stats flags.  CLI entry points are exempt:
# cli.py (renders the report + banners), report.py (builds the strings the
# CLI prints), and the kafka_topic_analyzer_tpu/tools/ bench/probe scripts
# (standalone __main__ programs whose stdout IS their output format).
#
# AST-based, not grep: strings like the `python -c "print('ok', ...)"`
# subprocess probe in jax_support.py must not trip it.
cd "$(dirname "$0")/.." || exit 1
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
ALLOWED = {
    PKG / "cli.py",
    PKG / "report.py",
}
ALLOWED_DIRS = (PKG / "tools",)

failures = []
for path in sorted(PKG.rglob("*.py")):
    if path in ALLOWED or any(d in path.parents for d in ALLOWED_DIRS):
        continue
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            failures.append(f"{path}:{node.lineno}: print() in library module")

if failures:
    print("lint: bare print() calls found (route output through report.py,")
    print("lint: the spinner, or the obs exporters):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (no print() in library modules)")
EOF
