#!/usr/bin/env bash
# Static lint pass, run as part of tools/tier1.sh.
#
# Rule: library modules never call print().  User-facing output must route
# through report.py (the renderer), the spinner (utils/progress.py), or the
# obs exporters — a print() buried in a library module corrupts --json
# stdout and bypasses the quiet/stats flags.  CLI entry points are exempt:
# cli.py (renders the report + banners), report.py (builds the strings the
# CLI prints), and the kafka_topic_analyzer_tpu/tools/ bench/probe scripts
# (standalone __main__ programs whose stdout IS their output format).
#
# AST-based, not grep: strings like the `python -c "print('ok', ...)"`
# subprocess probe in jax_support.py must not trip it.
cd "$(dirname "$0")/.." || exit 1
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
ALLOWED = {
    PKG / "cli.py",
    PKG / "report.py",
}
ALLOWED_DIRS = (PKG / "tools",)

failures = []
for path in sorted(PKG.rglob("*.py")):
    if path in ALLOWED or any(d in path.parents for d in ALLOWED_DIRS):
        continue
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "print"
        ):
            failures.append(f"{path}:{node.lineno}: print() in library module")

if failures:
    print("lint: bare print() calls found (route output through report.py,")
    print("lint: the spinner, or the obs exporters):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (no print() in library modules)")
EOF

# Second rule: decode-surface functions under io/ must never raise a BARE
# ValueError or struct.error — untrusted wire input must classify (a typed
# subclass: kafka_codec's CorruptFrameError taxonomy, compression's
# CorruptPayloadError, zstd_py's CorruptZstdStream, segfile's
# CorruptSegmentError family).  The segment READER surface (SegmentFile*,
# SegmentCatalog, *SegmentStore classes) counts as decode surface: .ktaseg
# files are untrusted on-disk input exactly like fetched frames.
# Encode-side helpers (ByteWriter, encode_*, *_compress_*, write_segment*,
# SegmentDumpWriter) are exempt: they validate caller input, not stored
# bytes.
python - <<'EOF'
import ast
import pathlib
import re
import sys

IO_DIR = pathlib.Path("kafka_topic_analyzer_tpu") / "io"
DECODE_SURFACE = re.compile(
    r"decode|decompress|salvage|iter_batch|_iter_frames|_parse_frame"
    r"|_resync|_plausible|scan_record|_read_uvarint|_output_size"
    r"|_output_bound|_snappy_raw|_lz4_block|_decode_legacy"
    r"|SegmentFile|SegmentCatalog|SegmentStore"
    # The fused decode→pack entry points consume the same untrusted wire
    # bytes (io/native.py bindings; decode_pack* is caught by "decode").
    # _raise_pack_range is NOT decode surface: it mirrors the packer's
    # caller-config ValueError (packing.pack_batch), not a wire
    # classification — the wire taxonomy for fused streams still comes
    # from the per-frame chain the walk falls back to.
    r"|pack_append_columns|pack_row_init|append_record_set"
)
ENCODE_SIDE = re.compile(
    r"encode|compress_xerial|compress_frame|_compress\b"
    r"|write_segment|SegmentDumpWriter"
)

failures = []
for path in sorted(IO_DIR.glob("*.py")):
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_Raise(self, node):
            name = None
            exc = node.exc
            if isinstance(exc, ast.Call):
                exc = exc.func
            if isinstance(exc, ast.Name):
                name = exc.id
            elif isinstance(exc, ast.Attribute):
                name = f"{getattr(exc.value, 'id', '?')}.{exc.attr}"
            if name in ("ValueError", "struct.error"):
                qual = ".".join(self.stack)
                in_decode = any(DECODE_SURFACE.search(s) for s in self.stack)
                in_encode = any(
                    ENCODE_SIDE.search(s) and "decompress" not in s
                    for s in self.stack
                ) or "ByteWriter" in self.stack
                if in_decode and not in_encode:
                    failures.append(
                        f"{path}:{node.lineno}: bare {name} in decode-surface "
                        f"function {qual!r}"
                    )
            self.generic_visit(node)

    V().visit(tree)

if failures:
    print("lint: bare ValueError/struct.error raised on the io/ decode")
    print("lint: surface (untrusted wire input must raise a classified")
    print("lint: error type — see io/kafka_codec.py CorruptFrameError):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (io/ decode surface raises only classified error types)")
EOF

# Third rule: parallel-ingest WORKER code paths (methods of *Worker*
# classes in parallel/ingest.py — code that runs on an ingest worker
# thread) must never mutate scan-shared container state without a lock.
# Shared mutable state crosses worker threads ONLY through the per-worker
# queue.Queue (thread-safe by construction) or the obs instruments (each
# guarded by its own lock); any container mutation on `self.X` / a
# closed-over name is flagged unless it sits inside a `with <...lock...>:`
# block.  Local variables are exempt (thread-confined).
python - <<'EOF'
import ast
import pathlib
import sys

PATH = pathlib.Path("kafka_topic_analyzer_tpu") / "parallel" / "ingest.py"
MUTATORS = {
    "update", "setdefault", "pop", "popitem", "clear",
    "append", "extend", "insert", "remove", "add", "discard",
}
#: Receivers whose mutation is the sanctioned cross-thread channel.
SAFE_RECEIVERS = ("queue",)

tree = ast.parse(PATH.read_text(encoding="utf-8"), filename=str(PATH))
failures = []


def local_names(fn) -> set:
    out = set(a.arg for a in fn.args.args)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for n in ast.walk(node.optional_vars):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


def receiver_root(expr):
    """(root, dotted) for a Name/Attribute chain; (None, repr) otherwise."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return expr.id, ".".join(reversed(parts))
    return None, ast.dump(expr)[:40]


def check_worker_fn(cls_name, fn):
    locals_ = local_names(fn)
    guarded = set()  # nodes lexically under a `with <...lock...>` item

    def mark_guarded(node):
        for child in ast.walk(node):
            guarded.add(id(child))

    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                src = ast.unparse(item.context_expr).lower()
                if "lock" in src:
                    mark_guarded(node)

    def flag(node, what, recv):
        if id(node) in guarded:
            return
        failures.append(
            f"{PATH}:{node.lineno}: {what} on scan-shared {recv!r} in "
            f"worker path {cls_name}.{fn.name} without a lock"
        )

    for node in ast.walk(fn):
        # container[key] = / del container[key] / container[key] += on a
        # non-local receiver
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target] if isinstance(node, ast.AugAssign)
                else node.targets
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    root, dotted = receiver_root(t.value)
                    leaf = dotted.rsplit(".", 1)[-1]
                    if (root == "self" or root not in locals_) and not any(
                        s in leaf for s in SAFE_RECEIVERS
                    ):
                        flag(node, "subscript mutation", dotted)
        # container.mutator(...) on a non-local receiver
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATORS:
                root, dotted = receiver_root(node.func.value)
                leaf = dotted.rsplit(".", 1)[-1]
                if (root == "self" or root not in locals_) and not any(
                    s in leaf for s in SAFE_RECEIVERS
                ):
                    flag(node, f".{node.func.attr}()", dotted)


for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef) and "Worker" in node.name:
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_worker_fn(node.name, item)

if failures:
    print("lint: unsynchronized scan-shared container mutation in a")
    print("lint: parallel-ingest worker code path (share through the")
    print("lint: worker queue / obs instruments, or hold a lock):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (parallel-ingest worker paths mutate no unlocked shared state)")
EOF

# Fourth rule: the superbatch drive loop can never hold more than
# --dispatch-depth staged superbatches.  Structurally enforced two ways:
# (a) in-flight dispatch bookkeeping (any attribute whose name contains
#     'inflight') is CONFINED to backends/base.py's DispatchQueue — no
#     drive loop or backend keeps its own unbounded in-flight list;
# (b) every function that records a launch (`.launched(`) also calls the
#     bound (`.throttle(`) in the same body, so a dispatch site cannot
#     launch without first blocking below the depth limit.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
QUEUE_HOME = PKG / "backends" / "base.py"
#: Where device dispatch lives: the backends, the mesh layer, the engine.
#: (io/kafka_wire.py has its own fetch-request `_inflight` — a different,
#: per-connection send-ahead window, bounded by the wire layer itself.)
DISPATCH_SCOPE = [PKG / "engine.py"] + sorted(
    (PKG / "backends").glob("*.py")
) + sorted((PKG / "parallel").glob("*.py"))

failures = []
for path in sorted(PKG.rglob("*.py")):
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    # (a) inflight bookkeeping confined to DispatchQueue.
    if path != QUEUE_HOME and path in DISPATCH_SCOPE:
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and "inflight" in node.attr.lower():
                failures.append(
                    f"{path}:{node.lineno}: in-flight dispatch bookkeeping "
                    f"({node.attr!r}) outside backends/base.DispatchQueue"
                )
    # (b) launch sites must throttle.
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        calls = {
            n.func.attr
            for n in ast.walk(node)
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
        }
        if "launched" in calls and "throttle" not in calls:
            failures.append(
                f"{path}:{node.lineno}: {node.name!r} launches a dispatch "
                "without calling the depth throttle first"
            )

if failures:
    print("lint: superbatch dispatch-depth bound violated (in-flight")
    print("lint: tracking lives in backends/base.DispatchQueue; every")
    print("lint: launch site must throttle to --dispatch-depth first):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (drive loops bound staged superbatches by dispatch depth)")
EOF

# Fifth rule: lockstep safety on the sharded mesh.  Every collective call
# site in the sharded superbatch/drain path must be reachable by ALL
# controllers: a collective launched under a condition that can DIFFER
# between controllers (process-local rows, process index, per-row
# liveness, locally-observed degradation/corruption) is a deadlock — one
# controller enters the collective, its peers never do.  AST rule over
# parallel/sharded.py and engine.py: calls to the collective entry points
# must not sit lexically under an `if`/`while` whose condition (or a
# `for` whose iterable) references a per-controller-varying name.
# Uniform guards (feature flags, superbatch config, `_multiprocess` —
# process_count is the same everywhere) stay legal.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
FILES = [PKG / "parallel" / "sharded.py", PKG / "engine.py"]
#: Host-level collective entry points (methods that launch a program every
#: controller must join).  Traced-code collectives (lax.psum etc.) compile
#: uniformly and are exempt — only runtime call sites can diverge.
COLLECTIVE_ATTRS = {
    "_step", "_superstep", "_any_fn", "_merge", "_pmax_fn",
    "update_shards", "update_shards_superbatch", "global_any",
    "gather_telemetry",
}
COLLECTIVE_NAMES = {"lockstep", "dispatch_fn"}
#: Names whose value varies per controller: a collective under a test of
#: one of these is one-sided.
VARYING = {
    "local_rows", "process_index", "addressable_shards", "feed_rows",
    "alive", "degraded", "corrupt", "local_flag", "step_valid",
    "fed_partitions", "row_workers",
}

failures = []
for path in FILES:
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    # Parent links for ancestor walks.
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def names_in(expr):
        return {
            n.id for n in ast.walk(expr) if isinstance(n, ast.Name)
        } | {
            n.attr for n in ast.walk(expr) if isinstance(n, ast.Attribute)
        }

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        is_collective = (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in COLLECTIVE_ATTRS
        ) or (
            isinstance(node.func, ast.Name)
            and node.func.id in COLLECTIVE_NAMES
        )
        if not is_collective:
            continue
        cur = node
        while cur in parents:
            parent = parents[cur]
            bad = None
            if isinstance(parent, (ast.If, ast.While)) and cur in (
                parent.body + parent.orelse
            ):
                # Only the guarded blocks — not the test expression itself.
                bad = names_in(parent.test) & VARYING
            elif isinstance(parent, ast.For) and cur in parent.body:
                bad = names_in(parent.iter) & VARYING
            elif isinstance(parent, ast.IfExp):
                bad = names_in(parent.test) & VARYING
            if bad:
                label = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else node.func.id
                )
                failures.append(
                    f"{path}:{node.lineno}: collective {label!r} guarded by "
                    f"per-controller-varying name(s) {sorted(bad)} — "
                    "unreachable on peers, would deadlock the fleet"
                )
                break
            cur = parent

if failures:
    print("lint: collective call sites must be reachable by ALL")
    print("lint: controllers (no collective under a per-controller")
    print("lint: early-return or varying condition — DESIGN.md §14):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (sharded collectives sit on lockstep-reachable paths)")
EOF

# Sixth rule: the fused decode→pack path is an OPTIMIZATION, never a
# dependency.  (a) Every fused call site (sink.append_*, sink draining,
# make_sink invocation) must sit under a guard that can turn it off —
# tier-1 passes with the native build disabled via KTA_DISABLE_NATIVE, so
# each site needs a reachable python-chain fallback branch.  (b) The
# kill-switch env knobs must exist where the gates read them.
# packing.py (the sink implementation itself) is exempt: it is only
# reachable through gated call sites, by this very rule.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
FILES = [
    PKG / "engine.py",
    PKG / "io" / "kafka_wire.py",
    PKG / "io" / "segfile.py",
    PKG / "parallel" / "ingest.py",
]
#: Calls that enter the fused path.
FUSED_ATTRS = {
    "append_record_set", "append_columns", "append_batch",
    "take_completed",
}
FUSED_NAMES = {"make_sink"}
#: Names whose truthiness gates the fused path off.
GUARDS = {
    "sink", "fused", "sink_factory", "use_native_decode",
    "native_available", "fused_ingest_enabled", "supports_fused_sink",
}

failures = []
for path in FILES:
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def names_in(expr):
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        label = None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in FUSED_ATTRS
        ):
            # Only sink-ish receivers; batch.take()/writer.append() etc.
            # share method names but different receivers.
            root = node.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if not (isinstance(root, ast.Name) and "sink" in root.id.lower()):
                continue
            label = node.func.attr
        elif isinstance(node.func, ast.Name) and node.func.id in FUSED_NAMES:
            label = node.func.id
        if label is None:
            continue
        guarded = False
        cur = node
        while cur in parents and not guarded:
            parent = parents[cur]
            test = None
            if isinstance(parent, (ast.If, ast.While)) and cur is not parent.test:
                test = parent.test
            elif isinstance(parent, ast.IfExp) and cur is not parent.test:
                test = parent.test
            if test is not None and names_in(test) & GUARDS:
                guarded = True
            cur = parent
        if not guarded:
            failures.append(
                f"{path}:{node.lineno}: fused call {label!r} has no "
                "reachable python-chain fallback guard (sink/fused gate)"
            )

# (b) kill-switch knobs live where the gates read them.
if "KTA_DISABLE_NATIVE" not in (PKG / "io" / "native.py").read_text():
    failures.append(
        "io/native.py: KTA_DISABLE_NATIVE env knob missing (tier-1 must "
        "be runnable with the native build disabled)"
    )
if "KTA_DISABLE_FUSED" not in (PKG / "packing.py").read_text():
    failures.append(
        "packing.py: KTA_DISABLE_FUSED env knob missing from "
        "fused_ingest_enabled"
    )
# (c) alive-pair compaction is an optimization with the same contract:
# the env kill switch must exist at the one resolution site (config.py),
# and the engine must book every bypassed compaction with its reason
# (kta_alive_compaction_off_total — a silent bypass is a lint failure).
if "KTA_DISABLE_COMPACTION" not in (PKG / "config.py").read_text():
    failures.append(
        "config.py: KTA_DISABLE_COMPACTION env knob missing from the "
        "alive_compaction resolution (__post_init__)"
    )
if "ALIVE_COMPACTION_OFF.labels(" not in (PKG / "engine.py").read_text():
    failures.append(
        "engine.py: kta_alive_compaction_off_total booking missing — an "
        "alive-key scan running uncompacted must record its reason"
    )

if failures:
    print("lint: fused decode→pack call sites must be gated so the")
    print("lint: python chain stays reachable (no hard native dependency):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (fused call sites keep a reachable python-chain fallback)")
EOF

# Seventh rule: wire-layout offsets and dtypes may only be derived from
# packing._sections (the single layout source for BOTH wire formats).
# Backends, the native shim glue, the parallel layer, and tests must not
# hand-carve a packed buffer — a literal-offset slice-and-view would pin
# one format's layout and silently skew when the section list changes
# (v4→v5 moved every offset).  AST rule: in the scoped files, (a) no
# references to packing.HEADER_BYTES (offset arithmetic belongs next to
# the section list), and (b) no `.view(dtype)` / `frombuffer`-style
# retyping of a subscript whose slice bounds are integer literals >= 16
# (the header size — i.e. a hard-coded section offset).
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
SCOPE = (
    sorted((PKG / "backends").glob("*.py"))
    + sorted((PKG / "parallel").glob("*.py"))
    + [PKG / "io" / "native.py"]
    + sorted(pathlib.Path("tests").glob("*.py"))
)

#: The compacted pair-table layout (PR 12) has exactly one source too:
#: packing._sections(pair_table=True) behind these helpers.  Scoped files
#: may CALL them (imported from packing) but never re-derive the layout.
PAIR_HELPERS = {
    "pack_pair_table", "unpack_pair_table_device",
    "unpack_pair_table_numpy", "pair_table_capacity", "pair_table_nbytes",
}

failures = []
for path in SCOPE:
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    packing_imports = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module
            and node.module.endswith("packing")
        ):
            packing_imports |= {a.name for a in node.names}
    for node in ast.walk(tree):
        # (c) pair-table helpers must come from packing (no local
        # reimplementation/shadowing of the pair-table buffer layout;
        # wrappers that CALL the imported helpers are fine).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name in PAIR_HELPERS
        ):
            failures.append(
                f"{path}:{node.lineno}: local {node.name!r} definition "
                "shadows the packing helper — the pair-table layout "
                "lives in packing._sections only"
            )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in PAIR_HELPERS
            and node.func.id not in packing_imports
        ):
            failures.append(
                f"{path}:{node.lineno}: {node.func.id} called without "
                "importing it from packing — pair tables are only "
                "addressed via packing._sections' helpers"
            )
        # (a) HEADER_BYTES belongs to packing.py.
        if isinstance(node, ast.Name) and node.id == "HEADER_BYTES":
            failures.append(
                f"{path}:{node.lineno}: HEADER_BYTES referenced outside "
                "packing.py — derive section positions from "
                "packing._sections"
            )
        if (
            isinstance(node, ast.Attribute)
            and node.attr == "HEADER_BYTES"
        ):
            failures.append(
                f"{path}:{node.lineno}: packing.HEADER_BYTES referenced — "
                "derive section positions from packing._sections"
            )
        # (b) literal-offset slice retyped in place: buf[123:456].view(...)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "view"
            and isinstance(node.func.value, ast.Subscript)
            and isinstance(node.func.value.slice, ast.Slice)
        ):
            sl = node.func.value.slice
            bounds = [
                b.value
                for b in (sl.lower, sl.upper)
                if isinstance(b, ast.Constant) and isinstance(b.value, int)
            ]
            if any(b >= 16 for b in bounds):
                failures.append(
                    f"{path}:{node.lineno}: hard-coded wire offset "
                    "(literal slice + .view) — derive offsets from "
                    "packing._sections / unpack_numpy"
                )

if failures:
    print("lint: wire-layout offsets hard-coded outside packing._sections")
    print("lint: (the section list is the single layout source — wire v4")
    print("lint: AND v5; see packing.py module docstring / DESIGN.md §16):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (wire offsets derive only from packing._sections)")
EOF

# Eighth rule: every instrument in obs/metrics.py (the one catalog module)
# must carry a cross-process MERGE POLICY and a README catalog row.
# Counters and histograms are additive by construction (obs/registry.py's
# merge algebra — the only sound policy for monotone series), so their
# policy is the type itself; gauges are ambiguous (fleet-max vs
# disjoint-local-sum) and MUST pass an explicit merge= keyword — a gauge
# added without one silently gets max-merged, which undercounts every
# disjoint-per-process quantity the moment a mesh scan gathers telemetry.
# And every constructed metric name must have a row in the README metric
# catalog, so the documented surface can never lag the shipped one.
python - <<'EOF'
import ast
import pathlib
import sys

METRICS = pathlib.Path("kafka_topic_analyzer_tpu") / "obs" / "metrics.py"
README = pathlib.Path("README.md").read_text(encoding="utf-8")

failures = []
names = []
tree = ast.parse(METRICS.read_text(encoding="utf-8"), filename=str(METRICS))
for node in ast.walk(tree):
    if not (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("counter", "gauge", "histogram")
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "_REG"
    ):
        continue
    if not (
        node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        failures.append(
            f"{METRICS}:{node.lineno}: instrument name must be a string "
            "literal (the catalog is audited statically)"
        )
        continue
    name = node.args[0].value
    names.append((node.lineno, name))
    if node.func.attr == "gauge":
        kws = {kw.arg for kw in node.keywords}
        if "merge" not in kws:
            failures.append(
                f"{METRICS}:{node.lineno}: gauge {name!r} does not declare "
                "an explicit merge= policy (max for same-quantity gauges, "
                "sum for disjoint per-process counts)"
            )

for lineno, name in names:
    if name not in README:
        failures.append(
            f"{METRICS}:{lineno}: instrument {name!r} has no README "
            "metric-catalog row"
        )

if failures:
    print("lint: obs/metrics.py instruments must declare a merge policy")
    print("lint: (explicit merge= on every gauge) and carry a README")
    print("lint: metric-catalog row:")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print(f"lint: OK ({len(names)} instruments: merge policies declared, "
      "README catalog rows present)")
EOF

# Ninth rule: the service HTTP surface can never stall ingest.  Handler
# code (do_* methods / BaseHTTPRequestHandler subclasses) under serve/
# and obs/exporters.py may not call into the drive loop or fold state
# (run/update/finalize/get_state/..., the source read loop, the window
# fold), may not take locks of its own (.acquire / `with <lock>`), and
# may not SERIALIZE (json.dumps / gzip.compress / GzipFile): encoding
# happens ONCE on the publishing side (serve/state.py's publish-time
# triple, history/flight's *_bytes accessors) — a handler that
# serializes per request turns N pollers into N encodes and re-creates
# the very cost the conditional-GET plane removes.  Everything a handler
# serves must come through a designated snapshot accessor —
# ServiceState.entry, healthz_entry, window_etag/window_bytes,
# series_etag/series_bytes, subscribe/next_frame, or render_prometheus
# over a registry snapshot — whose single-reference-swap locking is
# owned by the publishing side.  A scrape is then O(headers) work, and a
# slow client can never hold a lock the fold path wants (DESIGN.md §18
# snapshot-consistency rule, §26 read path).  The SSE publisher
# (serve/push.py SsePublisher) is the one piece of serving-plane code
# with its own thread + lock, so it gets the complementary no-fold-state
# check: its methods may never reach a drive-loop entry point either —
# it consumes published events, it never drives publishing.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
#: obs/health.py and obs/history.py are in scope for the same reason
#: exporters.py is: the /healthz and /history surfaces live behind them,
#: and any handler code that grows there inherits the purity rule.
SCOPE = sorted((PKG / "serve").glob("*.py")) + [
    PKG / "obs" / "exporters.py",
    PKG / "obs" / "health.py",
    PKG / "obs" / "history.py",
]
#: Drive-loop / fold-state entry points a handler must never reach.
DRIVE_CALLS = {
    "run", "run_scan", "run_follow",
    "update", "update_shards", "update_superbatch",
    "update_shards_superbatch", "finalize",
    "get_state", "set_state", "get_state_local", "set_state_local",
    "observe_batch", "observe", "merge", "merged",
    "batches", "refresh_watermarks", "watermarks",
    "publish", "request_stop",
    # Alert-engine mutation points: a probe must never trigger an
    # evaluation (evaluation belongs to the poll/heartbeat boundaries).
    "evaluate", "maybe_evaluate", "append",
}
#: The sanctioned read-only snapshot accessors.  /healthz reads the
#: engine's pre-serialized verdict; /history and /flight read their
#: stores' pre-encoded (body, etag) pairs under the stores' own locks;
#: /report.json reads the publish-time (raw, gzipped, etag) triple;
#: /events reads pre-formatted frames off its subscriber queue.
ACCESSORS = {"report_bytes", "snapshot", "series", "active",
             "render_prometheus", "healthz", "window", "doc",
             "alerts_block", "entry", "healthz_entry",
             "window_etag", "window_bytes", "series_etag", "series_bytes",
             "subscribe", "unsubscribe", "next_frame"}
#: Per-request serialization is forbidden in handlers: encoding is paid
#: once at publish time, never per scrape (DESIGN.md §26).
SERIALIZERS = {"dumps", "dump", "compress", "GzipFile"}

failures = []
for path in SCOPE:
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))

    handler_fns = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            bases = {
                getattr(b, "id", getattr(b, "attr", "")) for b in node.bases
            }
            is_handler_cls = node.name.endswith("Handler") or any(
                "Handler" in b for b in bases
            )
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if is_handler_cls or item.name.startswith("do_"):
                        handler_fns.append((node.name, item))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("do_"):
                handler_fns.append(("", node))

    for cls_name, fn in handler_fns:
        qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = None
                if isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                elif isinstance(node.func, ast.Name):
                    name = node.func.id
                if name in ACCESSORS:
                    continue
                if name in DRIVE_CALLS:
                    failures.append(
                        f"{path}:{node.lineno}: HTTP handler {qual!r} calls "
                        f"drive-loop/fold-state entry point {name!r} — serve "
                        "from the designated snapshot accessor instead"
                    )
                if name in SERIALIZERS:
                    failures.append(
                        f"{path}:{node.lineno}: HTTP handler {qual!r} "
                        f"serializes per request ({name!r}) — encoding is "
                        "paid once at publish time (serve/state.py, the "
                        "history/flight *_bytes accessors), never per scrape"
                    )
                if name == "acquire":
                    failures.append(
                        f"{path}:{node.lineno}: HTTP handler {qual!r} takes "
                        "a lock (.acquire) — locking belongs to the snapshot "
                        "accessor, not the scrape path"
                    )
            if isinstance(node, ast.With):
                for item in node.items:
                    src = ast.unparse(item.context_expr).lower()
                    if "lock" in src:
                        failures.append(
                            f"{path}:{node.lineno}: HTTP handler {qual!r} "
                            "holds a lock (`with ...lock...`) — serve "
                            "pre-published snapshots instead"
                        )

# The SSE publisher's no-fold-state check: SsePublisher consumes the
# publish stream, it must never drive it.  Its own intake deque/subscriber
# list mutations (.append) and its own lock are its sanctioned machinery,
# so only the fold/drive entry points are forbidden — not container
# mutators or locking.
PUSH = PKG / "serve" / "push.py"
FOLD_CALLS = DRIVE_CALLS - {"append", "request_stop"}
push_tree = ast.parse(PUSH.read_text(encoding="utf-8"), filename=str(PUSH))
for node in ast.walk(push_tree):
    if not (isinstance(node, ast.ClassDef) and node.name == "SsePublisher"):
        continue
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in ast.walk(item):
            if isinstance(n, ast.Call):
                name = None
                if isinstance(n.func, ast.Attribute):
                    name = n.func.attr
                elif isinstance(n.func, ast.Name):
                    name = n.func.id
                if name in FOLD_CALLS and name not in ACCESSORS:
                    failures.append(
                        f"{PUSH}:{n.lineno}: SsePublisher.{item.name} calls "
                        f"drive-loop/fold-state entry point {name!r} — the "
                        "publisher consumes published events, it never "
                        "drives publishing"
                    )

if failures:
    print("lint: service HTTP handlers must read only designated snapshot")
    print("lint: accessors (no drive-loop calls, no per-request")
    print("lint: serialization, no fold-state locks — a slow scrape can")
    print("lint: never stall ingest; DESIGN.md §18/§26):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (service HTTP handlers read only published snapshots; "
      "SSE publisher drives nothing)")
EOF

# Tenth rule: the fleet admission layer is PURE BOOKKEEPING.  (a) The
# scheduler (fleet/scheduler.py) — and any HTTP-handler code under
# fleet/ — may not call collective or drive-loop entry points directly
# (rule 9's surface plus the host-level collectives from rule 5): the
# layer that decides WHO runs must never be the layer that runs them, or
# an admission decision could block on a fetch, hold a fold lock, or
# launch a one-sided collective.  Only fleet/service.py drives scans.
# (b) Every admission decision books a kta_fleet_* reason: each decision
# method on the scheduler (admit/release/skip/rebalance families) must
# reference a FLEET_* instrument — the admission trace must be
# reconstructible from the counters alone (DESIGN.md §20).
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
FLEET = sorted((PKG / "fleet").glob("*.py"))
SCHEDULER = PKG / "fleet" / "scheduler.py"
#: Rule 9's drive-loop surface + rule 5's host-level collectives.
FORBIDDEN = {
    "run", "run_scan", "run_follow", "run_batch",
    "update", "update_shards", "update_superbatch",
    "update_shards_superbatch", "finalize",
    "get_state", "set_state", "get_state_local", "set_state_local",
    "observe_batch", "observe", "batches",
    "refresh_watermarks", "watermarks",
    "global_any", "gather_telemetry", "_step", "_superstep",
}
#: Scheduler methods that ARE admission decisions: each must book.
DECISION_PREFIXES = ("admit", "release", "skip_", "rebalance")

failures = []
for path in FLEET:
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    is_scheduler = path == SCHEDULER
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            bases = {
                getattr(b, "id", getattr(b, "attr", "")) for b in node.bases
            }
            is_handler = node.name.endswith("Handler") or any(
                "Handler" in b for b in bases
            )
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                check_calls = is_scheduler or is_handler
                if check_calls:
                    for n in ast.walk(item):
                        if isinstance(n, ast.Call):
                            name = None
                            if isinstance(n.func, ast.Attribute):
                                name = n.func.attr
                            elif isinstance(n.func, ast.Name):
                                name = n.func.id
                            if name in FORBIDDEN:
                                failures.append(
                                    f"{path}:{n.lineno}: fleet scheduler/"
                                    f"handler {node.name}.{item.name} calls "
                                    f"drive-loop/collective entry point "
                                    f"{name!r} — only fleet/service.py "
                                    "drives scans"
                                )
                if is_scheduler and item.name.startswith(DECISION_PREFIXES):
                    books = any(
                        isinstance(n, ast.Attribute)
                        and n.attr.startswith("FLEET_")
                        for n in ast.walk(item)
                    )
                    if not books:
                        failures.append(
                            f"{path}:{item.lineno}: admission decision "
                            f"{node.name}.{item.name} books no kta_fleet_* "
                            "reason (obs/metrics FLEET_* instrument)"
                        )

if failures:
    print("lint: the fleet admission layer must stay pure bookkeeping")
    print("lint: (no drive-loop/collective calls from the scheduler or")
    print("lint: fleet handlers; every admission decision books a")
    print("lint: kta_fleet_* reason — DESIGN.md §20):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (fleet scheduler is pure; admission decisions book reasons)")
EOF

# Eleventh rule: the remote segment tier's network I/O is confined to the
# retry-budget wrapper.  (a) Raw HTTP request primitives (.request /
# .getresponse) may appear ONLY inside io/objstore.py's RetryingHttp —
# the one class that paces attempts through io/retry.Backoff and routes
# failure streaks through the PartitionRetryBudget; any other call site
# would be a bare retry loop (or no retry at all).  (b) No other io/
# module may import an HTTP client (http.client, urllib.request) — the
# object-store protocol has exactly one door.  (The Kafka wire client's
# raw socket use is its own protocol layer, with its own PR-1 budget.)  (c) No unbooked sleeps:
# time.sleep is forbidden in io/objstore.py, io/segstore.py and
# io/segfile.py (pacing goes through Backoff.sleep_for, which books
# kta_backoff_sleep_seconds_total).  (d) Every fallback-to-direct-fetch
# path books a kta_segstore_* reason: each except handler in
# SegmentCache's get/put must either re-raise or reference the
# SEGSTORE_FALLBACK instrument (via _book_fallback) — a silent cache
# bypass is a lint failure.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
OBJSTORE = PKG / "io" / "objstore.py"
NO_SLEEP = [OBJSTORE, PKG / "io" / "segstore.py", PKG / "io" / "segfile.py"]
NET_MODULES = {"http", "urllib"}

failures = []

# (a) request/getresponse confined to RetryingHttp.
tree = ast.parse(OBJSTORE.read_text(encoding="utf-8"), filename=str(OBJSTORE))
class_of = {}
for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef):
        for child in ast.walk(node):
            class_of.setdefault(id(child), node.name)
for node in ast.walk(tree):
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in ("request", "getresponse", "urlopen")
    ):
        if class_of.get(id(node)) != "RetryingHttp":
            failures.append(
                f"{OBJSTORE}:{node.lineno}: raw HTTP call "
                f"{node.func.attr!r} outside RetryingHttp (the "
                "retry-budget wrapper is the only network door)"
            )

# (b) no other io/ module imports an HTTP/socket client.
for path in sorted((PKG / "io").glob("*.py")):
    if path == OBJSTORE:
        continue
    t = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(t):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods = [node.module]
        for mod in mods:
            root = mod.split(".")[0]
            if root in NET_MODULES:
                failures.append(
                    f"{path}:{node.lineno}: imports {mod!r} — remote-store "
                    "network I/O belongs to io/objstore.py's RetryingHttp"
                )

# (c) no unbooked sleeps on the remote tier.
for path in NO_SLEEP:
    t = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(t):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "sleep"
        ):
            failures.append(
                f"{path}:{node.lineno}: bare sleep() — pace retries via "
                "io/retry.Backoff.sleep_for (booked) instead"
            )

# (d) cache fallback paths book their reason.
def references_fallback(handler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Name) and "fallback" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and n.attr.startswith("SEGSTORE_"):
            return True
        if isinstance(n, ast.Raise):
            return True
    return False

for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef) and node.name == "SegmentCache":
        for item in node.body:
            if not isinstance(item, ast.FunctionDef):
                continue
            if item.name not in ("get", "put"):
                continue
            for n in ast.walk(item):
                if isinstance(n, ast.ExceptHandler) and not (
                    references_fallback(n)
                ):
                    # Handlers that only signal a MISS (return None) are
                    # cache-absent, not a fallback: the miss counter in
                    # the same body books them.  Require at least the
                    # miss/fallback instrument in the enclosing function.
                    books = any(
                        isinstance(m, ast.Attribute)
                        and m.attr.startswith("SEGSTORE_")
                        for m in ast.walk(item)
                    )
                    if not books:
                        failures.append(
                            f"{OBJSTORE}:{n.lineno}: SegmentCache."
                            f"{item.name} swallows an error without "
                            "booking a kta_segstore_* reason"
                        )

if failures:
    print("lint: remote segment tier network/booking discipline violated")
    print("lint: (HTTP only via RetryingHttp, sleeps only via Backoff,")
    print("lint: cache bypasses always booked — DESIGN.md §21):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (remote segment tier: one network door, booked fallbacks)")
EOF

# Twelfth rule: no silent alert-state changes.  The health engine's rule
# state machine (obs/health.py) may change an alert's state ONLY inside
# HealthEngine._transition — the one method that books
# kta_alerts_transitions_total{rule=,state=} (and moves the firing
# gauge / emits the typed event).  AST-enforced two ways:
# (a) every assignment to a `.state` attribute in obs/health.py sits
#     lexically inside `_transition` (dataclass field defaults are
#     class-body Name targets, not attribute assignments, and stay
#     legal);
# (b) `_transition` itself references the ALERTS_TRANSITIONS instrument
#     and the event bus — a transition that books nothing is a lint
#     failure, not a code-review nit.
python - <<'EOF'
import ast
import pathlib
import sys

HEALTH = pathlib.Path("kafka_topic_analyzer_tpu") / "obs" / "health.py"

tree = ast.parse(HEALTH.read_text(encoding="utf-8"), filename=str(HEALTH))
failures = []

# Map every node to its enclosing function name.
enclosing = {}


def walk(node, fn_name):
    for child in ast.iter_child_nodes(node):
        name = fn_name
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = child.name
        enclosing[id(child)] = name
        walk(child, name)


walk(tree, "<module>")

transition_fn = None
for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
        node.name == "_transition"
    ):
        transition_fn = node
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr == "state":
                if enclosing.get(id(node)) != "_transition":
                    failures.append(
                        f"{HEALTH}:{node.lineno}: alert state assigned "
                        f"outside HealthEngine._transition (silent state "
                        "change) — route it through _transition"
                    )

if transition_fn is None:
    failures.append(f"{HEALTH}: HealthEngine._transition missing")
else:
    names = {
        n.attr for n in ast.walk(transition_fn)
        if isinstance(n, ast.Attribute)
    } | {
        n.id for n in ast.walk(transition_fn) if isinstance(n, ast.Name)
    }
    if "ALERTS_TRANSITIONS" not in names:
        failures.append(
            f"{HEALTH}:{transition_fn.lineno}: _transition does not book "
            "kta_alerts_transitions_total (obs/metrics ALERTS_TRANSITIONS)"
        )
    if "emit" not in names:
        failures.append(
            f"{HEALTH}:{transition_fn.lineno}: _transition emits no typed "
            "event on the JSONL bus"
        )

if failures:
    print("lint: alert-state transitions must all route through")
    print("lint: HealthEngine._transition, which books the transitions")
    print("lint: counter and emits the typed event (DESIGN.md §22):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (alert-state transitions book their reason; none silent)")
EOF

# Thirteenth rule: no silent lease-ownership changes.  The fleet's
# arbitration layer (fleet/lease.py) may change a held lease's state
# ONLY inside LeaseManager._transition — the one method that books the
# kta_lease_* instruments (acquisitions/held/losses, plus
# kta_fleet_failovers_total on takeover) and emits the typed event.
# AST-enforced three ways:
# (a) every assignment to a `.state` attribute in fleet/lease.py sits
#     lexically inside `_transition` (dataclass field defaults are
#     class-body Name targets, not attribute assignments, and stay
#     legal);
# (b) `_transition` itself references the lease instruments and the
#     event bus — a transition that books nothing is a lint failure;
# (c) every acquire/renew/release/fence decision method books a reason:
#     it must reference a LEASE_*/FLEET_FAILOVERS instrument, call
#     `_transition`, or delegate to another decision method — no
#     decision path is silent.
python - <<'EOF'
import ast
import pathlib
import sys

LEASE = pathlib.Path("kafka_topic_analyzer_tpu") / "fleet" / "lease.py"

tree = ast.parse(LEASE.read_text(encoding="utf-8"), filename=str(LEASE))
failures = []

# Map every node to its enclosing function name.
enclosing = {}


def walk(node, fn_name):
    for child in ast.iter_child_nodes(node):
        name = fn_name
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = child.name
        enclosing[id(child)] = name
        walk(child, name)


walk(tree, "<module>")

DECISION_PREFIXES = ("acquire", "renew", "release", "fence")
INSTRUMENTS = {
    "LEASE_ACQUISITIONS", "LEASE_RENEWALS", "LEASE_LOSSES", "LEASE_HELD",
    "FLEET_FAILOVERS",
}


def refs(fn):
    return {
        n.attr for n in ast.walk(fn) if isinstance(n, ast.Attribute)
    } | {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}


transition_fn = None
decision_fns = []
for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        if node.name == "_transition":
            transition_fn = node
        stripped = node.name.lstrip("_")
        if stripped.startswith(DECISION_PREFIXES):
            decision_fns.append(node)
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if isinstance(t, ast.Attribute) and t.attr == "state":
                if enclosing.get(id(node)) != "_transition":
                    failures.append(
                        f"{LEASE}:{node.lineno}: lease state assigned "
                        f"outside LeaseManager._transition (silent "
                        "ownership change) — route it through _transition"
                    )

if transition_fn is None:
    failures.append(f"{LEASE}: LeaseManager._transition missing")
else:
    names = refs(transition_fn)
    if not (INSTRUMENTS & names):
        failures.append(
            f"{LEASE}:{transition_fn.lineno}: _transition books no "
            "kta_lease_* instrument (obs/metrics LEASE_*)"
        )
    if "emit" not in names:
        failures.append(
            f"{LEASE}:{transition_fn.lineno}: _transition emits no typed "
            "event on the JSONL bus"
        )

if not decision_fns:
    failures.append(
        f"{LEASE}: no acquire/renew/release/fence decision methods found"
    )
for fn in decision_fns:
    names = refs(fn)
    delegates = any(
        n.lstrip("_").startswith(DECISION_PREFIXES)
        for n in names
        if n != fn.name
    )
    if not (INSTRUMENTS & names) and "_transition" not in names and (
        not delegates
    ):
        failures.append(
            f"{LEASE}:{fn.lineno}: decision method {fn.name} books no "
            "kta_lease_* reason (no instrument, no _transition, no "
            "delegation to a booking decision method)"
        )

if failures:
    print("lint: lease-ownership transitions must all route through")
    print("lint: LeaseManager._transition, which books the kta_lease_*")
    print("lint: instruments and emits the typed event (DESIGN.md §23):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (lease transitions book their reason; none silent)")
EOF

# Fourteenth rule: no silent cursor jumps past unread log.  The wire
# source (io/kafka_wire.py) may advance a partition cursor past offsets
# it never read ONLY on a path that books the skip: the kta_log_*
# family through KafkaWireSource._note_lost (retention races, epoch
# fences, truncation, resume-below-log-start) or the corruption ledger
# through _note_corrupt/book_corruption (poison-frame skips).
# AST-enforced four ways:
# (a) _note_lost is the one loss choke point: it books BOTH kta_log_*
#     counters, emits the typed event, and carries the --on-data-loss
#     fail abort (DataLossError) — booking that cannot meter or abort
#     is a lint failure;
# (b) every function classifying a log-mutation signal (referencing
#     ERR_OFFSET_OUT_OF_RANGE / ERR_FENCED_LEADER_EPOCH /
#     ERR_UNKNOWN_LEADER_EPOCH) must reach _note_lost or a LOG_*
#     instrument — no mutation-classified path is silent;
# (c) every subscript assignment to a cursor map (next_offset/offsets)
#     whose value is NOT a read-derived progression (last+1, covered,
#     frame_next, max_frame_end — values bounded by frames actually
#     read) sits in a function that references a booking helper or a
#     LOG_*/CORRUPT* instrument;
# (d) the follow service's watermark poll (serve/follow.py _poll) books
#     kta_log_watermark_regressions_total and emits the event before it
#     holds or adopts a regressed head.
python - <<'EOF'
import ast
import pathlib
import sys

WIRE = pathlib.Path("kafka_topic_analyzer_tpu") / "io" / "kafka_wire.py"
FOLLOW = pathlib.Path("kafka_topic_analyzer_tpu") / "serve" / "follow.py"

failures = []

MUTATION_SIGNALS = {
    "ERR_OFFSET_OUT_OF_RANGE",
    "ERR_FENCED_LEADER_EPOCH",
    "ERR_UNKNOWN_LEADER_EPOCH",
}
BOOKERS = {"_note_lost", "_note_corrupt", "book_corruption"}
CURSOR_MAPS = {"next_offset", "offsets"}
#: Value leaves that prove the advance is bounded by frames actually
#: read (batch-header progression), not by watermarks or probes.
PROGRESSION_NAMES = {"last", "covered", "frame_next", "max_frame_end"}


def refs(fn):
    return {
        n.attr for n in ast.walk(fn) if isinstance(n, ast.Attribute)
    } | {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}


def books(names):
    return bool(
        BOOKERS & names
        or any(n.startswith(("LOG_", "CORRUPT")) for n in names)
    )


def nearest_functions(tree):
    enclosing = {}

    def walk(node, fn):
        for child in ast.iter_child_nodes(node):
            f = fn
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                f = child
            enclosing[id(child)] = f
            walk(child, f)

    walk(tree, None)
    return enclosing


wire_tree = ast.parse(WIRE.read_text(encoding="utf-8"), filename=str(WIRE))
enclosing = nearest_functions(wire_tree)

# (a) the choke point itself.
note_lost = None
for node in ast.walk(wire_tree):
    if isinstance(node, ast.FunctionDef) and node.name == "_note_lost":
        note_lost = node
if note_lost is None:
    failures.append(f"{WIRE}: KafkaWireSource._note_lost missing")
else:
    names = refs(note_lost)
    for need in ("LOG_LOST_RECORDS", "LOG_LOST_RANGES", "emit",
                 "DataLossError"):
        if need not in names:
            failures.append(
                f"{WIRE}:{note_lost.lineno}: _note_lost does not "
                f"reference {need} — loss booking must meter both "
                "kta_log_* counters, emit the event, and carry the "
                "--on-data-loss fail abort"
            )

# (b) mutation-signal classification is never silent.
for node in ast.walk(wire_tree):
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        continue
    names = refs(node)
    if MUTATION_SIGNALS & names and node.name != "_note_lost":
        if "_note_lost" not in names and not any(
            n.startswith("LOG_") for n in names
        ):
            failures.append(
                f"{WIRE}:{node.lineno}: {node.name} classifies a "
                "log-mutation signal but never reaches _note_lost or a "
                "kta_log_* instrument"
            )

# (c) cursor jumps book their reason.
for node in ast.walk(wire_tree):
    if not isinstance(node, ast.Assign):
        continue
    for t in node.targets:
        if not (
            isinstance(t, ast.Subscript)
            and isinstance(t.value, ast.Name)
            and t.value.id in CURSOR_MAPS
        ):
            continue
        value_leaves = {
            n.id for n in ast.walk(node.value) if isinstance(n, ast.Name)
        }
        if value_leaves & PROGRESSION_NAMES:
            continue  # read-derived advance: always legal
        fn = enclosing.get(id(node))
        fn_names = refs(fn) if fn is not None else set()
        if not books(fn_names):
            failures.append(
                f"{WIRE}:{node.lineno}: cursor jump "
                f"({t.value.id}[...] = non-progression value) in "
                f"{getattr(fn, 'name', '<module>')} books no kta_log_*/"
                "corruption reason — a skip past unread offsets must be "
                "accounted"
            )

# (d) the follow poll books watermark regressions.
follow_tree = ast.parse(
    FOLLOW.read_text(encoding="utf-8"), filename=str(FOLLOW)
)
poll = None
for node in ast.walk(follow_tree):
    if isinstance(node, ast.FunctionDef) and node.name == "_poll":
        poll = node
if poll is None:
    failures.append(f"{FOLLOW}: FollowService._poll missing")
else:
    names = refs(poll)
    if "LOG_WATERMARK_REGRESSIONS" not in names:
        failures.append(
            f"{FOLLOW}:{poll.lineno}: _poll handles end-watermark "
            "regression without booking "
            "kta_log_watermark_regressions_total"
        )
    if "emit" not in names:
        failures.append(
            f"{FOLLOW}:{poll.lineno}: _poll emits no typed event for "
            "watermark regression"
        )

if failures:
    print("lint: cursor advances past unread log must book a kta_log_*")
    print("lint: (or corruption) reason — the scan never skips offsets")
    print("lint: silently (DESIGN.md §24):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (cursor jumps book their loss reason; none silent)")
EOF

# Fifteenth rule: one fetch scheduler per process (DESIGN.md §25).  The
# process-wide scheduler in io/fetchsched.py is the single admission
# point for every remote segment byte, so: (a) no privately-constructed
# pools or bare threads in io/segstore.py, io/objstore.py or
# io/segfile.py — ThreadPoolExecutor / threading.Thread constructions
# (and concurrent.futures imports) are forbidden there; io/fetchsched.py
# is the only module of the remote tier allowed to spawn workers.
# (b) Cache-trust latching is confined to its choke points: the
# SegmentCache._trusted set may be touched ONLY inside _latch_trusted /
# _unlatch_trusted / _is_trusted (plus the __init__ assignment), and the
# hit-side choke point must book kta_segstore_cache_verify_latched_total
# — an unbooked trust decision is a lint failure.
python - <<'EOF'
import ast
import pathlib
import sys

PKG = pathlib.Path("kafka_topic_analyzer_tpu")
OBJSTORE = PKG / "io" / "objstore.py"
NO_POOLS = [OBJSTORE, PKG / "io" / "segstore.py", PKG / "io" / "segfile.py"]

failures = []

# (a) no private pools/threads outside the scheduler.
for path in NO_POOLS:
    t = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    for node in ast.walk(t):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods = [node.module]
        for mod in mods:
            if mod.split(".")[0] == "concurrent":
                failures.append(
                    f"{path}:{node.lineno}: imports {mod!r} — remote "
                    "fetch concurrency belongs to io/fetchsched.py's "
                    "process-wide scheduler"
                )
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            if name in ("ThreadPoolExecutor", "Thread"):
                failures.append(
                    f"{path}:{node.lineno}: constructs {name} — the "
                    "process-wide fetch scheduler (io/fetchsched.py) is "
                    "the only worker pool of the remote tier"
                )

# (b) trust latching confined to the booked choke points.
tree = ast.parse(OBJSTORE.read_text(encoding="utf-8"), filename=str(OBJSTORE))
CHOKE = {"_latch_trusted", "_unlatch_trusted", "_is_trusted", "__init__"}
cache = None
for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef) and node.name == "SegmentCache":
        cache = node
if cache is None:
    failures.append(f"{OBJSTORE}: SegmentCache missing")
else:
    func_of = {}
    for item in cache.body:
        if isinstance(item, ast.FunctionDef):
            for child in ast.walk(item):
                func_of.setdefault(id(child), item.name)
    for node in ast.walk(cache):
        if isinstance(node, ast.Attribute) and node.attr == "_trusted":
            fn = func_of.get(id(node))
            if fn not in CHOKE:
                failures.append(
                    f"{OBJSTORE}:{node.lineno}: SegmentCache._trusted "
                    f"touched in {fn!r} — trust transitions go through "
                    "_latch_trusted/_unlatch_trusted/_is_trusted only"
                )
    hit_side = next(
        (i for i in cache.body
         if isinstance(i, ast.FunctionDef) and i.name == "_is_trusted"),
        None,
    )
    if hit_side is None:
        failures.append(
            f"{OBJSTORE}: SegmentCache._is_trusted (the hit-side trust "
            "choke point) missing"
        )
    elif not any(
        isinstance(n, ast.Attribute)
        and n.attr == "SEGSTORE_CACHE_VERIFY_LATCHED"
        for n in ast.walk(hit_side)
    ):
        failures.append(
            f"{OBJSTORE}:{hit_side.lineno}: _is_trusted serves latched "
            "hits without booking "
            "kta_segstore_cache_verify_latched_total"
        )

if failures:
    print("lint: one fetch scheduler per process violated (no private")
    print("lint: pools on the remote tier; cache-trust latching only via")
    print("lint: its booked choke points — DESIGN.md §25):")
    for f in failures:
        print(f"  {f}")
    sys.exit(1)
print("lint: OK (one fetch scheduler; trust latching booked at its choke points)")
EOF
