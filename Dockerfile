# Runtime image (parity with the reference's Dockerfile, which ships the
# release binary on fedora:33 — and whose ENTRYPOINT is literally /usr/bin/bash,
# a quirk not replicated here).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY kafka_topic_analyzer_tpu ./kafka_topic_analyzer_tpu
COPY native ./native
RUN pip install --no-cache-dir "jax[cpu]" numpy && pip install --no-cache-dir . \
    && make -C native

ENTRYPOINT ["kta"]
