# Runtime image (parity with the reference's Dockerfile, which ships the
# release binary on fedora:33 — and whose ENTRYPOINT is literally /usr/bin/bash,
# a quirk not replicated here).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY kafka_topic_analyzer_tpu ./kafka_topic_analyzer_tpu
RUN pip install --no-cache-dir "jax[cpu]" numpy && pip install --no-cache-dir . \
    # Warm-build the native shim into the INSTALLED copy (cd out of /app so
    # the import resolves site-packages, not the source tree).
    && cd /tmp \
    && python -c "from kafka_topic_analyzer_tpu.io.native import load_library; load_library()"

ENTRYPOINT ["kta"]
