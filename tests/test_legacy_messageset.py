"""Legacy MessageSet v0/v1 decode (pre-0.11 segments that survive on
upgraded clusters; librdkafka reads these transparently so the reference
does too — /root/reference/Cargo.toml:19, consumed blindly at
src/kafka.rs:93).  Covers uncompressed sets, compressed wrapper-message
recursion with relative/absolute inner offsets, LogAppendTime wrappers,
CRC verification, mixed-format record sets, end-to-end scans through the
fake broker, and truncation/garbage fuzz."""

import random
import struct
import zlib

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeBroker

RECORDS = [
    (100, 1_600_000_000_000, b"k1", b"v1"),
    (101, 1_600_000_000_123, None, b"v2"),       # null key
    (105, 1_600_000_001_000, b"k3", None),       # tombstone, offset gap
    (106, 1_600_000_002_000, b"", b""),          # empty (not null) k/v
]


def _decode(buf, verify_crc=True):
    return [
        (off, ts, k, v)
        for off, (ts, k, v) in kc.decode_record_batches(buf, verify_crc=verify_crc)
    ]


@pytest.mark.parametrize("magic", [0, 1])
def test_uncompressed_roundtrip(magic):
    buf = kc.encode_message_set(RECORDS, magic=magic)
    got = _decode(buf)
    if magic == 1:
        assert got == RECORDS
    else:  # v0 has no timestamps: they read as -1 ("missing")
        assert got == [(o, -1, k, v) for o, _, k, v in RECORDS]


@pytest.mark.parametrize("magic", [0, 1])
@pytest.mark.parametrize(
    "codec", [kc.COMPRESSION_GZIP, kc.COMPRESSION_SNAPPY, kc.COMPRESSION_LZ4]
)
def test_compressed_wrapper_roundtrip(magic, codec):
    buf = kc.encode_message_set(RECORDS, magic=magic, compression=codec)
    got = _decode(buf)
    if magic == 1:
        assert got == RECORDS  # relative inner offsets resolved via wrapper
    else:
        assert got == [(o, -1, k, v) for o, _, k, v in RECORDS]


def test_v1_wrapper_log_append_time():
    buf = kc.encode_message_set(
        RECORDS, magic=1, compression=kc.COMPRESSION_GZIP, log_append_time=True
    )
    got = _decode(buf)
    wrapper_ts = RECORDS[-1][1]
    assert got == [(o, wrapper_ts, k, v) for o, _, k, v in RECORDS]


def test_v1_wrapper_compacted_first_inner():
    """The log cleaner can remove the FIRST inner record of a wrapper, so
    relative offsets need not start at 0; base = wrapper - last holds
    regardless."""
    inner = b"".join(
        kc._encode_legacy_message(rel, ts, k, v, 1)
        for rel, (_, ts, k, v) in zip([2, 3, 5], RECORDS[:3])
    )
    co = zlib.compressobj(wbits=31)
    payload = co.compress(inner) + co.flush()
    buf = kc._encode_legacy_message(
        105, RECORDS[2][1], None, payload, 1, kc.COMPRESSION_GZIP
    )
    assert [o for o, *_ in _decode(buf)] == [102, 103, 105]


def test_malformed_legacy_entries_raise_protocol_error():
    """Undersized entries and nested wrappers must surface as
    KafkaProtocolError, never IndexError/struct.error/RecursionError."""
    # 17-byte tail claiming magic 1 with batch_length 5.
    tiny = struct.pack(">qi", 0, 5) + b"\x00\x00\x00\x00\x01"
    with pytest.raises(kc.KafkaProtocolError, match="minimum size"):
        _decode(tiny, verify_crc=False)
    # Wrapper nested inside a wrapper.
    lvl1 = kc.encode_message_set(
        RECORDS[:1], magic=1, compression=kc.COMPRESSION_GZIP
    )
    co = zlib.compressobj(wbits=31)
    payload = co.compress(lvl1) + co.flush()
    lvl2 = kc._encode_legacy_message(
        0, 0, None, payload, 1, kc.COMPRESSION_GZIP
    )
    with pytest.raises(kc.KafkaProtocolError, match="nested"):
        _decode(lvl2, verify_crc=False)


def test_v1_wrapper_absolute_inner_offsets():
    """Some old producers wrote absolute inner offsets even in magic-1
    wrappers; base = wrapper_offset - last_inner then comes out 0, so the
    unconditional rule handles both conventions."""
    inner = b"".join(
        kc._encode_legacy_message(off, ts, k, v, 1)
        for off, ts, k, v in RECORDS
    )
    co = zlib.compressobj(wbits=31)
    payload = co.compress(inner) + co.flush()
    buf = kc._encode_legacy_message(
        RECORDS[-1][0], RECORDS[-1][1], None, payload, 1, kc.COMPRESSION_GZIP
    )
    assert _decode(buf) == RECORDS


def test_crc_verification():
    buf = bytearray(kc.encode_message_set(RECORDS[:1], magic=1))
    buf[-1] ^= 0xFF  # flip a value byte: CRC32 over the message body breaks
    with pytest.raises(kc.KafkaProtocolError, match="CRC"):
        _decode(bytes(buf), verify_crc=True)
    assert len(_decode(bytes(buf), verify_crc=False)) == 1  # unchecked path


def test_mixed_format_record_set():
    """A fetch response can contain old magic-0/1 entries followed by
    modern v2 batches (segments written across upgrades)."""
    v0 = kc.encode_message_set([(0, -1, b"a", b"x")], magic=0)
    v1 = kc.encode_message_set([(1, 1_600_000_000_000, b"b", b"y")], magic=1)
    v2 = kc.encode_record_batch([(2, 1_600_000_001_000, b"c", b"z")])
    got = _decode(v0 + v1 + v2)
    assert [o for o, *_ in got] == [0, 1, 2]
    assert [k for _, _, k, _ in got] == [b"a", b"b", b"c"]


def test_partial_trailing_legacy_entry_tolerated():
    full = kc.encode_message_set(RECORDS, magic=1)
    truncated = full + full[:20]  # 12-byte header + part of the message
    assert _decode(truncated) == RECORDS


def test_fuzz_truncations_and_garbage():
    rng = random.Random(5)
    base = kc.encode_message_set(
        RECORDS * 5, magic=1, compression=kc.COMPRESSION_GZIP
    )
    for i in range(150):
        if i % 2:
            buf = base[: rng.randrange(1, len(base))]
        else:
            buf = bytearray(base)
            for _ in range(rng.randrange(1, 5)):
                buf[rng.randrange(len(buf))] ^= rng.randrange(1, 256)
            buf = bytes(buf)
        try:
            _decode(buf, verify_crc=False)
        except kc.KafkaProtocolError:
            pass  # the only acceptable failure mode


@pytest.mark.parametrize("magic", [0, 1])
def test_wire_scan_legacy_broker(magic):
    """End-to-end: a broker serving magic-0/1 segments scans correctly,
    including through the native-decode code path (which must fall back to
    Python for legacy frames)."""
    rows = [
        (i, 1_600_000_000_000 + i * 1000,
         f"k{i % 7}".encode() if i % 3 else None,
         None if i % 11 == 5 else bytes(10 + i % 30))
        for i in range(400)
    ]
    with FakeBroker(
        "old.topic", {0: rows, 1: rows[:123]},
        message_magic=magic, max_records_per_fetch=90,
    ) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "old.topic")
        cfg = AnalyzerConfig(
            num_partitions=2, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=20,
        )
        result = run_scan("old.topic", src, CpuExactBackend(cfg, init_now_s=10**10), 128)
        src.close()
    m = result.metrics
    assert m.overall_count == 400 + 123
    assert m.overall_size == sum(
        (len(k) if k else 0) + (len(v) if v else 0) for _, _, k, v in rows
    ) + sum(
        (len(k) if k else 0) + (len(v) if v else 0) for _, _, k, v in rows[:123]
    )
    if magic == 1:
        assert m.earliest_ts_s == 1_600_000_000
    else:
        assert m.earliest_ts_s == 0  # v0: no timestamps -> unwrap_or(0)


@pytest.mark.parametrize("codec", [kc.COMPRESSION_GZIP, kc.COMPRESSION_SNAPPY])
def test_wire_scan_legacy_compressed_broker(codec):
    rows = [(i, 1_600_000_000_000 + i, f"k{i}".encode(), bytes(20))
            for i in range(200)]
    with FakeBroker(
        "old.topic", {0: rows}, message_magic=1, compression=codec,
        max_records_per_fetch=60,
    ) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "old.topic")
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        m = run_scan("old.topic", src, CpuExactBackend(cfg, init_now_s=0), 64).metrics
        src.close()
    assert m.overall_count == 200
