"""TPU backend parity vs the CPU-exact oracle (runs on the virtual CPU
platform in tests; same code path runs on real TPU).

Counters must match bit-for-bit; sketches within their error budgets
(SURVEY.md §4 backend-contract tests).
"""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

SPEC = SyntheticSpec(
    num_partitions=3,
    messages_per_partition=5_000,
    keys_per_partition=400,
    key_null_permille=80,
    tombstone_permille=150,
    value_len_min=50,
    value_len_max=350,
    seed=7,
)


def run_both(config: AnalyzerConfig, spec: SyntheticSpec = SPEC):
    cpu = CpuExactBackend(config, init_now_s=10**10)
    tpu = TpuBackend(config, init_now_s=10**10)
    src = SyntheticSource(spec)
    for batch in src.batches(config.batch_size):
        cpu.update(batch)
        tpu.update(batch)
    return cpu.finalize(), tpu.finalize()


def test_exact_counters_parity():
    cfg = AnalyzerConfig(num_partitions=3, batch_size=2048)
    m_cpu, m_tpu = run_both(cfg)
    assert np.array_equal(m_cpu.per_partition, m_tpu.per_partition)
    assert m_cpu.earliest_ts_s == m_tpu.earliest_ts_s
    assert m_cpu.latest_ts_s == m_tpu.latest_ts_s
    assert m_cpu.smallest_message == m_tpu.smallest_message
    assert m_cpu.largest_message == m_tpu.largest_message
    assert m_cpu.overall_size == m_tpu.overall_size
    assert m_cpu.overall_count == m_tpu.overall_count


def test_alive_bitmap_parity():
    cfg = AnalyzerConfig(
        num_partitions=3,
        batch_size=1024,
        count_alive_keys=True,
        alive_bitmap_bits=22,
    )
    m_cpu, m_tpu = run_both(cfg)
    assert m_cpu.alive_keys == m_tpu.alive_keys
    # With a roomy bitmap and few keys, the bitmap count equals the true
    # number of alive keys from a sequential dict replay.
    replay = {}
    for batch in SyntheticSource(SPEC).batches(4096):
        keyed = ~batch.key_null
        for h, dead in zip(
            batch.key_hash64[keyed].tolist(), batch.value_null[keyed].tolist()
        ):
            replay[h] = not dead
        # (offset order within partitions is preserved by the source)
    assert m_cpu.alive_keys == sum(replay.values())


def test_hll_within_error_budget():
    cfg = AnalyzerConfig(num_partitions=3, batch_size=2048, enable_hll=True, hll_p=14)
    m_cpu, m_tpu = run_both(cfg)
    exact = m_cpu.distinct_keys_exact
    assert exact == 3 * 400
    est = m_tpu.distinct_keys_hll
    assert est == pytest.approx(exact, rel=0.05)  # p=14 → ~0.8% σ; 5% is 6σ


def test_hll_estimator_accurate_across_range():
    """Ertl's improved estimator at the default p=16: accurate across the
    full range, INCLUDING the classic estimator's weak band around the old
    linear-counting crossover (2.5m = 163,840) where r3's config-3 budget
    breach lived.  1.7% bound = 4σ at p=16's 0.41% standard error."""
    import numpy as np

    from kafka_topic_analyzer_tpu.ops.hll import hll_estimate
    from kafka_topic_analyzer_tpu.packing import hll_idx_rho_numpy

    p, m = 16, 1 << 16
    rng = np.random.default_rng(11)
    for n in (1_000, 100_000, 163_840, 327_680, 2_000_000):
        for _ in range(3):
            h64 = rng.integers(0, 2**63, size=n, dtype=np.uint64)
            idx, rho = hll_idx_rho_numpy(h64, np.ones(n, dtype=bool), p)
            regs = np.zeros(m, dtype=np.int64)
            np.maximum.at(regs, idx.astype(np.int64), rho.astype(np.int64))
            est = hll_estimate(regs)
            assert est == pytest.approx(n, rel=0.017), n


def test_hll_default_precision_handles_small_cardinalities():
    """The default config (hll_p now 16) on a small topic: Ertl's sigma
    term takes over where linear counting used to — estimates must stay
    tight when almost every register is zero."""
    cfg = AnalyzerConfig(num_partitions=3, batch_size=2048, enable_hll=True)
    assert cfg.hll_p == 16
    m_cpu, m_tpu = run_both(cfg)
    assert m_cpu.distinct_keys_exact == 3 * 400
    assert m_tpu.distinct_keys_hll == pytest.approx(1200, rel=0.02)


def test_ddsketch_within_alpha():
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=2048, enable_quantiles=True, quantile_alpha=0.005
    )
    m_cpu, m_tpu = run_both(cfg)
    assert m_cpu.quantiles is not None and m_tpu.quantiles is not None
    for q_exact, q_sketch in zip(m_cpu.quantiles.values, m_tpu.quantiles.values):
        assert q_sketch == pytest.approx(q_exact, rel=0.011)  # 2*alpha + rank slack


def test_per_partition_hll_within_budget():
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=2048,
        distinct_keys_per_partition=True, hll_p=12,
    )
    m_cpu, m_tpu = run_both(cfg)
    assert m_cpu.distinct_keys_exact_per_partition == [400, 400, 400]
    assert len(m_tpu.distinct_keys_hll_per_partition) == 3
    for exact, est in zip(
        m_cpu.distinct_keys_exact_per_partition,
        m_tpu.distinct_keys_hll_per_partition,
    ):
        assert est == pytest.approx(exact, rel=0.1)  # p=12 → ~1.6% σ
    # Global line = union of rows (partition-disjoint keys → 1200).
    assert m_tpu.distinct_keys_hll == pytest.approx(1200, rel=0.1)


def test_per_partition_quantiles_within_alpha():
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=2048, enable_quantiles=True,
        quantiles_per_partition=True, quantile_alpha=0.005,
    )
    m_cpu, m_tpu = run_both(cfg)
    assert len(m_cpu.quantiles_per_partition) == 3
    assert len(m_tpu.quantiles_per_partition) == 3
    for exact, sketch in zip(m_cpu.quantiles_per_partition, m_tpu.quantiles_per_partition):
        for q_exact, q_sketch in zip(exact.values, sketch.values):
            assert q_sketch == pytest.approx(q_exact, rel=0.011)
    # Global line still matches the single-sketch path.
    for q_exact, q_sketch in zip(m_cpu.quantiles.values, m_tpu.quantiles.values):
        assert q_sketch == pytest.approx(q_exact, rel=0.011)


def test_batch_padding_is_inert():
    cfg = AnalyzerConfig(num_partitions=3, batch_size=4096)
    # 15000 records into 4096-sized padded steps exercises padding heavily.
    m_cpu, m_tpu = run_both(cfg)
    assert m_tpu.overall_count == 15_000


def test_prepare_staged_updates_match_direct_updates():
    """prepare()+update(StagedBatch) must be byte-identical to direct
    update(RecordBatch) — the engine stages on prefetch workers, so any
    divergence would corrupt scans only in the threaded path."""
    import numpy as np

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    cfg = AnalyzerConfig(
        num_partitions=5, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=16, enable_hll=True, hll_p=10,
        enable_quantiles=True,
    )
    spec = SyntheticSpec(
        num_partitions=5, messages_per_partition=700,
        keys_per_partition=90, tombstone_permille=120, seed=77,
    )
    batches = [
        b.pad_to(cfg.batch_size)
        for b in SyntheticSource(spec).batches(cfg.batch_size)
    ]
    direct = TpuBackend(cfg, init_now_s=0)
    staged = TpuBackend(cfg, init_now_s=0)
    for b in batches:
        direct.update(b)
        staged.update(staged.prepare(b))
    md, ms = direct.finalize(), staged.finalize()
    assert np.array_equal(md.per_partition, ms.per_partition)
    assert np.array_equal(md.per_partition_extremes, ms.per_partition_extremes)
    assert md.overall_count == ms.overall_count
    assert md.overall_size == ms.overall_size
    assert md.alive_keys == ms.alive_keys
    assert md.distinct_keys_hll == ms.distinct_keys_hll
    assert list(md.quantiles.values) == list(ms.quantiles.values)
