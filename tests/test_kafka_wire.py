"""Kafka wire-protocol client against the in-process fake broker.

Covers: codec roundtrips (varints, record batches, CRC32-C, gzip), the
topology handshake, the full fetch loop with multi-fetch pagination,
compaction gaps, null keys/values, missing timestamps, and end-to-end
metric parity with a direct scan of the same records.
"""

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource, parse_bootstrap
from kafka_topic_analyzer_tpu.records import RecordBatch

from fake_broker import FakeBroker, FakeCluster


# ---------------------------------------------------------------------------
# codec units


def test_varint_roundtrip():
    w = kc.ByteWriter()
    values = [0, 1, -1, 2, -2, 127, 128, -300, 10**12, -(10**12)]
    for v in values:
        w.varint(v)
    r = kc.ByteReader(w.done())
    assert [r.varint() for _ in values] == values


@pytest.mark.parametrize("compression", [kc.COMPRESSION_NONE, kc.COMPRESSION_GZIP])
def test_record_batch_roundtrip(compression):
    records = [
        (100, 1_600_000_000_000, b"k1", b"v1"),
        (101, 1_600_000_000_123, None, b"v2"),       # null key
        (105, 1_600_000_001_000, b"k3", None),       # tombstone, offset gap
        (106, -1, b"", b""),                          # empty (not null) k/v
    ]
    buf = kc.encode_record_batch(records, compression)
    got = [(off, ts, k, v) for off, (ts, k, v) in kc.decode_record_batches(buf, verify_crc=True)]
    assert got == records


def test_record_batch_crc_detects_corruption():
    buf = bytearray(kc.encode_record_batch([(0, 0, b"k", b"v")]))
    buf[-1] ^= 0xFF
    with pytest.raises(kc.KafkaProtocolError, match="CRC"):
        list(kc.decode_record_batches(bytes(buf), verify_crc=True))


def test_partial_trailing_batch_tolerated():
    full = kc.encode_record_batch([(0, 0, b"k", b"v"), (1, 0, b"k2", b"v2")])
    truncated = full + full[: len(full) // 2]
    assert len(list(kc.decode_record_batches(truncated))) == 2


def test_from_timestamp_scan():
    """Scan from a point in time via the broker's timestamp index."""
    # Partition 0: ts 1.6e12 + i*1000 ms at offsets 0..99.
    rows = [(i, 1_600_000_000_000 + i * 1000, f"k{i}".encode(), bytes(10))
            for i in range(100)]
    with FakeBroker("ts.topic", {0: rows}) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "ts.topic")
        # Cutoff mid-stream: first record with ts >= cutoff is offset 40.
        offs = src.offsets_for_timestamp(1_600_000_000_000 + 39_500)
        assert offs == {0: 40}
        cfg = AnalyzerConfig(num_partitions=1, batch_size=32)
        be = CpuExactBackend(cfg, init_now_s=10**10)
        m = run_scan("ts.topic", src, be, 32, start_at=offs).metrics
        assert m.overall_count == 60  # offsets 40..99
        assert m.earliest_ts_s == (1_600_000_000_000 + 40_000) // 1000
        # Cutoff beyond every record: nothing scanned.
        offs2 = src.offsets_for_timestamp(2_000_000_000_000)
        assert offs2 == {0: 100}  # end watermark
        src.close()


def test_cli_from_timestamp_flags():
    from kafka_topic_analyzer_tpu.cli import parse_timestamp_ms

    assert parse_timestamp_ms("1600000000000") == 1_600_000_000_000
    assert parse_timestamp_ms("2020-09-13T12:26:40") == 1_600_000_000_000
    assert parse_timestamp_ms("2020-09-13T12:26:40+00:00") == 1_600_000_000_000
    with pytest.raises(ValueError, match="from-timestamp"):
        parse_timestamp_ms("not-a-time")


def test_crc32c_native_matches_python():
    import ctypes
    import os

    from kafka_topic_analyzer_tpu.io.kafka_codec import _crc32c_py
    from kafka_topic_analyzer_tpu.io.native import load_library, native_available

    if not native_available():
        pytest.skip("native shim unavailable")  # fallback would self-compare
    lib = load_library()
    for data in (b"", b"a", b"123456789", os.urandom(100_001)):
        native = int(lib.kta_crc32c(data, ctypes.c_int64(len(data))))
        assert native == _crc32c_py(data)
    # Known CRC32-C vector: "123456789" -> 0xE3069283.
    assert _crc32c_py(b"123456789") == 0xE3069283


def test_parse_bootstrap():
    assert parse_bootstrap("a:9092,b") == [("a", 9092), ("b", 9092)]


def test_parse_bootstrap_ipv6():
    # Bracketed with and without port, and bare IPv6 literals (which contain
    # multiple colons and must not be split at the last one).
    assert parse_bootstrap("[::1]:9093") == [("::1", 9093)]
    assert parse_bootstrap("[2001:db8::1]") == [("2001:db8::1", 9092)]
    assert parse_bootstrap("::1") == [("::1", 9092)]
    assert parse_bootstrap("[::1]:9093,plain:9094,2001:db8::2") == [
        ("::1", 9093), ("plain", 9094), ("2001:db8::2", 9092),
    ]


def test_librdkafka_passthrough_knobs(caplog):
    """The broadened --librdkafka surface: socket/fetch knobs map onto the
    client, reference-style properties (src/kafka.rs:24-44) are accepted
    silently, and only truly unknown names warn."""
    import logging

    records = {0: _mk_records(0, 30)}
    with FakeBroker("wire.topic", records) as broker:
        with caplog.at_level(logging.DEBUG, "kafka_topic_analyzer_tpu.io.kafka_wire"):
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", "wire.topic",
                overrides={
                    "socket.timeout.ms": "5000",
                    "socket.connection.setup.timeout.ms": "4000",
                    "fetch.error.backoff.ms": "250",
                    "receive.message.max.bytes": "1000000",
                    "broker.address.family": "v4",
                    "socket.keepalive.enable": "true",
                    "socket.send.buffer.bytes": "65536",
                    "socket.receive.buffer.bytes": "65536",
                    # reference defaults (src/kafka.rs:24-44): no warnings
                    "auto.offset.reset": "earliest",
                    "enable.auto.commit": "false",
                    "enable.partition.eof": "false",
                    "enable.auto.offset.store": "false",
                    "queue.buffering.max.ms": "1000",
                    "group.id": "topic-analyzer--x",
                    # genuinely unknown: one warning
                    "definitely.not.a.property": "1",
                },
            )
        assert src.timeout_s == 5.0
        assert src.error_backoff_ms == 250
        assert src.max_bytes == 1_000_000
        assert src._sock_opts.connect_timeout_s == 4.0
        assert src._sock_opts.keepalive and src._sock_opts.rcvbuf == 65536
        assert sum(len(b) for b in src.batches(64)) == 30  # v4 pin works
        src.close()
    warned = [r.message for r in caplog.records if r.levelno >= logging.WARNING]
    assert any("definitely.not.a.property" in m for m in warned)
    assert not any("queue.buffering" in m or "group.id" in m for m in warned)


def test_invalid_address_family_rejected():
    with pytest.raises(ValueError, match="broker.address.family"):
        KafkaWireSource(
            "127.0.0.1:1", "x", overrides={"broker.address.family": "ipv9"}
        )


# ---------------------------------------------------------------------------
# end-to-end against the fake broker


def _mk_records(partition, n, start=0, key_every=1, tombstone_every=7, ts0=1_600_000_000_000):
    out = []
    for i in range(n):
        off = start + i
        key = f"p{partition}-key-{i % 50}".encode() if i % key_every == 0 else None
        value = None if (key is not None and i % tombstone_every == 3) else bytes(10 + i % 40)
        out.append((off, ts0 + i * 1000, key, value))
    return out


def _scan_via_wire(broker, topic="wire.topic", batch_size=333, overrides=None):
    src = KafkaWireSource(f"127.0.0.1:{broker.port}", topic, overrides=overrides)
    cfg = AnalyzerConfig(
        num_partitions=len(src.partitions()), batch_size=batch_size,
        count_alive_keys=True, alive_bitmap_bits=20,
    )
    be = CpuExactBackend(cfg, init_now_s=10**10)
    result = run_scan(topic, src, be, batch_size)
    src.close()
    return result


def _scan_direct(partition_records, partitions):
    cfg = AnalyzerConfig(
        num_partitions=len(partitions), batch_size=1024,
        count_alive_keys=True, alive_bitmap_bits=20,
    )
    be = CpuExactBackend(cfg, init_now_s=10**10)
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch

    for pidx, p in enumerate(sorted(partitions)):
        rows = [(pidx, ts, k, v) for (_, ts, k, v) in partition_records[p]]
        if rows:
            be.update(records_to_batch(rows))
    return be.finalize()


def test_wire_scan_matches_direct_scan():
    records = {0: _mk_records(0, 400), 1: _mk_records(1, 250), 2: []}
    with FakeBroker("wire.topic", records, max_records_per_fetch=97) as broker:
        result = _scan_via_wire(broker)
    direct = _scan_direct(records, [0, 1, 2])
    m = result.metrics
    assert np.array_equal(m.per_partition, direct.per_partition)
    assert m.alive_keys == direct.alive_keys
    assert m.overall_count == 650
    assert m.earliest_ts_s == direct.earliest_ts_s
    assert m.latest_ts_s == direct.latest_ts_s
    assert m.smallest_message == direct.smallest_message
    assert m.largest_message == direct.largest_message
    # Pagination actually happened (400 records / 97 per fetch).
    assert broker.fetch_count > 4


def test_wire_scan_gzip():
    records = {0: _mk_records(0, 120)}
    with FakeBroker("wire.topic", records, compression=kc.COMPRESSION_GZIP) as broker:
        result = _scan_via_wire(broker, overrides={"check.crcs": "true"})
    assert result.metrics.overall_count == 120


def test_wire_scan_compaction_gaps():
    # Only every third offset retained; start offset nonzero.
    rows = [r for r in _mk_records(0, 300, start=50) if r[0] % 3 == 0]
    with FakeBroker("wire.topic", {0: rows}) as broker:
        result = _scan_via_wire(broker)
    assert result.metrics.overall_count == len(rows)
    # Watermarks reflect the retained range, like fetch_watermarks.
    assert result.start_offsets == {0: 51}
    assert result.end_offsets == {0: 349}  # last retained offset 348 + 1


def test_wire_missing_timestamps_map_to_epoch():
    rows = [(0, -1, b"k", b"v"), (1, -1, b"k2", b"v2")]
    with FakeBroker("wire.topic", {0: rows}) as broker:
        result = _scan_via_wire(broker)
    assert result.metrics.earliest_ts_s == 0  # unwrap_or(0) semantics


def test_version_negotiation_modern_broker():
    """Default fake broker advertises Metadata up to v5 (Kafka 4.0 floor,
    KIP-896) — the whole default suite runs over negotiated v5.  This test
    pins the negotiation result explicitly."""
    with FakeBroker("wire.topic", {0: _mk_records(0, 20)}) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
        conn = src._any_conn()
        assert src._version(conn, kc.API_METADATA) == 5
        assert src._version(conn, kc.API_FETCH) == 4
        assert src.partitions() == [0]
        src.close()


def test_version_negotiation_legacy_and_ancient_brokers():
    records = {0: _mk_records(0, 30)}
    legacy_ranges = {
        kc.API_FETCH: (0, 4), kc.API_LIST_OFFSETS: (0, 1),
        kc.API_METADATA: (0, 1),
    }
    for kwargs in ({"api_ranges": legacy_ranges}, {"no_api_versions": True}):
        with FakeBroker("wire.topic", records, **kwargs) as broker:
            src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
            conn = src._any_conn()
            assert src._version(conn, kc.API_METADATA) == 1
            m = _scan_via_wire(broker)
            assert m.metrics.overall_count == 30


def test_version_negotiation_incompatible_broker():
    ranges = {
        # Too new: both our Fetch encodings (v12 flexible, v4 classic)
        # removed by a hypothetical future KIP-896-style floor raise.
        kc.API_FETCH: (13, 17),
        kc.API_LIST_OFFSETS: (0, 9),
        kc.API_METADATA: (0, 13),
    }
    with FakeBroker("wire.topic", {0: _mk_records(0, 5)}, api_ranges=ranges) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
        with pytest.raises(kc.KafkaProtocolError, match="Fetch versions"):
            src._version(src._any_conn(), kc.API_FETCH)
        src.close()


def test_metadata_v5_roundtrip():
    md = kc.MetadataResponse(
        {0: ("h", 1), 2: ("i", 3)}, 0,
        [kc.TopicMetadata(0, "t", [kc.PartitionMetadata(0, 7, 2)])],
    )
    for v in (1, 2, 3, 5):
        buf = kc.encode_metadata_response(md, version=v)
        got = kc.decode_metadata_response(kc.ByteReader(buf), version=v)
        assert got.brokers == md.brokers
        assert got.topics[0].partitions[0].partition == 7
        assert got.topics[0].partitions[0].leader == 2


def test_native_and_python_decode_paths_agree():
    """The C++ frame decoder and the Python per-record generator must yield
    byte-identical RecordBatch streams (fields, hashes, offsets) across
    nulls, tombstones, gaps, headers-free records and gzip compression."""
    rows = [r for r in _mk_records(0, 700, start=13) if r[0] % 4 != 1]
    for compression in (kc.COMPRESSION_NONE, kc.COMPRESSION_GZIP):
        with FakeBroker(
            "wire.topic", {0: rows}, compression=compression,
            max_records_per_fetch=123,
        ) as broker:
            batches = {}
            for native in (True, False):
                src = KafkaWireSource(
                    f"127.0.0.1:{broker.port}", "wire.topic",
                    use_native_hashing=native,
                )
                batches[native] = RecordBatch.concat(list(src.batches(97)))
                src.close()
        a, b = batches[True], batches[False]
        assert len(a) == len(b) == len(rows)
        for name, _ in RecordBatch.FIELDS:
            assert np.array_equal(getattr(a, name), getattr(b, name)), name
        assert np.array_equal(a.offsets, b.offsets)


def test_multi_broker_cluster_scan():
    """Partitions led by different nodes: the client must group fetches by
    leader and pull each partition from the right broker."""
    records = {p: _mk_records(p, 150 + 37 * p) for p in range(5)}
    with FakeCluster("wire.topic", records, n_nodes=3, max_records_per_fetch=60) as cluster:
        src = KafkaWireSource(cluster.bootstrap, "wire.topic")
        cfg = AnalyzerConfig(
            num_partitions=5, batch_size=128, count_alive_keys=True,
            alive_bitmap_bits=20,
        )
        be = CpuExactBackend(cfg, init_now_s=10**10)
        result = run_scan("wire.topic", src, be, 128)
        src.close()
        # Every node served fetch traffic (each leads at least one partition).
        assert all(node.fetch_count > 0 for node in cluster.nodes)
    m = result.metrics
    direct = _scan_direct(records, list(records))
    assert np.array_equal(m.per_partition, direct.per_partition)
    assert m.alive_keys == direct.alive_keys
    assert m.overall_count == sum(len(r) for r in records.values())


def test_multi_broker_bootstrap_via_single_node():
    """Bootstrapping from ONE node must still discover and use the others."""
    records = {p: _mk_records(p, 80) for p in range(4)}
    with FakeCluster("wire.topic", records, n_nodes=2) as cluster:
        one = f"127.0.0.1:{cluster.nodes[0].port}"
        src = KafkaWireSource(one, "wire.topic")
        cfg = AnalyzerConfig(num_partitions=4, batch_size=64)
        be = CpuExactBackend(cfg, init_now_s=10**10)
        m = run_scan("wire.topic", src, be, 64).metrics
        src.close()
        assert cluster.nodes[1].fetch_count > 0  # discovered via metadata
    assert m.overall_count == 4 * 80


def test_wire_all_records_beyond_watermark_terminates():
    # Snapshot end=15, but compaction removed 10..14 and retained records
    # continue at 20: the fetch at offset 10 returns a non-empty batch whose
    # offsets are all >= end.  The scan must skip to the watermark and
    # terminate with only the 10 in-window records.
    rows = _mk_records(0, 10) + [
        (20 + i, 1_600_000_100_000 + i, b"late", b"v") for i in range(10)
    ]
    with FakeBroker(
        "wire.topic", {0: rows}, end_offsets={0: 15}
    ) as broker:
        result = _scan_via_wire(broker)
    assert result.metrics.overall_count == 10


def test_wire_compacted_batch_before_truncated_batch_not_skipped():
    """Regression: a fetch response whose first batch retains only records
    BELOW the fetch position (its last_offset_delta covers compacted-away
    offsets) while the next batch is truncated by partition_max_bytes must
    advance to the covered batch end and refetch — not conclude the
    partition is exhausted and skip to the watermark."""
    batch_a = _mk_records(0, 10)                       # offsets 0..9
    batch_b = [(15 + i, 1_600_000_100_000 + i, b"late", bytes(20))
               for i in range(5)]                      # offsets 15..19
    with FakeBroker(
        "wire.topic", {0: batch_a + batch_b},
        max_records_per_fetch=10,  # chunk 1 = batch_a, chunk 2 = batch_b
        honor_partition_max_bytes=True,
        # Batch A's on-disk range covers compacted-away 10..14, so a fetch
        # at offset 10 serves batch A again.
        coverage_overrides={0: {0: 14}},
    ) as broker:
        a_len = len(broker._chunks[0][0][2])
        # First fetch returns A + a truncated sliver of B.
        result = _scan_via_wire(
            broker,
            overrides={"max.partition.fetch.bytes": str(a_len + 10)},
        )
    assert result.metrics.overall_count == 15  # 10 from A, 5 from B


def test_wire_last_retained_batch_before_fetch_position_terminates():
    """The dual of the refetch regression above: when the compacted batch
    preceding the fetch position is the LAST data in the partition, its
    covered end (base + last_offset_delta + 1) reaches the watermark, so
    the scan must terminate — not grow the fetch size forever."""
    batch_a = _mk_records(0, 10)  # offsets 0..9; watermark snapshot says 15
    with FakeBroker(
        "wire.topic", {0: batch_a}, end_offsets={0: 15},
        honor_partition_max_bytes=True,
        coverage_overrides={0: {0: 14}},  # batch covers 10..14 on disk
    ) as broker:
        result = _scan_via_wire(broker)
    assert result.metrics.overall_count == 10


def test_wire_response_budget_starvation_not_mistaken_for_end():
    """KIP-74: when the request-level fetch.max.bytes budget is spent on
    earlier partitions, later ones come back EMPTY despite having data.
    The client must rotate the fetch order and keep going — not conclude
    the starved partitions are compacted away."""
    records = {p: _mk_records(p, 50) for p in range(3)}
    with FakeBroker(
        "wire.topic", records, max_records_per_fetch=10,
        honor_partition_max_bytes=True, honor_max_bytes=True,
    ) as broker:
        one_chunk = len(broker._chunks[0][0][2])
        # Budget fits ~one chunk per response: every round starves two of
        # the three partitions.
        result = _scan_via_wire(
            broker, overrides={"fetch.max.bytes": str(one_chunk + 10)}
        )
    assert result.metrics.overall_count == 150


def test_wire_oversized_batch_grows_fetch_size():
    """A single batch larger than max.partition.fetch.bytes comes back
    truncated (no complete frame): the client must double the limit until
    the batch fits."""
    rows = [(i, 1_600_000_000_000 + i, b"k%d" % i, bytes(200))
            for i in range(20)]
    with FakeBroker(
        "wire.topic", {0: rows}, honor_partition_max_bytes=True,
    ) as broker:
        result = _scan_via_wire(
            broker, overrides={"max.partition.fetch.bytes": "64"}
        )
    assert result.metrics.overall_count == 20


def test_gzip_uses_real_gzip_framing():
    # Kafka's gzip codec is RFC-1952; the encoded payload must carry the
    # gzip magic so real brokers/clients interoperate.
    buf = kc.encode_record_batch([(0, 0, b"k", b"v")], kc.COMPRESSION_GZIP)
    # header: offset(8) + len(4) + epoch(4) + magic(1) + crc(4) + attrs..count(45 total to payload)
    assert b"\x1f\x8b" in buf  # gzip magic somewhere in the batch payload


def test_topic_not_found_exits():
    with FakeBroker("other.topic", {0: []}) as broker:
        with pytest.raises(SystemExit, match="Topic not found!"):
            KafkaWireSource(f"127.0.0.1:{broker.port}", "missing.topic")


def test_empty_topic_is_empty():
    with FakeBroker("wire.topic", {0: [], 1: []}) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
        assert src.is_empty()
        src.close()


# ---------------------------------------------------------------------------
# flexible (KIP-482) protocol versions: Metadata v12 / ListOffsets v7 /
# Fetch v12 / ApiVersions v3


def test_flexible_primitives_roundtrip():
    w = kc.ByteWriter()
    for v in (0, 1, 127, 128, 300, 1 << 31):
        w.uvarint(v)
    w.compact_string(None).compact_string("").compact_string("héllo")
    w.compact_bytes(None).compact_bytes(b"").compact_bytes(b"\x00\xff")
    w.compact_array_len(None).compact_array_len(0).compact_array_len(3)
    w.tags()
    r = kc.ByteReader(w.done())
    assert [r.uvarint() for _ in range(6)] == [0, 1, 127, 128, 300, 1 << 31]
    assert [r.compact_string() for _ in range(3)] == [None, "", "héllo"]
    assert [r.compact_bytes() for _ in range(3)] == [None, b"", b"\x00\xff"]
    assert [r.compact_array_len() for _ in range(3)] == [0, 0, 3]
    r.skip_tags()
    assert r.remaining() == 0


def test_skip_tags_skips_unknown_tagged_fields():
    # Forward compatibility: a response carrying tagged fields this client
    # does not know must decode as if they were absent.
    w = kc.ByteWriter()
    w.uvarint(2)  # two tagged fields
    w.uvarint(0).uvarint(3).raw(b"abc")
    w.uvarint(7).uvarint(1).raw(b"z")
    w.i32(42)
    r = kc.ByteReader(w.done())
    r.skip_tags()
    assert r.i32() == 42


@pytest.mark.parametrize("version", [9, 12])
def test_metadata_flexible_roundtrip(version):
    topics = [
        kc.TopicMetadata(
            0, "t", [kc.PartitionMetadata(0, 0, 1), kc.PartitionMetadata(0, 1, 2)]
        )
    ]
    resp = kc.MetadataResponse({1: ("h1", 9092), 2: ("h2", 9093)}, 1, topics)
    out = kc.decode_metadata_response(
        kc.ByteReader(kc.encode_metadata_response(resp, version)), version
    )
    assert out.brokers == resp.brokers
    assert out.controller_id == resp.controller_id
    assert [(t.error, t.name) for t in out.topics] == [(0, "t")]
    assert [(p.partition, p.leader) for p in out.topics[0].partitions] == [
        (0, 1), (1, 2),
    ]
    req = kc.encode_metadata_request(["a", "b"], version)
    assert kc.decode_metadata_request(kc.ByteReader(req), version) == ["a", "b"]


def test_list_offsets_v7_roundtrip():
    req = kc.encode_list_offsets_request("t", [(0, -2), (3, -1)], 7)
    topic, parts = kc.decode_list_offsets_request(kc.ByteReader(req), 7)
    assert (topic, parts) == ("t", [(0, -2), (3, -1)])
    resp = kc.encode_list_offsets_response(
        "t", [(0, 0, -1, 17), (3, 0, -1, 99)], 7
    )
    out = kc.decode_list_offsets_response(kc.ByteReader(resp), 7)
    assert out == {0: (0, 17, -1), 3: (0, 99, -1)}
    resp = kc.encode_list_offsets_response(
        "t", [(0, 0, -1, 17, 4), (3, 0, -1, 99, 7)], 7
    )
    out = kc.decode_list_offsets_response(kc.ByteReader(resp), 7)
    assert out == {0: (0, 17, 4), 3: (0, 99, 7)}


def test_fetch_v12_roundtrip():
    req = kc.encode_fetch_request("t", [(0, 5), (2, 11)], 100, 1, 1 << 20,
                                  1 << 16, 12)
    topic, parts, mw, mb, xb = kc.decode_fetch_request(kc.ByteReader(req), 12)
    assert (topic, mw, mb, xb) == ("t", 100, 1, 1 << 20)
    assert parts == [(0, 5, 1 << 16, -1), (2, 11, 1 << 16, -1)]
    req = kc.encode_fetch_request("t", [(0, 5, 3)], 100, 1, 1 << 20,
                                  1 << 16, 12)
    _t, parts, _mw, _mb, _xb = kc.decode_fetch_request(kc.ByteReader(req), 12)
    assert parts == [(0, 5, 1 << 16, 3)]
    records = kc.encode_record_batch([(5, 1000, b"k", b"v")])
    resp = kc.encode_fetch_response("t", [(0, 0, 6, records)], 12)
    fps = kc.decode_fetch_response(kc.ByteReader(resp), 12)
    assert len(fps) == 1
    assert (fps[0].partition, fps[0].error, fps[0].high_watermark) == (0, 0, 6)
    assert bytes(fps[0].records) == records


def test_api_versions_v3_roundtrip():
    apis = [(1, 4, 12), (3, 1, 12), (18, 0, 3)]
    out = kc.decode_api_versions_response(
        kc.ByteReader(kc.encode_api_versions_response(apis, 3)), 3
    )
    assert out == {1: (4, 12), 3: (1, 12), 18: (0, 3)}
    # The v3 request body is compact strings + tags; decodable as written.
    r = kc.ByteReader(kc.encode_api_versions_request(3))
    assert r.compact_string() == "kafka-topic-analyzer-tpu"
    assert r.compact_string() == "2"
    r.skip_tags()
    assert r.remaining() == 0


def test_version_negotiation_flexible_broker():
    """A broker advertising current ranges drives the client onto the
    flexible versions (and the whole request/response cycle survives the
    tagged headers)."""
    with FakeBroker("wire.topic", {0: _mk_records(0, 20)}, modern=True) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
        conn = src._any_conn()
        assert src._version(conn, kc.API_METADATA) == 12
        assert src._version(conn, kc.API_LIST_OFFSETS) == 7
        assert src._version(conn, kc.API_FETCH) == 12
        assert src.partitions() == [0]
        src.close()


def test_wire_scan_flexible_broker_matches_direct():
    records = {0: _mk_records(0, 400), 1: _mk_records(1, 250), 2: []}
    with FakeBroker(
        "wire.topic", records, max_records_per_fetch=97, modern=True
    ) as broker:
        result = _scan_via_wire(broker)
    direct = _scan_direct(records, [0, 1, 2])
    m = result.metrics
    assert np.array_equal(m.per_partition, direct.per_partition)
    assert m.alive_keys == direct.alive_keys
    assert m.overall_count == 650


def test_wire_scan_flexible_broker_compressed_and_paginated():
    rows = [r for r in _mk_records(0, 300, start=50) if r[0] % 3 == 0]
    with FakeBroker(
        "wire.topic", {0: rows}, compression=kc.COMPRESSION_LZ4, modern=True
    ) as broker:
        result = _scan_via_wire(broker, overrides={"check.crcs": "true"})
    assert result.metrics.overall_count == len(rows)
    assert result.end_offsets == {0: 349}


def test_api_versions_downgrade_dance():
    """The client offers ApiVersions v3 first (KIP-511); a classic broker
    rejects it with error 35 in v0 format and the client retries at v0 —
    same negotiation result, no eviction, same connection."""
    with FakeBroker("wire.topic", {0: _mk_records(0, 20)}) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "wire.topic")
        conn = src._any_conn()
        assert src._version(conn, kc.API_METADATA) == 5  # classic fallback
        assert conn.api_versions  # handshake completed despite the 35
        src.close()
