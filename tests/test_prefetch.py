"""Prefetch iterator: ordering, error propagation, disable switch."""

import pytest

from kafka_topic_analyzer_tpu.utils.prefetch import PrefetchIterator, prefetch


def test_order_preserved():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))


def test_exception_propagates_in_position():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_depth_zero_is_passthrough():
    src = iter([1, 2])
    assert prefetch(src, depth=0) is src


def test_tuple_items_not_mistaken_for_errors():
    items = [("__error__", ValueError("x")), ("a", "b")]
    assert list(PrefetchIterator(iter(items), depth=1)) == items


def test_no_thread_leak_after_scans():
    """Engine scans — completed AND crashed — must not leak prefetch worker
    threads (the close-on-exit contract)."""
    import threading
    import time

    import pytest

    from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec

    spec = SyntheticSpec(num_partitions=3, messages_per_partition=2000)
    cfg = AnalyzerConfig(num_partitions=3, batch_size=256)

    class Boom(Exception):
        pass

    class Crashy(SyntheticSource):
        def batches(self, *a, **k):
            yield from list(super().batches(*a, **k))[:2]
            raise Boom()

    before = threading.active_count()
    for _ in range(3):
        run_scan("t", SyntheticSource(spec), CpuExactBackend(cfg, init_now_s=0), 256)
        with pytest.raises(Boom):
            run_scan("t", Crashy(spec), CpuExactBackend(cfg, init_now_s=0), 256)
    # Workers terminate via the cancel event; give them a beat.
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_close_closes_underlying_generator():
    """close() must unwind the source generator's finally blocks
    (GeneratorExit) on early exit — sources hold real resources (broker
    connections), so draining the worker thread alone is not enough."""
    closed = []

    def gen():
        try:
            for i in range(1000):
                yield i
        finally:
            closed.append(True)

    it = prefetch(gen(), depth=2)
    assert next(it) == 0
    it.close()
    assert closed == [True]
    it.close()  # idempotent


def test_close_after_exhaustion_is_noop():
    closed = []

    def gen():
        try:
            yield 1
        finally:
            closed.append(True)

    it = prefetch(gen(), depth=2)
    assert list(it) == [1]
    it.close()
    assert closed == [True]  # closed once, by natural exhaustion
