"""Prefetch iterator: ordering, error propagation, disable switch."""

import pytest

from kafka_topic_analyzer_tpu.utils.prefetch import PrefetchIterator, prefetch


def test_order_preserved():
    assert list(prefetch(iter(range(100)), depth=3)) == list(range(100))


def test_exception_propagates_in_position():
    def gen():
        yield 1
        yield 2
        raise RuntimeError("boom")

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="boom"):
        next(it)


def test_depth_zero_is_passthrough():
    src = iter([1, 2])
    assert prefetch(src, depth=0) is src


def test_tuple_items_not_mistaken_for_errors():
    items = [("__error__", ValueError("x")), ("a", "b")]
    assert list(PrefetchIterator(iter(items), depth=1)) == items
