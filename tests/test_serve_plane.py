"""The production read path (ISSUE 20; DESIGN.md §26): conditional
caching, publish-time compression, SSE push, priced /history, and the
HTTP/1.1 header discipline that lets a fleet of dashboards poll the
service without touching the scan.

Coverage layers:

- conditional GET: strong ETags on all four snapshot routes, 304 with
  zero body bytes on a validator match, full 200 when the validator
  goes stale (and again 304 after refreshing it);
- publish-time encoding: the gzip variant decompresses to the exact
  identity body, both validators name the same seq, a publish-vs-read
  hammer proves the (raw, gzipped, etag) triple can never tear;
- priced /history: max_points answers from the coarsest satisfying RRD
  tier, stride decimation keeps the LAST row (cum-exact), tracks filter
  before serialization, bad params are clean 400s;
- SSE: subscribe/catch-up/receive over real HTTP, slow-client eviction
  (booked, never blocking) and re-sync, publisher shutdown closes
  streams;
- header discipline: exact Content-Length on every route x status, a
  body-less 304, JSON errors, keep-alive across mixed statuses on one
  connection;
- byte-identity: a follow scan with the WHOLE serving plane on and
  pollers hammering it folds identically to the bare referee.
"""

from __future__ import annotations

import gzip
import http.client
import json
import queue
import threading
import time
import urllib.error
import urllib.request

import pytest

from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig, FollowConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.obs import flight as obs_flight
from kafka_topic_analyzer_tpu.obs import health as obs_health
from kafka_topic_analyzer_tpu.obs import history as obs_history
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter
from kafka_topic_analyzer_tpu.obs.flight import FlightRecorder
from kafka_topic_analyzer_tpu.obs.health import AlertRule, HealthEngine
from kafka_topic_analyzer_tpu.obs.history import HistoryStore
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.serve import push as serve_push
from kafka_topic_analyzer_tpu.serve import state as serve_state
from kafka_topic_analyzer_tpu.serve.follow import FollowService
from kafka_topic_analyzer_tpu.serve.push import SsePublisher
from kafka_topic_analyzer_tpu.serve.state import ServiceState

from fake_broker import FakeBroker

pytestmark = pytest.mark.serveplane


@pytest.fixture(autouse=True)
def _reset():
    default_registry().reset()
    yield
    default_registry().reset()
    serve_state.set_active(None)
    serve_push.set_active(None)
    obs_flight.set_active(None)
    obs_history.set_active(None)
    obs_health.set_active(None)


def _fetch(port, path, headers=None):
    """(status, headers, body) — errors return their response too."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def wait_metric(predicate, timeout_s=5.0):
    """Handlers book metrics AFTER writing the response, so a client
    that just read the body can race the inc() — poll, don't assert."""
    deadline = time.monotonic() + timeout_s
    while not predicate():
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.01)
    return True


def metric_total(name, **labels):
    m = default_registry().snapshot().get(name)
    if not m:
        return 0.0
    return sum(
        s["value"] for s in m["samples"]
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


def _doc(seq_hint: int, pad: int = 600) -> dict:
    """A report-ish doc big enough to clear the gzip floor."""
    return {"topic": "t", "hint": seq_hint, "pad": "x" * pad}


# ---------------------------------------------------------------------------
# /report.json: conditional GET + publish-time gzip


def test_report_conditional_get_and_gzip_roundtrip():
    svc = ServiceState()
    serve_state.set_active(svc)
    svc.publish(_doc(1))
    exporter = PrometheusExporter(0)
    try:
        code, hdr, body = _fetch(exporter.port, "/report.json")
        assert code == 200
        assert hdr["ETag"] == '"r1"'
        assert hdr["Content-Type"] == "application/json"
        assert hdr["Cache-Control"] == "no-cache"
        assert int(hdr["Content-Length"]) == len(body)
        assert "Content-Encoding" not in hdr
        assert json.loads(body)["seq"] == 1

        # Conditional: zero body bytes, validator echoed.
        nm0 = metric_total("kta_serve_not_modified_total")
        code, hdr, body = _fetch(
            exporter.port, "/report.json",
            {"If-None-Match": '"r1"'},
        )
        assert (code, body) == (304, b"")
        assert hdr["Content-Length"] == "0"
        assert wait_metric(
            lambda: metric_total("kta_serve_not_modified_total") == nm0 + 1
        )

        # The gzip variant: its own validator, identical content.
        code, hdr, gz = _fetch(
            exporter.port, "/report.json",
            {"Accept-Encoding": "gzip"},
        )
        assert code == 200
        assert hdr["Content-Encoding"] == "gzip"
        assert hdr["ETag"] == '"r1+gzip"'
        assert hdr["Vary"] == "Accept-Encoding"
        assert int(hdr["Content-Length"]) == len(gz)
        assert gzip.decompress(gz) == body or json.loads(
            gzip.decompress(gz)
        )["seq"] == 1
        assert len(gz) < len(gzip.decompress(gz))
        assert wait_metric(
            lambda: metric_total("kta_serve_bytes_total", encoding="gzip") > 0
        )

        # Cross-variant 304: same seq = same content, either validator
        # satisfies a conditional for either encoding.
        code, _, body = _fetch(
            exporter.port, "/report.json",
            {"If-None-Match": '"r1"', "Accept-Encoding": "gzip"},
        )
        assert (code, body) == (304, b"")
        # q=0 explicitly refuses gzip.
        code, hdr, _ = _fetch(
            exporter.port, "/report.json",
            {"Accept-Encoding": "gzip;q=0"},
        )
        assert code == 200 and "Content-Encoding" not in hdr
    finally:
        exporter.close()


def test_report_304_across_seq_bumps():
    svc = ServiceState()
    serve_state.set_active(svc)
    svc.publish(_doc(1))
    exporter = PrometheusExporter(0)
    try:
        _, hdr, _ = _fetch(exporter.port, "/report.json")
        etag1 = hdr["ETag"]
        code, _, _ = _fetch(
            exporter.port, "/report.json", {"If-None-Match": etag1}
        )
        assert code == 304
        # A new publish stales the validator: the SAME conditional now
        # pays the full body, and its refreshed validator 304s again.
        svc.publish(_doc(2))
        code, hdr, body = _fetch(
            exporter.port, "/report.json", {"If-None-Match": etag1}
        )
        assert code == 200
        assert hdr["ETag"] == '"r2"'
        assert json.loads(body)["seq"] == 2
        code, _, _ = _fetch(
            exporter.port, "/report.json", {"If-None-Match": hdr["ETag"]}
        )
        assert code == 304
    finally:
        exporter.close()


def test_small_and_disabled_bodies_fall_back_to_identity():
    # Below the gzip floor: no gzip variant exists, gzip readers get
    # identity (visible in the encoding label, never an error).
    svc = ServiceState()
    serve_state.set_active(svc)
    svc.publish({"topic": "t"})
    exporter = PrometheusExporter(0)
    try:
        code, hdr, _ = _fetch(
            exporter.port, "/report.json", {"Accept-Encoding": "gzip"}
        )
        assert code == 200 and "Content-Encoding" not in hdr
        assert svc.entry().gzipped is None
    finally:
        exporter.close()
    # --no-serve-gzip: large bodies stay identity too.
    svc2 = ServiceState(gzip_enabled=False)
    svc2.publish(_doc(1))
    assert svc2.entry().gzipped is None


def test_torn_triple_hammer_under_concurrent_publishes():
    """Readers racing a publisher can never see a body from one publish
    with a validator (or gzip variant) from another."""
    svc = ServiceState()
    serve_state.set_active(svc)
    svc.publish(_doc(0))
    exporter = PrometheusExporter(0)
    stop = threading.Event()
    errors = []

    def publisher():
        i = 1
        while not stop.is_set():
            svc.publish(_doc(i, pad=600 + (i % 7) * 40))
            i += 1

    def reader(gzip_on: bool):
        hdr_in = {"Accept-Encoding": "gzip"} if gzip_on else {}
        try:
            while not stop.is_set():
                code, hdr, body = _fetch(
                    exporter.port, "/report.json", dict(hdr_in)
                )
                assert code == 200
                raw = (
                    gzip.decompress(body)
                    if hdr.get("Content-Encoding") == "gzip"
                    else body
                )
                doc = json.loads(raw)
                etag_seq = int(
                    hdr["ETag"].strip('"').replace("+gzip", "")[1:]
                )
                assert doc["seq"] == etag_seq, (doc["seq"], hdr["ETag"])
                assert int(hdr["Content-Length"]) == len(body)
        except BaseException as e:  # surfaced below
            errors.append(e)

    threads = [threading.Thread(target=publisher)] + [
        threading.Thread(target=reader, args=(g,)) for g in (False, True)
    ]
    try:
        for t in threads:
            t.start()
        time.sleep(1.2)
    finally:
        stop.set()
        for t in threads:
            t.join(10)
        exporter.close()
    assert not errors, errors[0]


# ---------------------------------------------------------------------------
# /healthz + /flight validators


def test_healthz_conditional_get_follows_evaluations():
    eng = HealthEngine(
        [AlertRule("r", "s", lambda ctx: ctx.extras.get("on"))]
    )
    obs_health.set_active(eng)
    eng.evaluate()
    exporter = PrometheusExporter(0)
    try:
        code, hdr, body = _fetch(exporter.port, "/healthz")
        assert code == 200
        etag = hdr["ETag"]
        assert etag.startswith('"e')
        assert json.loads(body)["healthy"] is True
        code, _, got = _fetch(
            exporter.port, "/healthz", {"If-None-Match": etag}
        )
        assert (code, got) == (304, b"")
        # Every evaluation moves the validator, changed verdict or not.
        eng.evaluate()
        code, hdr, _ = _fetch(
            exporter.port, "/healthz", {"If-None-Match": etag}
        )
        assert code == 200 and hdr["ETag"] != etag
    finally:
        exporter.close()


def test_flight_conditional_get_follows_samples():
    rec = FlightRecorder()
    obs_flight.set_active(rec)
    rec.sample_once()
    exporter = PrometheusExporter(0)
    try:
        code, hdr, body = _fetch(exporter.port, "/flight")
        assert code == 200
        etag = hdr["ETag"]
        assert etag.startswith('"f')
        json.loads(body)  # valid series doc
        code, _, got = _fetch(
            exporter.port, "/flight", {"If-None-Match": etag}
        )
        assert (code, got) == (304, b"")
        rec.sample_once()
        code, hdr, _ = _fetch(
            exporter.port, "/flight", {"If-None-Match": etag}
        )
        assert code == 200 and hdr["ETag"] != etag
    finally:
        exporter.close()


# ---------------------------------------------------------------------------
# /history: pricing + validators


def _seeded_store(tmp_path, n=32):
    store = HistoryStore(str(tmp_path / "hist"))
    store.register_kinds({"cnt": "cum", "g": "inst"})
    for i in range(n):
        store.append({"cnt": float(i), "g": float(i % 4)}, t=float(i))
    return store


def test_history_max_points_prices_from_tiers(tmp_path):
    store = _seeded_store(tmp_path)
    obs_history.set_active(store)
    exporter = PrometheusExporter(0)
    try:
        # Unpriced: every tier-0 row.
        _, _, body = _fetch(exporter.port, "/history")
        full = json.loads(body)
        assert len(full["t"]) == 32
        assert "max_points" not in full

        # Priced: the coarsest satisfying RRD tier answers.
        _, _, body = _fetch(exporter.port, "/history?max_points=8")
        priced = json.loads(body)
        assert priced["points"] == len(priced["t"]) <= 8
        assert priced["max_points"] == 8
        assert priced["decimated"] is False
        # Cum tracks keep the LAST value at the surviving points —
        # the window's final delta is exact.
        assert priced["tracks"]["cnt"][-1] == full["tracks"]["cnt"][-1]

        # Below every tier: stride decimation, still keep-last.
        _, _, body = _fetch(exporter.port, "/history?max_points=3")
        dec = json.loads(body)
        assert dec["points"] <= 3 and dec["decimated"] is True
        assert dec["tracks"]["cnt"][-1] == full["tracks"]["cnt"][-1]

        # Track filtering happens before serialization.
        _, _, body = _fetch(
            exporter.port, "/history?tracks=cnt&max_points=8"
        )
        only = json.loads(body)
        assert set(only["tracks"]) == {"cnt"}

        # Bad params are clean JSON 400s.
        for q in ("?max_points=0", "?max_points=zero", "?t0=notatime"):
            code, hdr, body = _fetch(exporter.port, f"/history{q}")
            assert code == 400
            assert hdr["Content-Type"] == "application/json"
            assert "error" in json.loads(body)
    finally:
        exporter.close()
        store.close()


def test_history_etag_covers_data_and_query(tmp_path):
    store = _seeded_store(tmp_path, n=8)
    obs_history.set_active(store)
    exporter = PrometheusExporter(0)
    try:
        _, hdr, _ = _fetch(exporter.port, "/history?max_points=4")
        etag = hdr["ETag"]
        code, _, body = _fetch(
            exporter.port, "/history?max_points=4",
            {"If-None-Match": etag},
        )
        assert (code, body) == (304, b"")
        # A different question never matches the old answer's validator.
        code, hdr2, _ = _fetch(
            exporter.port, "/history?max_points=2",
            {"If-None-Match": etag},
        )
        assert code == 200 and hdr2["ETag"] != etag
        # New data stales every query's validator.
        store.append({"cnt": 99.0, "g": 1.0}, t=100.0)
        code, hdr3, _ = _fetch(
            exporter.port, "/history?max_points=4",
            {"If-None-Match": etag},
        )
        assert code == 200 and hdr3["ETag"] != etag
    finally:
        exporter.close()
        store.close()


# ---------------------------------------------------------------------------
# /events: SSE push


def _read_sse_frame(resp, timeout_s=5.0):
    """Read one frame (lines up to a blank line), skipping comments."""
    deadline = time.monotonic() + timeout_s
    lines = []
    while time.monotonic() < deadline:
        line = resp.readline()
        if not line:
            return None
        line = line.rstrip(b"\r\n")
        if line.startswith(b":"):
            continue  # comment (stream-open / keepalive)
        if line == b"":
            if lines:
                return lines
            continue
        lines.append(line)
    raise AssertionError("no SSE frame within the timeout")


def test_sse_stream_over_http_pushes_publishes():
    svc = ServiceState()
    serve_state.set_active(svc)
    pub = SsePublisher().start()
    serve_push.set_active(pub)
    exporter = PrometheusExporter(0)
    conn = http.client.HTTPConnection("127.0.0.1", exporter.port, timeout=5)
    try:
        conn.request("GET", "/events")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "text/event-stream"
        assert resp.headers["Cache-Control"] == "no-store"
        assert resp.headers.get("Connection") == "close"
        # Let the subscribe land before publishing so the frame is live,
        # not catch-up.
        deadline = time.monotonic() + 5
        while metric_total("kta_serve_sse_subscribers") < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        svc.publish(_doc(1), summary={"records": 7})
        frame = _read_sse_frame(resp)
        assert frame is not None
        fields = dict(
            line.split(b": ", 1) for line in frame if b": " in line
        )
        assert fields[b"event"] == b"publish"
        assert int(fields[b"id"]) == 1
        data = json.loads(fields[b"data"])
        assert data["seq"] == 1 and data["records"] == 7
        assert wait_metric(
            lambda: metric_total("kta_serve_bytes_total", encoding="sse") > 0
        )
    finally:
        conn.close()
        pub.stop()
        exporter.close()
    assert wait_metric(
        lambda: metric_total("kta_serve_sse_subscribers") == 0
    )


def test_sse_catchup_eviction_and_resync():
    pub = SsePublisher(queue_len=2).start()
    serve_push.set_active(pub)
    svc = ServiceState()
    try:
        # Catch-up: a late subscriber gets the latest frame on connect.
        svc.publish(_doc(1), summary={"records": 1})
        deadline = time.monotonic() + 5
        while pub._last_frame is None:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        sub = pub.subscribe()
        frame = sub.next_frame(timeout=5)
        assert b"id: 1" in frame

        # Slow client: the queue bound evicts (booked), never blocks the
        # publisher; the close sentinel ends the stream.
        d0 = metric_total(
            "kta_serve_sse_dropped_total", reason="slow-client"
        )
        for i in range(2, 12):
            svc.publish(_doc(i), summary={"records": i})
        deadline = time.monotonic() + 5
        while metric_total(
            "kta_serve_sse_dropped_total", reason="slow-client"
        ) <= d0:
            assert time.monotonic() < deadline, "eviction never booked"
            time.sleep(0.01)
        got = []
        while True:
            try:
                f = sub.next_frame(timeout=0.5)
            except queue.Empty:
                pytest.fail("evicted stream not closed")
            if f is None:
                break
            got.append(f)
        assert len(got) <= 2  # bounded: never more than the queue held

        # Re-sync: a fresh subscribe catches up at the LATEST seq (wait
        # out the publisher thread draining its batch first).
        deadline = time.monotonic() + 5
        while b"id: 11" not in (pub._last_frame or b""):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        sub2 = pub.subscribe()
        frame = sub2.next_frame(timeout=5)
        assert b"id: 11" in frame
        pub.unsubscribe(sub2)
    finally:
        pub.stop()
        serve_push.set_active(None)
    assert metric_total("kta_serve_sse_subscribers") == 0
    assert metric_total(
        "kta_serve_sse_dropped_total", reason="shutdown"
    ) >= 0


def test_events_404_without_publisher():
    exporter = PrometheusExporter(0)
    try:
        code, hdr, body = _fetch(exporter.port, "/events")
        assert code == 404
        assert "--sse" in json.loads(body)["error"]
        assert int(hdr["Content-Length"]) == len(body)
    finally:
        exporter.close()


# ---------------------------------------------------------------------------
# header discipline + keep-alive


def test_header_discipline_on_every_status():
    svc = ServiceState()
    exporter = PrometheusExporter(0)
    try:
        cases = [
            ("/report.json", 404),   # no service registered
            ("/healthz", 404),       # no engine
            ("/history", 404),       # no store
            ("/flight", 404),        # no recorder
            ("/nope", 404),          # unknown route
        ]
        for path, want in cases:
            code, hdr, body = _fetch(exporter.port, path)
            assert code == want, path
            assert hdr["Content-Type"] == "application/json", path
            assert int(hdr["Content-Length"]) == len(body), path
            json.loads(body)
        serve_state.set_active(svc)
        code, hdr, body = _fetch(exporter.port, "/report.json")
        assert code == 503  # registered but nothing published yet
        assert int(hdr["Content-Length"]) == len(body)
        code, hdr, body = _fetch(
            exporter.port, "/report.json?topic=ghost"
        )
        assert code == 404 and b"ghost" in body
    finally:
        exporter.close()


def test_keepalive_survives_mixed_statuses_on_one_connection():
    """HTTP/1.1 framing is exact enough that 200/304/404/503 can share
    one socket — a 1 Hz poller keeps a single connection."""
    svc = ServiceState()
    serve_state.set_active(svc)
    svc.publish(_doc(1))
    exporter = PrometheusExporter(0)
    conn = http.client.HTTPConnection("127.0.0.1", exporter.port, timeout=5)
    try:
        seq = [
            ("/report.json", {}, 200),
            ("/report.json", {"If-None-Match": '"r1"'}, 304),
            ("/healthz", {}, 404),
            ("/report.json?topic=ghost", {}, 404),
            ("/report.json", {"Accept-Encoding": "gzip"}, 200),
            ("/metrics", {}, 200),
        ]
        for path, hdrs, want in seq:
            conn.request("GET", path, headers=hdrs)
            resp = conn.getresponse()
            body = resp.read()
            assert resp.status == want, path
            if want == 304:
                assert body == b""
        # The socket was reused throughout: requests_total book matches.
        assert wait_metric(
            lambda: metric_total(
                "kta_serve_requests_total", route="/report.json"
            ) == 4.0
        )
    finally:
        conn.close()
        exporter.close()


# ---------------------------------------------------------------------------
# byte-identity: serving plane on + pollers hammering vs bare referee


N_PARTS = 2


def _mk_records(partition, n):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 17}".encode() if i % 5 else None,
            bytes(18 + (i % 11)) if i % 7 else None,
        )
        for i in range(n)
    ]


def _scan_cfg():
    return AnalyzerConfig(
        num_partitions=N_PARTS, batch_size=64,
        count_alive_keys=True, alive_bitmap_bits=16,
        enable_hll=True, hll_p=8,
    )


def _full_doc(result):
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def test_scan_identity_with_serving_plane_under_load(tmp_path):
    records = {p: _mk_records(p, 200) for p in range(N_PARTS)}

    with FakeBroker("serve.topic", records, max_records_per_fetch=48) as b:
        src = KafkaWireSource(
            f"127.0.0.1:{b.port}", "serve.topic",
            overrides={"retry.backoff.ms": "5"},
        )
        referee = _full_doc(run_scan(
            "serve.topic", src,
            TpuBackend(_scan_cfg(), init_now_s=10**10), 64,
        ))
        src.close()
    default_registry().reset()

    pub = SsePublisher().start()
    serve_push.set_active(pub)
    exporter = PrometheusExporter(0)
    stop = threading.Event()
    poll_errors = []

    def poller(gz):
        etag = None
        while not stop.is_set():
            try:
                hdrs = {"Accept-Encoding": "gzip"} if gz else {}
                if etag:
                    hdrs["If-None-Match"] = etag
                code, hdr, _ = _fetch(exporter.port, "/report.json", hdrs)
                if code == 200:
                    etag = hdr.get("ETag")
                elif code not in (304, 404, 503):
                    raise AssertionError(f"poller got {code}")
            except (OSError, urllib.error.URLError):
                pass  # teardown race
            except BaseException as e:
                poll_errors.append(e)
                return

    pollers = [
        threading.Thread(target=poller, args=(g,))
        for g in (False, True, False)
    ]
    try:
        for t in pollers:
            t.start()
        follow = FollowConfig(
            poll_interval_s=0.02, idle_backoff_max_s=0.05,
            idle_exit_s=0.6,
        )
        with FakeBroker("serve.topic", records,
                        max_records_per_fetch=48) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", "serve.topic",
                overrides={"retry.backoff.ms": "5"},
            )
            svc = FollowService(
                "serve.topic", src,
                TpuBackend(_scan_cfg(), init_now_s=10**10), 64, follow,
            )
            result = svc.run()
            src.close()
    finally:
        stop.set()
        for t in pollers:
            t.join(10)
        pub.stop()
        exporter.close()

    assert not poll_errors, poll_errors[0]
    assert _full_doc(result) == referee
    # The plane actually served while the scan ran.
    assert metric_total("kta_serve_requests_total") > 0
