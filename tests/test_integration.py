"""Grand integration: every layer at once.

A 2-node cluster serving a gzip-compressed, log-compacted topic over TCP →
wire client → prefetched sharded scan on a (2, 2) mesh with per-step
snapshots → crash → resume with a fresh backend → report must equal an
uninterrupted CPU-oracle scan of the same topic.
"""

import numpy as np
import pytest

import jax

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeCluster

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices"
)

TOPIC = "grand.topic"


def _records():
    out = {}
    for p in range(5):
        rows = []
        for off in range(0, 4000, 1 + p % 3):  # varying compaction gaps
            key = f"p{p}-k{off % 211}".encode() if off % 9 else None
            value = None if (key is not None and off % 17 == 5) else bytes(
                20 + (off * 7 + p) % 300
            )
            rows.append((off, 1_600_000_000_000 + off * 250, key, value))
        out[p] = rows
    return out


class _Interrupt(Exception):
    pass


def test_full_stack_interrupt_resume(tmp_path):
    records = _records()
    cfg = AnalyzerConfig(
        num_partitions=5,
        batch_size=512,
        count_alive_keys=True,
        alive_bitmap_bits=20,
        enable_hll=True,
        hll_p=12,
        enable_quantiles=True,
        quantiles_per_partition=True,
        mesh_shape=(2, 2),
    )
    with FakeCluster(
        TOPIC, records, n_nodes=2, compression=kc.COMPRESSION_GZIP,
        max_records_per_fetch=700,
    ) as cluster:
        # Referee: uninterrupted CPU-oracle scan.
        oracle_cfg = AnalyzerConfig(
            num_partitions=5, batch_size=512, count_alive_keys=True,
            alive_bitmap_bits=20, enable_hll=True, hll_p=12,
            enable_quantiles=True, quantiles_per_partition=True,
        )
        src0 = KafkaWireSource(cluster.bootstrap, TOPIC)
        referee = run_scan(
            TOPIC, src0, CpuExactBackend(oracle_cfg, init_now_s=10**10), 512
        ).metrics
        src0.close()

        # Interrupted sharded scan with per-step snapshots.
        from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

        src1 = KafkaWireSource(cluster.bootstrap, TOPIC)

        class Limited:
            def __init__(self, inner, limit):
                self.inner, self.limit, self.seen = inner, limit, 0

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def batches(self, batch_size, partitions=None, start_at=None):
                for b in self.inner.batches(batch_size, partitions, start_at):
                    if start_at is None:
                        self.seen += 1
                        if self.seen > self.limit:
                            raise _Interrupt()
                    yield b

        be1 = ShardedTpuBackend(cfg, init_now_s=10**10)
        with pytest.raises(_Interrupt):
            run_scan(
                TOPIC, Limited(src1, 6), be1, 512,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )
        src1.close()

        # Resume with a fresh backend and fresh connections.
        src2 = KafkaWireSource(cluster.bootstrap, TOPIC)
        be2 = ShardedTpuBackend(cfg, init_now_s=0)
        result = run_scan(
            TOPIC, src2, be2, 512,
            snapshot_dir=str(tmp_path), resume=True,
        )
        src2.close()

    m = result.metrics
    assert np.array_equal(m.per_partition, referee.per_partition)
    assert np.array_equal(m.per_partition_extremes, referee.per_partition_extremes)
    assert m.overall_count == referee.overall_count
    assert m.overall_size == referee.overall_size
    assert m.alive_keys == referee.alive_keys
    assert m.earliest_ts_s == referee.earliest_ts_s
    assert m.latest_ts_s == referee.latest_ts_s
    # Sketches within budget vs the oracle's exact referees.
    assert m.distinct_keys_hll == pytest.approx(
        referee.distinct_keys_exact, rel=0.1  # p=12 → ~1.6% σ; 10% ≈ 6σ
    )
    for exact, sketch in zip(
        referee.quantiles_per_partition, m.quantiles_per_partition
    ):
        for qe, qs in zip(exact.values, sketch.values):
            assert qs == pytest.approx(qe, rel=0.011)
    # Watermarks reflect the gappy retained ranges.
    assert result.end_offsets == {
        p: rows[-1][0] + 1 for p, rows in _records().items()
    }


def test_non_dense_partitions_staged_scan_snapshots_true_ids(tmp_path):
    """Engine staging (pack on the prefetch worker) must not disturb the
    true-partition-id bookkeeping: remap_batch mutates in place, so the
    worker packs a dense COPY.  A topic with ids {3,4,5} is scanned with
    snapshots on; the snapshot must key next_offsets by TRUE ids and a
    resume must not double-count (the exact regression a staged in-place
    remap would cause)."""
    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
    from kafka_topic_analyzer_tpu.checkpoint import load_snapshot
    from kafka_topic_analyzer_tpu.io.kafka_wire import records_to_batch

    records = {
        p: [
            (off, 1_600_000_000_000 + off * 500,
             f"p{p}-k{off % 37}".encode() if off % 7 else None,
             None if off % 13 == 5 else bytes(10 + (off * 3 + p) % 60))
            for off in range(600)
        ]
        for p in (3, 4, 5)
    }
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=256, count_alive_keys=True,
        alive_bitmap_bits=16,
    )
    with FakeBroker("gap.topic", records) as b:
        src = KafkaWireSource(f"127.0.0.1:{b.port}", "gap.topic")
        try:
            result = run_scan(
                "gap.topic", src, TpuBackend(cfg, init_now_s=0), 256,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )
        finally:
            src.close()
        snap = load_snapshot(str(tmp_path), "gap.topic", cfg)
        assert snap is not None
        _, next_offsets, records_seen, _ = snap
        # Keys are TRUE partition ids at their end offsets, not dense rows.
        assert next_offsets == {3: 600, 4: 600, 5: 600}
        assert records_seen == 1800

        # Resume from the completed snapshot: nothing left to scan, and
        # metrics must come back identical (no double counting).
        src2 = KafkaWireSource(f"127.0.0.1:{b.port}", "gap.topic")
        try:
            resumed = run_scan(
                "gap.topic", src2, TpuBackend(cfg, init_now_s=0), 256,
                snapshot_dir=str(tmp_path), resume=True,
            )
        finally:
            src2.close()

    m = result.metrics
    assert m.partitions == [3, 4, 5]
    assert m.overall_count == 1800
    oracle = CpuExactBackend(cfg, init_now_s=0)
    rows = [
        (p, ts, k, v)
        for p in (3, 4, 5)
        for (_off, ts, k, v) in records[p]
    ]
    # Oracle needs dense rows; feed with remapped partition ids.
    for lo in range(0, len(rows), 256):
        chunk = rows[lo:lo + 256]
        oracle.update(records_to_batch([(p - 3, ts, k, v) for p, ts, k, v in chunk]))
    want = oracle.finalize()
    assert np.array_equal(m.per_partition, want.per_partition)
    assert m.overall_size == want.overall_size
    assert m.alive_keys == want.alive_keys
    rm = resumed.metrics
    assert rm.overall_count == m.overall_count
    assert np.array_equal(rm.per_partition, m.per_partition)
    assert rm.alive_keys == m.alive_keys
