"""Grand integration: every layer at once.

A 2-node cluster serving a gzip-compressed, log-compacted topic over TCP →
wire client → prefetched sharded scan on a (2, 2) mesh with per-step
snapshots → crash → resume with a fresh backend → report must equal an
uninterrupted CPU-oracle scan of the same topic.
"""

import numpy as np
import pytest

import jax

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

from fake_broker import FakeCluster

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs 4 virtual devices"
)

TOPIC = "grand.topic"


def _records():
    out = {}
    for p in range(5):
        rows = []
        for off in range(0, 4000, 1 + p % 3):  # varying compaction gaps
            key = f"p{p}-k{off % 211}".encode() if off % 9 else None
            value = None if (key is not None and off % 17 == 5) else bytes(
                20 + (off * 7 + p) % 300
            )
            rows.append((off, 1_600_000_000_000 + off * 250, key, value))
        out[p] = rows
    return out


class _Interrupt(Exception):
    pass


def test_full_stack_interrupt_resume(tmp_path):
    records = _records()
    cfg = AnalyzerConfig(
        num_partitions=5,
        batch_size=512,
        count_alive_keys=True,
        alive_bitmap_bits=20,
        enable_hll=True,
        hll_p=12,
        enable_quantiles=True,
        quantiles_per_partition=True,
        mesh_shape=(2, 2),
    )
    with FakeCluster(
        TOPIC, records, n_nodes=2, compression=kc.COMPRESSION_GZIP,
        max_records_per_fetch=700,
    ) as cluster:
        # Referee: uninterrupted CPU-oracle scan.
        oracle_cfg = AnalyzerConfig(
            num_partitions=5, batch_size=512, count_alive_keys=True,
            alive_bitmap_bits=20, enable_hll=True, hll_p=12,
            enable_quantiles=True, quantiles_per_partition=True,
        )
        src0 = KafkaWireSource(cluster.bootstrap, TOPIC)
        referee = run_scan(
            TOPIC, src0, CpuExactBackend(oracle_cfg, init_now_s=10**10), 512
        ).metrics
        src0.close()

        # Interrupted sharded scan with per-step snapshots.
        from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

        src1 = KafkaWireSource(cluster.bootstrap, TOPIC)

        class Limited:
            def __init__(self, inner, limit):
                self.inner, self.limit, self.seen = inner, limit, 0

            def __getattr__(self, name):
                return getattr(self.inner, name)

            def batches(self, batch_size, partitions=None, start_at=None):
                for b in self.inner.batches(batch_size, partitions, start_at):
                    if start_at is None:
                        self.seen += 1
                        if self.seen > self.limit:
                            raise _Interrupt()
                    yield b

        be1 = ShardedTpuBackend(cfg, init_now_s=10**10)
        with pytest.raises(_Interrupt):
            run_scan(
                TOPIC, Limited(src1, 6), be1, 512,
                snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )
        src1.close()

        # Resume with a fresh backend and fresh connections.
        src2 = KafkaWireSource(cluster.bootstrap, TOPIC)
        be2 = ShardedTpuBackend(cfg, init_now_s=0)
        result = run_scan(
            TOPIC, src2, be2, 512,
            snapshot_dir=str(tmp_path), resume=True,
        )
        src2.close()

    m = result.metrics
    assert np.array_equal(m.per_partition, referee.per_partition)
    assert np.array_equal(m.per_partition_extremes, referee.per_partition_extremes)
    assert m.overall_count == referee.overall_count
    assert m.overall_size == referee.overall_size
    assert m.alive_keys == referee.alive_keys
    assert m.earliest_ts_s == referee.earliest_ts_s
    assert m.latest_ts_s == referee.latest_ts_s
    # Sketches within budget vs the oracle's exact referees.
    assert m.distinct_keys_hll == pytest.approx(
        referee.distinct_keys_exact, rel=0.1  # p=12 → ~1.6% σ; 10% ≈ 6σ
    )
    for exact, sketch in zip(
        referee.quantiles_per_partition, m.quantiles_per_partition
    ):
        for qe, qs in zip(exact.values, sketch.values):
            assert qs == pytest.approx(qe, rel=0.011)
    # Watermarks reflect the gappy retained ranges.
    assert result.end_offsets == {
        p: rows[-1][0] + 1 for p, rows in _records().items()
    }
