"""In-process fake Kafka broker speaking the same wire protocol as the
client (Metadata v1–v5 / ListOffsets v1 / Fetch v4 / ApiVersions v0 with
configurable advertised ranges), serving configurable per-partition records
— the cluster-free integration seam (SURVEY.md §4)."""

from __future__ import annotations

import bisect
import socket
import time
import struct
import threading
from typing import Dict, List, Optional, Tuple

from kafka_topic_analyzer_tpu.io import kafka_codec as kc

#: (offset, ts_ms, key, value)
Record = Tuple[int, int, Optional[bytes], Optional[bytes]]


class FaultInjector:
    """Transport-fault plan for a FakeBroker (or shared by a FakeCluster).

    Every fault is armed with a bounded ``times`` count and consumed
    atomically, so the broker misbehaves a deterministic number of times
    and then heals — the client's recovery path must then complete the
    scan with metrics identical to a fault-free run (tests/test_chaos.py).

    Faults:
    - ``drop_connection(after_bytes, times)``: the next ``times`` responses
      send only their first ``after_bytes`` bytes and then hard-close the
      connection (``after_bytes`` < 4 cuts mid-response-header);
    - ``refuse_connections(times)``: the next ``times`` accepted
      connections are closed before any bytes are served (a dead or
      restarting broker's connection-refused window);
    - ``stall_responses(seconds, times)``: the next ``times`` responses are
      delayed by ``seconds`` (past the client's socket timeout this reads
      as a hang);
    - ``inject_fetch_errors(code, times)``: the next ``times`` fetched
      partitions answer with the given transient Kafka error code instead
      of records.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._drop: "list[int]" = []       # remaining drops, bytes each
        self._refuse = 0
        self._stall: "list[float]" = []    # remaining stalls, seconds each
        self._fetch_errors: "list[int]" = []

    # -- arming --------------------------------------------------------------

    def drop_connection(self, after_bytes: int, times: int = 1) -> "FaultInjector":
        with self._lock:
            self._drop.extend([after_bytes] * times)
        return self

    def refuse_connections(self, times: int = 1) -> "FaultInjector":
        with self._lock:
            self._refuse += times
        return self

    def stall_responses(self, seconds: float, times: int = 1) -> "FaultInjector":
        with self._lock:
            self._stall.extend([seconds] * times)
        return self

    def inject_fetch_errors(self, code: int, times: int = 1) -> "FaultInjector":
        with self._lock:
            self._fetch_errors.extend([code] * times)
        return self

    # -- consumption (broker side) -------------------------------------------

    def take_refusal(self) -> bool:
        with self._lock:
            if self._refuse > 0:
                self._refuse -= 1
                return True
            return False

    def take_drop(self) -> Optional[int]:
        with self._lock:
            return self._drop.pop(0) if self._drop else None

    def take_stall(self) -> Optional[float]:
        with self._lock:
            return self._stall.pop(0) if self._stall else None

    def take_fetch_error(self) -> Optional[int]:
        with self._lock:
            return self._fetch_errors.pop(0) if self._fetch_errors else None

    def exhausted(self) -> bool:
        with self._lock:
            return not (
                self._drop or self._refuse or self._stall or self._fetch_errors
            )


class CorruptionInjector:
    """Deterministic poison plan for a FakeBroker's pre-encoded chunks.

    Unlike `FaultInjector` (transient transport faults that heal after a
    bounded fire count), corruption is applied ONCE, at chunk pre-encode
    time — modeling bit rot on the broker's disk: every fetch of a
    poisoned range returns byte-identical garbage, so the client's
    disambiguating re-fetch must conclude "deterministically corrupt" and
    apply its --on-corruption policy.

    Mutations target ``(partition, chunk_index)`` (chunks are
    ``max_records_per_fetch``-sized; for magic-2 topics each chunk is one
    RecordBatch v2 frame):

    - ``flip_byte``: XOR one byte (default: the last payload byte — a CRC
      mismatch under check.crcs, silent value garbage without);
    - ``corrupt_length``: overwrite the frame's batch_length prefix (a
      negative value exercises the mid-buffer classification the codec's
      old "partial trailing batch" path silently swallowed);
    - ``garbage_compression``: set the codec bits to gzip, scramble the
      payload, and RE-COMPUTE the CRC — only decompression can fail, the
      checksum is valid (the bad-compression classification);
    - ``truncate``: drop trailing bytes of the chunk.
    """

    def __init__(self) -> None:
        self._plans: Dict[Tuple[int, int], list] = {}
        #: Every (partition, chunk_index) a mutation targets.
        self.poisoned: "set[Tuple[int, int]]" = set()

    @property
    def poisoned_frames(self) -> int:
        return len(self.poisoned)

    def _plan(self, partition: int, chunk: int, fn) -> "CorruptionInjector":
        self._plans.setdefault((partition, chunk), []).append(fn)
        self.poisoned.add((partition, chunk))
        return self

    def flip_byte(
        self, partition: int, chunk: int = 0, offset: int = -1, xor: int = 0xFF
    ) -> "CorruptionInjector":
        def fn(b: bytearray) -> bytearray:
            b[offset] ^= xor
            return b

        return self._plan(partition, chunk, fn)

    def corrupt_length(
        self, partition: int, chunk: int = 0, value: int = -5
    ) -> "CorruptionInjector":
        def fn(b: bytearray) -> bytearray:
            struct.pack_into(">i", b, 8, value)
            return b

        return self._plan(partition, chunk, fn)

    def truncate(
        self, partition: int, chunk: int = 0, drop: int = 10
    ) -> "CorruptionInjector":
        def fn(b: bytearray) -> bytearray:
            return b[: max(len(b) - drop, 0)]

        return self._plan(partition, chunk, fn)

    def garbage_compression(
        self, partition: int, chunk: int = 0
    ) -> "CorruptionInjector":
        def fn(b: bytearray) -> bytearray:
            # v2 frame layout: attributes i16 at byte 21, payload from 61.
            attrs = struct.unpack_from(">h", b, 21)[0]
            struct.pack_into(">h", b, 21, (attrs & ~0x07) | kc.COMPRESSION_GZIP)
            for i in range(61, len(b)):
                b[i] = (b[i] * 31 + 7) & 0xFF  # deterministic garbage
            struct.pack_into(">I", b, 17, kc._crc32c(bytes(b[21:])))
            return b

        return self._plan(partition, chunk, fn)

    def apply(self, partition: int, chunk_index: int, data: bytes) -> bytes:
        fns = self._plans.get((partition, chunk_index))
        if not fns:
            return data
        b = bytearray(data)
        for fn in fns:
            b = bytearray(fn(b))
        return bytes(b)


class FakeBroker:
    def __init__(
        self,
        topic: str,
        partition_records: Dict[int, List[Record]],
        compression: int = kc.COMPRESSION_NONE,
        max_records_per_fetch: int = 500,
        start_offsets: Optional[Dict[int, int]] = None,
        end_offsets: Optional[Dict[int, int]] = None,
        tls_context=None,
        node_id: int = 0,
        cluster: "Optional[FakeCluster]" = None,
        api_ranges: "Optional[Dict[int, Tuple[int, int]]]" = None,
        no_api_versions: bool = False,
        modern: bool = False,
        sasl_plain: "Optional[Tuple[str, str]]" = None,
        sasl_scram: "Optional[Tuple[str, str, str]]" = None,
        honor_partition_max_bytes: bool = False,
        honor_max_bytes: bool = False,
        coverage_overrides: "Optional[Dict[int, Dict[int, int]]]" = None,
        message_magic: int = 2,
        control_offsets: "Optional[Dict[int, set]]" = None,
        response_delay=None,
        faults: "Optional[FaultInjector]" = None,
        corruption: "Optional[CorruptionInjector]" = None,
        extra_topics: "Optional[Dict[str, Dict[int, List[Record]]]]" = None,
        internal_topics: "Optional[Dict[str, Dict[int, List[Record]]]]" = None,
    ):
        #: Transport-fault plan (connection drops/refusals, stalls,
        #: transient fetch errors); None = behave.  Mutable attribute, so
        #: tests can arm faults mid-scan or give FakeCluster nodes
        #: distinct injectors after construction.
        self.faults = faults
        #: Poison plan applied to the pre-encoded chunks at startup (bit
        #: rot on disk: deterministic, identical on every fetch).
        self.corruption = corruption
        #: Optional callable (api_key, node_id) -> seconds, slept before
        #: each response send: induces cross-leader timing skew so the
        #: client's concurrent fetch threads interleave differently every
        #: run (tests/test_race_stress.py).
        self.response_delay = response_delay
        #: partition → offsets rendered as transaction control batches
        #: (commit markers) instead of data records.
        self.control_offsets = control_offsets or {}
        #: 2 = RecordBatch v2 (default); 0/1 = legacy MessageSet entries,
        #: emulating pre-0.11 segments retained on upgraded clusters.
        self.message_magic = message_magic
        #: partition → {chunk_index: last_covered_offset}: emulates a
        #: compacted log where a batch's last_offset_delta extends past its
        #: last *retained* record (the log cleaner preserves batch offset
        #: ranges when it removes records).
        self.coverage_overrides = coverage_overrides or {}
        #: When True, fetch responses concatenate chunks from the fetch
        #: position onward and hard-truncate at the request's
        #: partition_max_bytes — emulating a real broker's byte-limited
        #: (possibly mid-batch-truncated) responses.
        self.honor_partition_max_bytes = honor_partition_max_bytes
        #: When True, the request-level max_bytes is enforced across
        #: partitions in REQUEST order (KIP-74): once the budget is spent,
        #: later partitions get empty record sets.
        self.honor_max_bytes = honor_max_bytes
        #: When set, every connection must SASL/PLAIN-authenticate with
        #: these credentials before any other API is served.
        self.sasl_plain = sasl_plain
        #: (mechanism, username, password) with mechanism SCRAM-SHA-256 or
        #: SCRAM-SHA-512: connections must complete the two-round SCRAM
        #: exchange before any other API is served.
        self.sasl_scram = sasl_scram
        self.tls_context = tls_context
        self.node_id = node_id
        self.cluster = cluster
        #: Advertised ApiVersions ranges; the default mirrors a classic
        #: broker (Metadata up to v5) so tests exercise the negotiated v5
        #: path; ``modern=True`` advertises the flexible (KIP-482) ranges
        #: a current broker offers, driving the client onto Metadata v12 /
        #: ListOffsets v7 / Fetch v12.
        if modern and api_ranges is None:
            api_ranges = {
                kc.API_FETCH: (4, 12),
                kc.API_LIST_OFFSETS: (1, 7),
                kc.API_METADATA: (1, 12),
                kc.API_VERSIONS: (0, 3),
                kc.API_OFFSET_FOR_LEADER_EPOCH: (0, 4),
            }
        self.api_ranges = api_ranges or {
            kc.API_FETCH: (0, 4),
            kc.API_LIST_OFFSETS: (0, 1),
            kc.API_METADATA: (0, 5),
            kc.API_OFFSET_FOR_LEADER_EPOCH: (0, 3),
        }
        #: Pretend to be an ancient broker with no ApiVersions support.
        self.no_api_versions = no_api_versions
        self.topic = topic
        #: topic name -> per-topic log store ({"records", "start_offsets",
        #: "end_offsets", "chunks", "chunk_last"}).  The broker serves
        #: every topic here: Metadata(all-topics) lists them (internal
        #: flags included), ListOffsets/Fetch route by the request's topic
        #: name.  The corruption/control/coverage injectors stay keyed on
        #: the DEFAULT topic's partitions (the single-topic seam every
        #: existing test drives); extra topics serve clean v2 frames.
        self._stores: "Dict[str, dict]" = {}
        #: Topic names flagged is_internal in metadata (plus anything
        #: passed via ``internal_topics``) — the __consumer_offsets shape
        #: fleet discovery must exclude by default.
        self.internal_names: "set[str]" = set()
        self.compression = compression
        self.max_records_per_fetch = max_records_per_fetch
        self._stores[topic] = self._build_store(
            topic, partition_records,
            start_offsets=start_offsets, end_offsets=end_offsets,
        )
        for name, recs in (extra_topics or {}).items():
            self._stores[name] = self._build_store(name, recs)
        for name, recs in (internal_topics or {}).items():
            self._stores[name] = self._build_store(name, recs)
            self.internal_names.add(name)
        # Single-topic attribute surface (aliases of the default topic's
        # store) — the seam every pre-fleet test drives.
        store = self._stores[topic]
        self.records = store["records"]
        self.start_offsets = store["start_offsets"]
        self.end_offsets = store["end_offsets"]
        self._chunks = store["chunks"]
        self._chunk_last_offsets = store["chunk_last"]
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self.fetch_count = 0
        #: Open per-client sockets, so kill()/stop() can sever live
        #: connections (a stopped listener alone lets in-flight scans
        #: finish — not what "broker died" means).
        self._conn_lock = threading.Lock()
        self._open_conns: "set[socket.socket]" = set()

    # -- per-topic log stores --------------------------------------------------

    def _build_store(
        self,
        name: str,
        partition_records: Dict[int, List[Record]],
        start_offsets: Optional[Dict[int, int]] = None,
        end_offsets: Optional[Dict[int, int]] = None,
    ) -> dict:
        """Pre-encode one topic's records into fetch-sized record sets at
        startup: encoding per fetch in pure Python made the broker ~100x
        slower than the client it exists to test."""
        records = {
            p: sorted(rs, key=lambda r: r[0])
            for p, rs in partition_records.items()
        }
        start_offsets = start_offsets or {
            p: (rs[0][0] if rs else 0) for p, rs in records.items()
        }
        # High watermark: one past the last retained offset (overridable to
        # simulate a watermark snapshot older than the retained log).
        end_offsets = end_offsets or {
            p: (rs[-1][0] + 1 if rs else start_offsets[p])
            for p, rs in records.items()
        }
        # Injectors (corruption/control/coverage) target the default topic
        # only — they are keyed by bare partition, a pre-fleet contract.
        is_default = name == self.topic
        chunks_by_p: Dict[int, "list[tuple[int, int, bytes]]"] = {}
        chunk_last: Dict[int, "list[int]"] = {}
        control = self.control_offsets if is_default else {}
        coverage = self.coverage_overrides if is_default else {}
        for p, rs in records.items():
            chunks = []
            for ci, lo in enumerate(range(0, len(rs), self.max_records_per_fetch)):
                part = rs[lo : lo + self.max_records_per_fetch]
                last = coverage.get(p, {}).get(ci, part[-1][0])
                ctrl = control.get(p, set())
                if self.message_magic == 2 and any(r[0] in ctrl for r in part):
                    assert ci not in coverage.get(p, {}), (
                        "control_offsets and coverage_overrides cannot "
                        "target the same chunk (coverage would be dropped)"
                    )
                    # Transactional log shape: marker offsets become
                    # single-record control batches between data batches.
                    pieces, run = [], []

                    def flush_run():
                        if run:
                            pieces.append(
                                kc.encode_record_batch(
                                    list(run), self.compression,
                                    leader_epoch=0,
                                )
                            )
                            run.clear()

                    for rec in part:
                        if rec[0] in ctrl:
                            flush_run()
                            pieces.append(
                                kc.encode_control_batch(rec[0], rec[1])
                            )
                        else:
                            run.append(rec)
                    flush_run()
                    encoded = b"".join(pieces)
                elif self.message_magic == 2:
                    encoded = kc.encode_record_batch(
                        part, self.compression, last_offset=last,
                        leader_epoch=0,
                    )
                else:
                    encoded = kc.encode_message_set(
                        part, magic=self.message_magic,
                        compression=self.compression,
                    )
                if self.corruption is not None and is_default:
                    encoded = self.corruption.apply(p, ci, encoded)
                chunks.append((part[0][0], last, encoded))
            chunks_by_p[p] = chunks
            chunk_last[p] = [c[1] for c in chunks]
        return {
            "records": records,
            "start_offsets": start_offsets,
            "end_offsets": end_offsets,
            "chunks": chunks_by_p,
            "chunk_last": chunk_last,
            # KIP-320 leader-epoch state: the current epoch per partition
            # and the epoch history [(epoch, first_offset_of_epoch), ...]
            # OffsetForLeaderEpoch answers from.  Batches are stamped with
            # the epoch in effect when they were written (epoch 0 at
            # build; bumped by unclean_elect()).
            "epoch": {p: 0 for p in records},
            "epoch_starts": {
                p: [(0, start_offsets[p])] for p in records
            },
        }

    def create_topic(
        self,
        name: str,
        partition_records: Dict[int, List[Record]],
        internal: bool = False,
    ) -> None:
        """Add a topic WHILE the broker serves — the mid-test creation
        seam fleet discovery tests drive (a re-discovery poll must see the
        new topic).  The store is fully built before the dict insert, and
        the insert is atomic under the GIL, so a concurrent Metadata
        request sees either no topic or a complete one."""
        if name in self._stores:
            raise AssertionError(f"topic {name!r} already exists")
        store = self._build_store(name, partition_records)
        self._stores[name] = store
        if internal:
            self.internal_names.add(name)

    def topic_names(self) -> "list[str]":
        return sorted(self._stores)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FakeBroker":
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def produce(
        self,
        partition: int,
        records: "List[Record]",
        topic: "Optional[str]" = None,
    ) -> None:
        """Append records to a partition WHILE the broker serves — the
        follow-mode test seam (tests/test_follow.py).  Offsets must
        strictly extend the partition's retained log.  The records are
        pre-encoded into one new fetch chunk, the chunk is made fetchable
        first, and only then is the end watermark advanced (appends are
        atomic under the GIL) — so a client can never observe a watermark
        it cannot fetch up to.  ``topic`` defaults to the broker's default
        topic; fleet tests pass the name explicitly."""
        if not records:
            return
        name = topic if topic is not None else self.topic
        store = self._stores.get(name)
        if store is None:
            raise AssertionError(f"produce() targets unknown topic {name!r}")
        if partition not in store["records"]:
            raise AssertionError(
                "produce() targets an existing partition (metadata is "
                "fixed at construction)"
            )
        records = sorted(records, key=lambda r: r[0])
        rs = store["records"][partition]
        if rs and records[0][0] <= rs[-1][0]:
            raise AssertionError("produced offsets must extend the log")
        if self.message_magic == 2:
            encoded = kc.encode_record_batch(
                records, self.compression,
                leader_epoch=store["epoch"].get(partition, 0),
            )
        else:
            encoded = kc.encode_message_set(
                records, magic=self.message_magic,
                compression=self.compression,
            )
        if self.corruption is not None and name == self.topic:
            encoded = self.corruption.apply(
                partition, len(store["chunks"][partition]), encoded
            )
        rs.extend(records)
        store["chunks"][partition].append(
            (records[0][0], records[-1][0], encoded)
        )
        store["chunk_last"][partition].append(records[-1][0])
        store["end_offsets"][partition] = records[-1][0] + 1

    # -- log-mutation seams (retention / truncation / unclean election) -------

    def _mut_store(self, topic: "Optional[str]") -> dict:
        name = topic if topic is not None else self.topic
        store = self._stores.get(name)
        if store is None:
            raise AssertionError(f"mutation targets unknown topic {name!r}")
        return store

    def _epoch_at(self, store: dict, partition: int, offset: int) -> int:
        """Leader epoch in effect at ``offset`` (from the epoch history)."""
        epoch = 0
        for ep, start in store["epoch_starts"].get(partition, []):
            if start <= offset:
                epoch = ep
        return epoch

    def _rebuild_chunks(
        self, store: dict, partition: int, rs: "List[Record]"
    ) -> None:
        """Re-encode a partition's surviving records into fetch chunks,
        each stamped with the epoch in effect at its first offset.  The
        mutation seams re-segment the log, so corruption plans (keyed by
        chunk index) do not compose with them — chaos tests pick one."""
        chunks: "list[tuple[int, int, bytes]]" = []
        last: "list[int]" = []
        for lo in range(0, len(rs), self.max_records_per_fetch):
            part = rs[lo : lo + self.max_records_per_fetch]
            if self.message_magic == 2:
                encoded = kc.encode_record_batch(
                    part, self.compression,
                    leader_epoch=self._epoch_at(store, partition, part[0][0]),
                )
            else:
                encoded = kc.encode_message_set(
                    part, magic=self.message_magic,
                    compression=self.compression,
                )
            chunks.append((part[0][0], part[-1][0], encoded))
            last.append(part[-1][0])
        store["chunks"][partition] = chunks
        store["chunk_last"][partition] = last

    def expire_to(
        self, partition: int, offset: int, topic: "Optional[str]" = None
    ) -> None:
        """Retention fired WHILE the broker serves: every record below
        ``offset`` is deleted and the log start advances to ``offset``.
        Whole chunks that fell below the new start are dropped; a chunk
        straddling the boundary stays (a segment whose tail survives —
        clients filter fetched records below their position).  Fetches at
        a now-expired position answer OFFSET_OUT_OF_RANGE, exactly like a
        real broker whose retention ran mid-scan."""
        store = self._mut_store(topic)
        if partition not in store["records"]:
            raise AssertionError(f"expire_to() unknown partition {partition}")
        rs = [r for r in store["records"][partition] if r[0] >= offset]
        keep = [c for c in store["chunks"][partition] if c[1] >= offset]
        store["chunks"][partition] = keep
        store["chunk_last"][partition] = [c[1] for c in keep]
        store["records"][partition] = rs
        if offset > store["start_offsets"][partition]:
            store["start_offsets"][partition] = offset
        if offset > store["end_offsets"][partition]:
            store["end_offsets"][partition] = offset

    def truncate_to(
        self, partition: int, offset: int, topic: "Optional[str]" = None
    ) -> None:
        """Log truncation WHILE the broker serves: every record at or
        after ``offset`` is deleted and the end watermark pulls BACK to
        ``offset`` — the follower-made-leader shape of an unclean
        election (pair with unclean_elect() for the epoch bump)."""
        store = self._mut_store(topic)
        if partition not in store["records"]:
            raise AssertionError(f"truncate_to() unknown partition {partition}")
        if offset >= store["end_offsets"][partition]:
            return
        rs = [r for r in store["records"][partition] if r[0] < offset]
        self._rebuild_chunks(store, partition, rs)
        store["records"][partition] = rs
        store["end_offsets"][partition] = max(
            offset, store["start_offsets"][partition]
        )

    def unclean_elect(
        self,
        partition: int,
        truncate_to: "Optional[int]" = None,
        topic: "Optional[str]" = None,
    ) -> int:
        """Unclean leader election: optionally truncate the log to
        ``truncate_to`` (the new leader's shorter log), then bump the
        partition's leader epoch.  Batches produced afterwards carry the
        new epoch; fetches sending the old current_leader_epoch answer
        FENCED_LEADER_EPOCH; OffsetForLeaderEpoch answers the old epoch's
        end offset from the history.  Returns the new epoch."""
        store = self._mut_store(topic)
        if partition not in store["records"]:
            raise AssertionError(
                f"unclean_elect() unknown partition {partition}"
            )
        if truncate_to is not None:
            self.truncate_to(partition, truncate_to, topic=topic)
        new_epoch = store["epoch"][partition] + 1
        store["epoch"][partition] = new_epoch
        store["epoch_starts"][partition].append(
            (new_epoch, store["end_offsets"][partition])
        )
        return new_epoch

    def stop(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._open_conns)
            self._open_conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def kill(self) -> None:
        """Broker death mid-scan: the listener AND every live connection go
        away at once, like a SIGKILLed process — clients see resets, and
        reconnect attempts get connection-refused."""
        self.stop()

    def __enter__(self) -> "FakeBroker":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- serving -------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # listener closed
            # TLS handshake happens in the per-connection thread: one
            # client's failed handshake (SSLError is an OSError) must not
            # kill the accept loop.
            t = threading.Thread(
                target=self._handshake_and_serve, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _handshake_and_serve(self, conn: socket.socket) -> None:
        if self.faults is not None and self.faults.take_refusal():
            # Connection-refused window: close before serving a byte.
            conn.close()
            return
        if self.tls_context is not None:
            try:
                conn = self.tls_context.wrap_socket(conn, server_side=True)
            except OSError:
                conn.close()
                return
        with self._conn_lock:
            self._open_conns.add(conn)
        try:
            self._serve(conn)
        finally:
            with self._conn_lock:
                self._open_conns.discard(conn)

    def _recv_exact(self, conn: socket.socket, n: int) -> Optional[bytes]:
        chunks = []
        got = 0
        while got < n:
            try:
                chunk = conn.recv(n - got)
            except OSError:
                return None
            if not chunk:
                return None
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def _offered_mechanisms(self) -> "list[str]":
        out = []
        if self.sasl_plain is not None:
            out.append("PLAIN")
        if self.sasl_scram is not None:
            out.append(self.sasl_scram[0])
        return out

    def _serve(self, conn: socket.socket) -> None:
        authed = self.sasl_plain is None and self.sasl_scram is None
        scram_state = None  # in-flight kc.ScramServer for this connection
        with conn:
            while not self._stop.is_set():
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                (length,) = struct.unpack(">i", head)
                payload = self._recv_exact(conn, length)
                if payload is None:
                    return
                api_key, api_version, corr, _client, r = kc.decode_request_header(
                    payload
                )
                if not authed and api_key not in (
                    kc.API_SASL_HANDSHAKE, kc.API_SASL_AUTHENTICATE,
                ):
                    return  # real brokers drop unauthenticated requests
                if api_key == kc.API_SASL_HANDSHAKE:
                    mech = kc.decode_sasl_handshake_request(r)
                    offered = self._offered_mechanisms()
                    if mech in offered:
                        if mech != "PLAIN":
                            scram_state = kc.ScramServer(*self.sasl_scram)
                        body = kc.encode_sasl_handshake_response(0, offered)
                    else:
                        body = kc.encode_sasl_handshake_response(33, offered)
                elif api_key == kc.API_SASL_AUTHENTICATE:
                    token = kc.decode_sasl_authenticate_request(r)
                    if scram_state is not None:
                        if scram_state._server_first is None:
                            body = kc.encode_sasl_authenticate_response(
                                0, None, scram_state.handle_first(token)
                            )
                        else:
                            ok, final = scram_state.handle_final(token)
                            if ok:
                                authed = True
                                body = kc.encode_sasl_authenticate_response(
                                    0, None, final
                                )
                            else:
                                body = kc.encode_sasl_authenticate_response(
                                    kc.ERR_SASL_AUTHENTICATION_FAILED,
                                    "Authentication failed: invalid "
                                    "credentials",
                                )
                            scram_state = None
                    elif self.sasl_plain is not None and token == kc.sasl_plain_token(
                        *self.sasl_plain
                    ):
                        authed = True
                        body = kc.encode_sasl_authenticate_response(0)
                    else:
                        body = kc.encode_sasl_authenticate_response(
                            kc.ERR_SASL_AUTHENTICATION_FAILED,
                            "Authentication failed: invalid credentials",
                        )
                else:
                    body = self._dispatch(api_key, api_version, r)
                if self.response_delay is not None:
                    time.sleep(self.response_delay(api_key, self.node_id))
                # Flexible responses use header v1 (a tag buffer after the
                # correlation id) — except ApiVersions, which stays header
                # v0 at every version.
                head_tags = (
                    b"\x00"
                    if api_key != kc.API_VERSIONS
                    and kc.is_flexible(api_key, api_version)
                    else b""
                )
                resp = (
                    struct.pack(">i", 4 + len(head_tags) + len(body))
                    + struct.pack(">i", corr)
                    + head_tags
                    + body
                )
                if not self._send_response(conn, resp):
                    return

    def _send_response(self, conn: socket.socket, resp: bytes) -> bool:
        """Send one framed response, applying stall/drop faults; returns
        False when the connection must close (drop fired or peer gone)."""
        f = self.faults
        if f is not None:
            stall = f.take_stall()
            if stall:
                time.sleep(stall)
            cut = f.take_drop()
            if cut is not None:
                try:
                    conn.sendall(resp[: max(0, cut)])
                except OSError:
                    pass
                return False
        try:
            conn.sendall(resp)
        except OSError:
            # Peer vanished (e.g. it timed out during a stall): this
            # connection is done, the broker itself stays up.
            return False
        return True

    def _dispatch(self, api_key: int, api_version: int, r: kc.ByteReader) -> bytes:
        if api_key == kc.API_VERSIONS:
            if self.no_api_versions:
                # Ancient brokers answer with an UNSUPPORTED_VERSION error.
                w = kc.ByteWriter()
                w.i16(35).i32(0)
                return w.done()
            av_max = self.api_ranges.get(kc.API_VERSIONS, (0, 0))[1]
            if api_version > av_max:
                # KIP-511: an unknown ApiVersions version gets error 35 in
                # v0 format; the client downgrades and retries.
                w = kc.ByteWriter()
                w.i16(35).i32(0)
                return w.done()
            return kc.encode_api_versions_response(
                [(k, lo, hi) for k, (lo, hi) in sorted(self.api_ranges.items())],
                api_version,
            )
        if api_key == kc.API_METADATA:
            requested = kc.decode_metadata_request(r, api_version)
            brokers = (
                self.cluster.broker_addrs()
                if self.cluster is not None
                else {self.node_id: ("127.0.0.1", self.port)}
            )
            topics: List[kc.TopicMetadata] = []
            # None/empty = ALL topics (the fleet discovery request path);
            # a name list answers per topic, unknown names with the error.
            names = requested if requested else self.topic_names()
            for name in names:
                store = self._stores.get(name)
                if store is not None:
                    topics.append(
                        kc.TopicMetadata(
                            0,
                            name,
                            [
                                kc.PartitionMetadata(0, p, self._leader(p))
                                for p in sorted(store["records"])
                            ],
                            is_internal=int(name in self.internal_names),
                        )
                    )
                else:
                    topics.append(
                        kc.TopicMetadata(
                            kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, name or "", []
                        )
                    )
            if not (self.api_ranges[kc.API_METADATA][0] <= api_version
                    <= self.api_ranges[kc.API_METADATA][1]):
                raise AssertionError(
                    f"client requested unadvertised Metadata v{api_version}"
                )
            return kc.encode_metadata_response(
                kc.MetadataResponse(brokers, 0, topics), version=api_version
            )
        if api_key == kc.API_LIST_OFFSETS:
            req_topic, parts = kc.decode_list_offsets_request(r, api_version)
            store = self._stores.get(req_topic, None)
            records = store["records"] if store is not None else {}
            results = []
            for pid, ts in parts:
                if pid not in records:
                    results.append((pid, kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, -1))
                elif ts == kc.EARLIEST_TIMESTAMP:
                    start = store["start_offsets"][pid]
                    results.append(
                        (pid, 0, -1, start, self._epoch_at(store, pid, start))
                    )
                elif ts == kc.LATEST_TIMESTAMP:
                    results.append((
                        pid, 0, -1, store["end_offsets"][pid],
                        store["epoch"].get(pid, 0),
                    ))
                else:
                    # Timestamp lookup: earliest offset whose record ts >= query
                    # (-1 when no such record), like a real broker.
                    hit = next(
                        (off for off, rts, _k, _v in records[pid] if rts >= ts),
                        -1,
                    )
                    epoch = self._epoch_at(store, pid, hit) if hit >= 0 else -1
                    results.append((pid, 0, ts, hit, epoch))
            return kc.encode_list_offsets_response(
                req_topic, results, api_version
            )
        if api_key == kc.API_FETCH:
            self.fetch_count += 1
            req_topic, parts, _mw, _mb, _xb = kc.decode_fetch_request(r, api_version)
            store = self._stores.get(req_topic, None)
            out = []
            budget = _xb if self.honor_max_bytes else None
            served_any = False
            for pid, fetch_offset, _pmax, req_epoch in parts:
                if self.faults is not None:
                    code = self.faults.take_fetch_error()
                    if code is not None:
                        # Transient per-partition fetch error (leader
                        # election, coordinator churn): the client should
                        # warn, back off, and re-poll.
                        out.append((pid, code, -1, b""))
                        continue
                rs = store["records"].get(pid) if store is not None else None
                if rs is None:
                    out.append((pid, kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, b""))
                    continue
                if self._leader(pid) != self.node_id:
                    # A real broker rejects fetches for partitions it does
                    # not lead.
                    out.append((pid, kc.ERR_NOT_LEADER_FOR_PARTITION, -1, b""))
                    continue
                # KIP-320 fencing: a client-sent current_leader_epoch that
                # disagrees with the partition's epoch is rejected — below
                # means the client's view predates an election (FENCED),
                # above means it is from the future (UNKNOWN).
                cur_epoch = store["epoch"].get(pid, 0)
                if req_epoch >= 0 and req_epoch != cur_epoch:
                    err = (
                        kc.ERR_FENCED_LEADER_EPOCH
                        if req_epoch < cur_epoch
                        else kc.ERR_UNKNOWN_LEADER_EPOCH
                    )
                    out.append((pid, err, -1, b""))
                    continue
                hw = store["end_offsets"][pid]
                log_start = store["start_offsets"][pid]
                if fetch_offset < log_start or fetch_offset > hw:
                    # The requested position no longer exists (retention
                    # expired it) or never did (beyond the log end).
                    out.append((pid, kc.ERR_OFFSET_OUT_OF_RANGE, -1, b""))
                    continue
                # First pre-encoded chunk whose last offset reaches the fetch
                # position (it may start earlier; clients filter by offset,
                # exactly as with real compacted batches).
                chunks = store["chunks"][pid]
                i = bisect.bisect_left(store["chunk_last"][pid], fetch_offset)
                if self.honor_partition_max_bytes:
                    buf = bytearray()
                    for j in range(i, len(chunks)):
                        buf += chunks[j][2]
                        if len(buf) >= _pmax:
                            break
                    record_set = bytes(buf[:_pmax])
                else:
                    record_set = chunks[i][2] if i < len(chunks) else b""
                if budget is not None:
                    cut = max(budget, 0)
                    if not served_any and len(record_set) >= 12:
                        # KIP-74: the first batch of the response is always
                        # returned whole, even when it exceeds max_bytes —
                        # guarantees the consumer can make progress.
                        (blen,) = struct.unpack_from(">i", record_set, 8)
                        cut = max(cut, 12 + blen)
                    record_set = record_set[:cut]
                    budget -= len(record_set)
                if record_set:
                    served_any = True
                out.append((pid, 0, hw, record_set, log_start))
            return kc.encode_fetch_response(req_topic, out, api_version)
        if api_key == kc.API_OFFSET_FOR_LEADER_EPOCH:
            req_topic, parts = kc.decode_offset_for_leader_epoch_request(
                r, api_version
            )
            store = self._stores.get(req_topic, None)
            results = []
            for pid, cur_epoch, ask_epoch in parts:
                if store is None or pid not in store["records"]:
                    results.append(
                        (pid, kc.ERR_UNKNOWN_TOPIC_OR_PARTITION, -1, -1)
                    )
                    continue
                broker_epoch = store["epoch"].get(pid, 0)
                if cur_epoch >= 0 and cur_epoch != broker_epoch:
                    err = (
                        kc.ERR_FENCED_LEADER_EPOCH
                        if cur_epoch < broker_epoch
                        else kc.ERR_UNKNOWN_LEADER_EPOCH
                    )
                    results.append((pid, err, -1, -1))
                    continue
                # End offset of the largest epoch <= ask: the next epoch's
                # first offset, or the live log end for the latest epoch.
                history = store["epoch_starts"].get(pid) or [
                    (0, store["start_offsets"][pid])
                ]
                ans_epoch, ans_end = -1, -1
                for i, (ep, _start) in enumerate(history):
                    if ep <= ask_epoch:
                        ans_epoch = ep
                        ans_end = (
                            history[i + 1][1]
                            if i + 1 < len(history)
                            else store["end_offsets"][pid]
                        )
                results.append((pid, 0, ans_epoch, ans_end))
            return kc.encode_offset_for_leader_epoch_response(
                req_topic, results, api_version
            )
        raise AssertionError(f"fake broker: unsupported api {api_key}")

    def _leader(self, partition: int) -> int:
        if self.cluster is not None:
            return self.cluster.leader(partition)
        return self.node_id


class FakeCluster:
    """Several FakeBroker nodes sharing one topic; partition p is led by
    node p % n_nodes.  Exercises the client's by-leader fetch grouping and
    NOT_LEADER rerouting, which a single node never does."""

    def __init__(
        self,
        topic: str,
        partition_records: Dict[int, List[Record]],
        n_nodes: int = 2,
        **broker_kwargs,
    ):
        self.n_nodes = n_nodes
        #: partition -> node overrides (leader migration mid-scan); every
        #: node serves every partition's records, so after migration the
        #: new leader answers fetches and the old one NOT_LEADERs them —
        #: like a real reassignment with full replication.
        self._leader_overrides: Dict[int, int] = {}
        self.nodes = [
            FakeBroker(
                topic, partition_records, node_id=i, cluster=self, **broker_kwargs
            )
            for i in range(n_nodes)
        ]

    def leader(self, partition: int) -> int:
        return self._leader_overrides.get(partition, partition % self.n_nodes)

    def migrate_leader(self, partition: int, node_id: int) -> None:
        """Move a partition's leadership; takes effect on the next
        metadata/fetch the brokers serve."""
        self._leader_overrides[partition] = node_id

    def kill(self, node_id: int) -> None:
        """SIGKILL one node: listener and live connections drop; leadership
        of its partitions must be migrated for the scan to recover."""
        self.nodes[node_id].kill()

    def create_topic(
        self,
        name: str,
        partition_records: "Dict[int, List[Record]]",
        internal: bool = False,
    ) -> None:
        """Mid-test topic creation on every node (all nodes replicate all
        topics, like the single-topic records every node already serves)."""
        for b in self.nodes:
            b.create_topic(name, partition_records, internal=internal)

    def produce(
        self,
        partition: int,
        records: "List[Record]",
        topic: "Optional[str]" = None,
    ) -> None:
        """Append to every node's replica of the partition (tests produce
        through the cluster so a leader migration cannot strand records)."""
        for b in self.nodes:
            b.produce(partition, records, topic=topic)

    def broker_addrs(self) -> Dict[int, "tuple[str, int]"]:
        return {b.node_id: ("127.0.0.1", b.port) for b in self.nodes}

    def start(self) -> "FakeCluster":
        for b in self.nodes:
            b.start()
        return self

    def stop(self) -> None:
        for b in self.nodes:
            b.stop()

    def __enter__(self) -> "FakeCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def bootstrap(self) -> str:
        return ",".join(f"127.0.0.1:{b.port}" for b in self.nodes)


class ChaosTrigger:
    """Source proxy that fires ``action`` once, after the Nth yielded batch:
    chaos strikes mid-scan, at a deterministic point between engine steps
    (after the init handshake — metadata/watermarks — has succeeded)."""

    def __init__(self, inner, after_batches: int, action):
        self.inner = inner
        self.after = after_batches
        self.action = action
        self._fired = False

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def batches(self, *args, **kwargs):
        n = 0
        for batch in self.inner.batches(*args, **kwargs):
            yield batch
            n += 1
            if n == self.after and not self._fired:
                self._fired = True
                self.action()
