"""Pure unit tests for the transport retry substrate (io/retry.py):
deterministic backoff schedule, jitter bounds, and budget exhaustion →
degraded transitions — injected rand/sleep, no sockets, tier-1 fast."""

import pytest

from kafka_topic_analyzer_tpu.config import TransportRetryConfig
from kafka_topic_analyzer_tpu.io.retry import Backoff, PartitionRetryBudget


def test_schedule_doubles_and_caps():
    cfg = TransportRetryConfig(backoff_ms=100, backoff_max_ms=1000, jitter=0.0)
    b = Backoff(cfg, rand=lambda: 0.5, sleep=lambda s: None)
    assert [b.delay_ms(k) for k in range(1, 6)] == [100, 200, 400, 800, 1000]
    assert b.delay_ms(0) == 0.0  # no failures yet -> no delay


def test_jitter_bounds():
    cfg = TransportRetryConfig(
        backoff_ms=100, backoff_max_ms=10_000, jitter=0.2
    )
    assert Backoff(cfg, rand=lambda: 0.0).delay_ms(1) == pytest.approx(80.0)
    assert Backoff(cfg, rand=lambda: 0.5).delay_ms(1) == pytest.approx(100.0)
    hi = Backoff(cfg, rand=lambda: 1.0 - 1e-12).delay_ms(1)
    assert hi <= 120.0 and hi == pytest.approx(120.0)


def test_jittered_delay_never_exceeds_cap():
    cfg = TransportRetryConfig(backoff_ms=100, backoff_max_ms=1000, jitter=0.2)
    b = Backoff(cfg, rand=lambda: 0.999999)
    for attempt in (4, 5, 50):
        assert b.delay_ms(attempt) <= 1000.0


def test_huge_attempt_counts_do_not_overflow():
    cfg = TransportRetryConfig(backoff_ms=100, backoff_max_ms=500, jitter=0.0)
    b = Backoff(cfg, rand=lambda: 0.5)
    assert b.delay_ms(100_000) == 500.0


def test_sleep_for_uses_injected_sleep():
    slept = []
    cfg = TransportRetryConfig(backoff_ms=100, backoff_max_ms=1000, jitter=0.0)
    b = Backoff(cfg, rand=lambda: 0.5, sleep=slept.append)
    assert b.sleep_for(2) == pytest.approx(0.2)
    assert slept == [pytest.approx(0.2)]
    assert b.sleep_for(0) == 0.0
    assert len(slept) == 1  # zero delay never calls sleep


def test_budget_exhaustion_degrades_exactly_once():
    budget = PartitionRetryBudget(3)
    assert not budget.record_failure(7, "ConnectionResetError: peer reset")
    assert not budget.record_failure(7, "ConnectionResetError: peer reset")
    assert budget.record_failure(7, "OSError: timed out")  # third strike
    assert "3 consecutive transport failures" in budget.degraded[7]
    assert "OSError: timed out" in budget.degraded[7]
    # Already degraded: never re-triggers (the caller dropped it already).
    assert not budget.record_failure(7, "whatever")


def test_budget_resets_on_success():
    budget = PartitionRetryBudget(2)
    assert not budget.record_failure(0, "a")
    budget.record_success(0)
    assert not budget.record_failure(0, "b")  # count restarted after success
    assert budget.record_failure(0, "c")
    assert 0 in budget.degraded


def test_budgets_are_per_partition():
    budget = PartitionRetryBudget(2)
    assert not budget.record_failure(0, "x")
    assert not budget.record_failure(1, "x")
    assert budget.record_failure(0, "x")
    assert 1 not in budget.degraded


def test_config_from_overrides_pops_retry_knobs():
    ov = {
        "retry.backoff.ms": "50",
        "reconnect.backoff.ms": "80",
        "reconnect.backoff.max.ms": "400",
        "transport.retry.budget": "3",
        "fetch.min.bytes": "1",
    }
    cfg = TransportRetryConfig.from_overrides(ov)
    assert cfg.backoff_ms == 80  # the higher of the two configured floors
    assert cfg.backoff_max_ms == 400
    assert cfg.retry_budget == 3
    assert set(ov) == {"fetch.min.bytes"}  # non-retry knobs untouched


def test_config_validation():
    with pytest.raises(ValueError, match="retry.backoff.ms"):
        TransportRetryConfig(backoff_ms=0)
    with pytest.raises(ValueError, match="reconnect.backoff.max.ms"):
        TransportRetryConfig(backoff_ms=100, backoff_max_ms=50)
    with pytest.raises(ValueError, match="transport.retry.budget"):
        TransportRetryConfig(retry_budget=0)
    with pytest.raises(ValueError, match="jitter"):
        TransportRetryConfig(jitter=1.0)


def test_wire_source_threads_overrides_to_retry_config():
    """The librdkafka overrides table reaches the scan's retry policy (and
    the knobs are consumed, not warned about as unsupported)."""
    from fake_broker import FakeBroker
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    with FakeBroker("rt.topic", {0: [(0, 0, b"k", b"v")]}) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}",
            "rt.topic",
            overrides={
                "retry.backoff.ms": "7",
                "reconnect.backoff.max.ms": "70",
                "transport.retry.budget": "2",
            },
        )
        try:
            assert src.retry_config.backoff_ms == 7
            assert src.retry_config.backoff_max_ms == 70
            assert src.retry_config.retry_budget == 2
            assert src.degraded_partitions() == {}
        finally:
            src.close()
