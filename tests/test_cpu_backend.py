"""CPU-exact oracle vs a hand-replayed sequential model of the reference."""

import numpy as np

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.records import RecordBatch
from kafka_topic_analyzer_tpu.results import U64_MAX


def make_batch(rows):
    """rows: (partition, key_len|None, value_len|None, ts_s, h32)."""
    n = len(rows)
    b = RecordBatch.empty(n)
    for i, (p, kl, vl, ts, h32) in enumerate(rows):
        b.partition[i] = p
        b.key_null[i] = kl is None
        b.key_len[i] = 0 if kl is None else kl
        b.value_null[i] = vl is None
        b.value_len[i] = 0 if vl is None else vl
        b.ts_s[i] = ts
        b.key_hash32[i] = h32
        b.key_hash64[i] = h32  # identity is enough for these tests
        b.valid[i] = True
    return b


def test_counters_match_reference_semantics():
    cfg = AnalyzerConfig(num_partitions=2)
    be = CpuExactBackend(cfg, init_now_s=10_000)
    # p0: keyed+value, null-key+value, keyed tombstone
    # p1: keyed+value
    be.update(
        make_batch(
            [
                (0, 3, 10, 100, 1),
                (0, None, 7, 50, 0),
                (0, 4, None, 200, 2),
                (1, 2, 20, 150, 3),
            ]
        )
    )
    m = be.finalize()
    assert m.total(0) == 3 and m.total(1) == 1
    assert m.alive(0) == 2 and m.tombstones(0) == 1
    assert m.key_null(0) == 1 and m.key_non_null(0) == 2
    # Tombstone key bytes still count (src/metric.rs:218-231).
    assert m.key_size_sum(0) == 7
    assert m.value_size_sum(0) == 17
    # min/max excludes the tombstone's key-only size (src/metric.rs:249-251).
    assert m.smallest_message == 7  # null-key record: value only
    assert m.largest_message == 22
    assert m.overall_size == 3 + 10 + 7 + 4 + 2 + 20
    assert m.overall_count == 4
    # Timestamps: earliest min(now=10000, 50) = 50; latest 200.
    assert m.earliest_ts_s == 50
    assert m.latest_ts_s == 200
    # Averages divide by alive.
    assert m.key_size_avg(0) == 7 // 2
    assert m.message_size_avg(0) == (7 + 17) // 2


def test_empty_scan_reports_init_values():
    cfg = AnalyzerConfig(num_partitions=1)
    be = CpuExactBackend(cfg, init_now_s=1234)
    m = be.finalize()
    assert m.earliest_ts_s == 1234  # earliest starts at "now"
    assert m.latest_ts_s == 0      # latest starts at epoch
    assert m.smallest_message == U64_MAX
    assert m.smallest_message_reported() == 0
    assert m.largest_message == 0


def test_alive_bitmap_last_writer_wins():
    cfg = AnalyzerConfig(num_partitions=1, count_alive_keys=True, alive_bitmap_bits=16)
    be = CpuExactBackend(cfg, init_now_s=0)
    # Key h=5: alive then tombstoned in the same batch → dead.
    # Key h=6: tombstoned then re-inserted → alive.
    # Key h=7: alive.  Null-key records never touch the bitmap.
    be.update(
        make_batch(
            [
                (0, 2, 5, 0, 5),
                (0, 2, None, 0, 5),
                (0, 2, None, 0, 6),
                (0, 2, 5, 0, 6),
                (0, 2, 5, 0, 7),
                (0, None, 5, 0, 0),
            ]
        )
    )
    assert be.finalize().alive_keys == 2
    # Across batches: kill 7, revive 5.
    be2_rows = [(0, 2, None, 0, 7), (0, 2, 9, 0, 5)]
    be.update(make_batch(be2_rows))
    assert be.finalize().alive_keys == 2  # {5, 6}


def test_bitmap_collision_semantics():
    # Two distinct keys sharing a slot conflate, like the reference's
    # fnv32-indexed BitSet (src/metric.rs:256-260).
    cfg = AnalyzerConfig(num_partitions=1, count_alive_keys=True, alive_bitmap_bits=4)
    be = CpuExactBackend(cfg, init_now_s=0)
    be.update(
        make_batch(
            [
                (0, 2, 5, 0, 3),
                (0, 2, 5, 0, 19),  # 19 mod 16 == 3 → same slot
            ]
        )
    )
    assert be.finalize().alive_keys == 1
