"""Test env: force JAX onto a virtual 8-device CPU platform *before* jax is
imported anywhere (SURVEY.md §4 — multi-core without a cluster), and make the
repo root importable without installation."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# The accelerator watchdog (jax_support.ensure_responsive_accelerator) is
# moot on the forced-CPU test platform; short-circuit it so CLI tests don't
# pay a subprocess probe each (its own tests delenv this).
os.environ.setdefault("KTA_ACCEL_OK", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Some environments (axon) import jax from sitecustomize before conftest runs,
# freezing jax_platforms from the ambient env; force_platform overrides it
# via the config API and drops the tunnel plugin factory, whose client init
# would otherwise block when the tunnel/chip lease is wedged — tests must
# never depend on the chip being reachable.
from kafka_topic_analyzer_tpu.jax_support import force_platform  # noqa: E402

force_platform("cpu")
