"""Test env: force JAX onto a virtual 8-device CPU platform *before* jax is
imported anywhere (SURVEY.md §4 — multi-core without a cluster), and make the
repo root importable without installation."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some environments (axon) import jax from sitecustomize before conftest runs,
# freezing jax_platforms from the ambient env; override via the config API,
# which works as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
