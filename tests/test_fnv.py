"""Hash kernels: reference-variant fnv32, standard fnv64, batch == scalar."""

import numpy as np

from kafka_topic_analyzer_tpu.ops.fnv import (
    fnv1a32_ref,
    fnv1a32_ref_batch,
    fnv1a64,
    fnv1a64_batch,
    splitmix64,
    splitmix64_np,
)


def test_fnv32_ref_empty_is_offset_basis():
    assert fnv1a32_ref(b"") == 0x811C9DC5


def test_fnv32_ref_variant_multiplies_by_offset_basis():
    # One hand-evaluated step of the reference's (buggy) recurrence
    # (src/fnv32.rs:92-101): h = (basis ^ byte) * basis mod 2^32.
    expected = ((0x811C9DC5 ^ 0x61) * 0x811C9DC5) & 0xFFFFFFFF
    assert fnv1a32_ref(b"a") == expected
    # And differs from standard FNV-1a-32 of "a" (0xe40c292c).
    assert fnv1a32_ref(b"a") != 0xE40C292C


def test_fnv64_known_vectors():
    # Standard FNV-1a 64-bit test vectors (isthe.com/chongo/tech/comp/fnv).
    assert fnv1a64(b"") == 0xCBF29CE484222325
    assert fnv1a64(b"a") == 0xAF63DC4C8601EC8C
    assert fnv1a64(b"foobar") == 0x85944171F73967E8


def test_batch_matches_scalar():
    rng = np.random.default_rng(0)
    keys = [rng.integers(0, 256, size=int(n), dtype=np.uint8).tobytes()
            for n in rng.integers(0, 20, size=64)]
    maxlen = max(len(k) for k in keys)
    padded = np.zeros((len(keys), maxlen), dtype=np.uint8)
    lengths = np.zeros(len(keys), dtype=np.int64)
    for i, k in enumerate(keys):
        padded[i, : len(k)] = np.frombuffer(k, dtype=np.uint8)
        lengths[i] = len(k)
    h32 = fnv1a32_ref_batch(padded, lengths)
    h64 = fnv1a64_batch(padded, lengths)
    for i, k in enumerate(keys):
        assert int(h32[i]) == fnv1a32_ref(k)
        assert int(h64[i]) == fnv1a64(k)


def test_splitmix_batch_matches_scalar():
    xs = np.array([0, 1, 2, 0xDEADBEEF, 2**63, 2**64 - 1], dtype=np.uint64)
    out = splitmix64_np(xs)
    for i, x in enumerate(xs.tolist()):
        assert int(out[i]) == splitmix64(int(x))
