"""gzip/snappy/LZ4/zstd decompression: native vs pure-Python vs handcrafted
streams, and end-to-end through record batches + the fake broker (zstd
specifics live in test_zstd.py)."""

import struct

import pytest

from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.compression import (
    UnsupportedCodecError,
    decompress,
    lz4_compress_frame,
    lz4_decompress,
    lz4_decompress_py,
    snappy_compress_xerial,
    snappy_decompress,
    snappy_decompress_py,
)

PAYLOADS = [
    b"",
    b"x",
    b"hello snappy world " * 40,
    bytes(range(256)) * 17,
]


@pytest.mark.parametrize("data", PAYLOADS)
def test_snappy_literal_roundtrip_python(data):
    assert snappy_decompress_py(snappy_compress_xerial(data)) == data


@pytest.mark.parametrize("data", PAYLOADS)
def test_snappy_literal_roundtrip_native_dispatch(data):
    assert snappy_decompress(snappy_compress_xerial(data)) == data


def _snappy_with_copy() -> "tuple[bytes, bytes]":
    """Handcrafted raw snappy stream using a copy op (incl. RLE overlap)."""
    # "abcd" literal, then copy len=8 offset=4 -> "abcdabcd" appended,
    # then copy len=4 offset=1 (RLE of last byte 'd').
    expected = b"abcd" + b"abcdabcd" + b"dddd"
    out = bytearray()
    out.append(len(expected))  # uncompressed length varint (<128)
    out.append((4 - 1) << 2)  # literal, 4 bytes
    out += b"abcd"
    # copy kind 1: len 4..11, offset 11-bit: tag = ((len-4)<<2)|1 | (off>>8)<<5
    out.append(((8 - 4) << 2) | 1)
    out.append(4)  # offset low byte
    out.append(((4 - 4) << 2) | 1)
    out.append(1)
    return bytes(out), expected


def test_snappy_copy_ops_python_and_native():
    raw, expected = _snappy_with_copy()
    assert snappy_decompress_py(raw) == expected
    assert snappy_decompress(raw) == expected


@pytest.mark.parametrize("data", PAYLOADS)
def test_lz4_frame_roundtrip(data):
    assert lz4_decompress_py(lz4_compress_frame(data)) == data
    assert lz4_decompress(lz4_compress_frame(data)) == data


def test_lz4_block_with_matches():
    # literals "abcd", match offset 4 len 8 (overlapping copy), then final
    # literals "XY".  Token: lit=4, mlen=8-4=4 -> token 0x44.
    block = bytes([0x44]) + b"abcd" + struct.pack("<H", 4) + bytes([0x20]) + b"XY"
    expected = b"abcd" + b"abcdabcd" + b"XY"
    assert lz4_decompress_py(block) == expected
    assert lz4_decompress(block) == expected


def test_corrupt_snappy_raises_without_buffer_churn():
    # A tiny payload declaring a huge uncompressed length must fail fast
    # (no 1 GiB allocation loop) with a clear error.
    bogus = b"\xff\xff\xff\xff\x0f" + b"x"  # ulen varint ~2^34
    with pytest.raises(ValueError, match="> 1 GiB cap"):
        snappy_decompress(bogus)


def test_truncated_lz4_literal_raises():
    # Token promises 10 literal bytes but only 2 are present: must raise,
    # not silently return truncated data.
    with pytest.raises(ValueError, match="truncated lz4 literal"):
        lz4_decompress_py(bytes([0xA0]) + b"ab")
    with pytest.raises(ValueError, match="truncated lz4 literal"):
        lz4_decompress(bytes([0xA0]) + b"ab")


def test_truncated_snappy_literal_raises():
    bogus = bytes([4]) + bytes([(4 - 1) << 2]) + b"ab"  # promises 4, has 2
    with pytest.raises(ValueError, match="truncated snappy literal"):
        snappy_decompress_py(bogus)


def test_corrupt_compressed_batch_is_protocol_error():
    buf = bytearray(kc.encode_record_batch(
        [(0, 0, b"k", b"v" * 50)], kc.COMPRESSION_SNAPPY
    ))
    # Replace the whole compressed payload (past the 61-byte batch header)
    # with garbage that parses as a huge snappy length declaration.
    buf[61:] = b"\xff" * (len(buf) - 61)
    with pytest.raises(kc.KafkaProtocolError, match="record batch at offset"):
        list(kc.decode_record_batches(bytes(buf)))


def test_truncated_lz4_length_extension_rejected_everywhere():
    # Token 0xF0 starts a literal-length extension that runs off the end:
    # both decoders must reject (the native one used to accept silently).
    bogus = bytes([0xF0, 0xFF, 0xFF])
    with pytest.raises(ValueError, match="length extension"):
        lz4_decompress_py(bogus)
    with pytest.raises(ValueError, match="length extension"):
        lz4_decompress(bogus)  # native says -1, python delivers the verdict


def test_lz4_python_path_respects_cap(monkeypatch):
    import kafka_topic_analyzer_tpu.io.compression as comp

    # Tiny cap so a legitimate stream trips it without big allocations.
    monkeypatch.setattr(comp, "MAX_DECOMPRESSED", 1000)
    big = comp.lz4_compress_frame(b"x" * 5000)
    with pytest.raises(ValueError, match="cap"):
        comp.lz4_decompress_py(big)


def test_gzip_path_respects_cap(monkeypatch):
    import gzip

    import kafka_topic_analyzer_tpu.io.compression as comp

    # Tiny cap so a gzip bomb trips it without big allocations.
    monkeypatch.setattr(comp, "MAX_DECOMPRESSED", 1000)
    bomb = gzip.compress(b"x" * 50_000)
    with pytest.raises(ValueError, match="cap"):
        comp.decompress(1, bomb)
    # In-cap payloads still round-trip (both gzip and bare-zlib framing).
    assert comp.decompress(1, gzip.compress(b"ok" * 100)) == b"ok" * 100
    import zlib

    assert comp.decompress(1, zlib.compress(b"ok" * 100)) == b"ok" * 100


def test_gzip_truncated_stream_rejected():
    import gzip

    from kafka_topic_analyzer_tpu.io.compression import decompress as dec

    payload = gzip.compress(b"x" * 1000)
    with pytest.raises(ValueError, match="truncated"):
        dec(1, payload[:-8])  # trailer cut off
    # Trailing garbage after a complete stream stays tolerated, matching
    # the previous zlib.decompress(wbits=47) behavior.
    assert dec(1, payload + b"junk") == b"x" * 1000


def test_unknown_codec_rejected():
    with pytest.raises(UnsupportedCodecError, match="unknown compression"):
        decompress(5, b"\x00")


@pytest.mark.parametrize(
    "codec", [kc.COMPRESSION_SNAPPY, kc.COMPRESSION_LZ4, kc.COMPRESSION_ZSTD]
)
def test_record_batch_roundtrip_compressed(codec):
    records = [
        (10, 1_600_000_000_000, b"key-a", b"value-a" * 10),
        (11, 1_600_000_000_001, None, b"v"),
        (12, 1_600_000_000_002, b"key-b", None),
    ]
    buf = kc.encode_record_batch(records, codec)
    got = [(off, ts, k, v) for off, (ts, k, v) in kc.decode_record_batches(buf, verify_crc=True)]
    assert got == records


@pytest.mark.parametrize(
    "codec", [kc.COMPRESSION_SNAPPY, kc.COMPRESSION_LZ4, kc.COMPRESSION_ZSTD]
)
def test_wire_scan_with_compressed_broker(codec):
    import sys

    sys.path.insert(0, "tests")
    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    rows = [(i, 1_600_000_000_000 + i, f"k{i % 9}".encode(), bytes(20 + i % 50))
            for i in range(300)]
    with FakeBroker("z.topic", {0: rows}, compression=codec) as broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "z.topic")
        cfg = AnalyzerConfig(num_partitions=1, batch_size=128)
        m = run_scan("z.topic", src, CpuExactBackend(cfg, init_now_s=0), 128).metrics
        src.close()
    assert m.overall_count == 300
    assert m.overall_size == sum(len(k) + len(v) for _, _, k, v in rows)
