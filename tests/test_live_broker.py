"""Real-broker integration tier (SURVEY.md §4).

The reference's only end-to-end validation was a live-cluster run
(demo_output.png, /root/reference/README.md:27-28).  This repo's
cluster-free tiers (fake_broker.py, test_golden.py) validate the client
against OUR reading of the protocol; this tier validates it against a
broker somebody else wrote.

Gate: the build environment has no container runtime and no network
egress (see ROADMAP.md "Real-broker integration" for the recorded
attempt), so the live test is keyed on ``KTA_KAFKA_BOOTSTRAP``:

    docker run -p 9092:9092 apache/kafka:3.7.0   # single-node KRaft
    KTA_KAFKA_BOOTSTRAP=127.0.0.1:9092 pytest tests/test_live_broker.py

The producer machinery itself (io/kafka_produce.py) stays exercised in CI
by the ungated tests below: the Produce request's record set must decode —
through the same golden-locked decoder the wire client uses — back to the
records that went in.
"""

import os
import uuid

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_produce import (
    create_topic,
    encode_produce_request,
    produce,
)
from kafka_topic_analyzer_tpu.io.kafka_wire import (
    KafkaWireSource,
    records_to_batch,
)

BOOT = os.environ.get("KTA_KAFKA_BOOTSTRAP")


def _test_records(partitions: int = 3, n: int = 400):
    """Deterministic per-partition (ts_ms, key, value) rows covering the
    analyzer's semantic corners: null keys, tombstones (null values),
    repeated keys (compaction aliveness), varying sizes."""
    out = {}
    for p in range(partitions):
        rows = []
        for i in range(n):
            ts = 1_700_000_000_000 + 1_000 * i + p
            key = f"k{p}-{i % 29}".encode() if i % 5 else None
            value = (
                None if (key is not None and i % 11 == 3)
                else bytes(10 + (i * 13 + p) % 200)
            )
            rows.append((ts, key, value))
        out[p] = rows
    return out


def test_produce_record_set_roundtrips_through_decoder():
    """The bytes produce() would hand a live broker must decode back to
    the same records via the wire client's own decoder."""
    rows = _test_records(partitions=1, n=120)[0]
    record_set = kc.encode_record_batch(
        [(i, ts, k, v) for i, (ts, k, v) in enumerate(rows)]
    )
    decoded = list(kc.decode_record_batches(record_set, verify_crc=True))
    assert [off for off, _ in decoded] == list(range(len(rows)))
    assert [r for _, r in decoded] == rows


def test_produce_request_body_shape():
    """The Produce v3 body parses back field-for-field (the request the
    gated tier sends a real broker)."""
    record_set = kc.encode_record_batch([(0, 123, b"k", b"v")])
    body = encode_produce_request("t.opic", 7, record_set).done()
    r = kc.ByteReader(body)
    assert r.string() is None        # transactional_id
    assert r.i16() == -1             # acks
    assert r.i32() == 30_000         # timeout_ms
    assert r.i32() == 1              # topic_data[1]
    assert r.string() == "t.opic"
    assert r.i32() == 1              # partition_data[1]
    assert r.i32() == 7
    assert r.bytes_() == record_set
    assert r.remaining() == 0


def test_producer_version_negotiation_against_fake_broker():
    """_negotiated() clamps into the advertised range via a real
    ApiVersions round-trip, and refuses with a clear error when the
    broker's floor is above what this producer speaks."""
    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.io.kafka_produce import (
        API_PRODUCE,
        _negotiated,
    )
    from kafka_topic_analyzer_tpu.io.kafka_wire import BrokerConnection

    with FakeBroker(
        "t", {0: []},
        api_ranges={kc.API_VERSIONS: (0, 3), API_PRODUCE: (3, 9)},
    ) as b:
        conn = BrokerConnection("127.0.0.1", b.port)
        try:
            # Clamped to this module's non-flexible ceiling, not the
            # broker's flexible max.
            assert _negotiated(conn, API_PRODUCE, 3, 8) == 8
            # Cached: a second call must not re-handshake.  Poison the
            # request method so any round-trip attempt blows up.
            conn.request = None
            assert _negotiated(conn, API_PRODUCE, 3, 8) == 8
        finally:
            conn.close()
    with FakeBroker(
        "t", {0: []},
        api_ranges={kc.API_VERSIONS: (0, 3), API_PRODUCE: (9, 12)},
    ) as b:
        conn = BrokerConnection("127.0.0.1", b.port)
        try:
            with pytest.raises(kc.KafkaProtocolError,
                               match=r"v9-12.*speaks v3-8"):
                _negotiated(conn, API_PRODUCE, 3, 8)
        finally:
            conn.close()


@pytest.mark.skipif(
    not BOOT,
    reason="set KTA_KAFKA_BOOTSTRAP=host:port to run against a live broker",
)
def test_live_broker_end_to_end():
    """Create a fresh topic on the live broker, produce known records,
    scan it through the full wire client, and compare every metric to a
    locally-fed oracle over the same records.

    Assumes the broker uses CreateTime (the default) so stored timestamps
    are the produced ones; a LogAppendTime cluster would legitimately
    shift ts metrics."""
    topic = f"kta-live-{uuid.uuid4().hex[:12]}"
    partitions = 3
    recs = _test_records(partitions)
    create_topic(BOOT, topic, partitions)
    base = produce(BOOT, topic, recs)
    # Fresh topic: every batch lands at offset 0.
    assert all(b == 0 for b in base.values()), base

    cfg = AnalyzerConfig(
        num_partitions=partitions, batch_size=256,
        count_alive_keys=True, alive_bitmap_bits=20,
    )
    src = KafkaWireSource(BOOT, topic)
    try:
        got = run_scan(topic, src, CpuExactBackend(cfg, init_now_s=0),
                       256).metrics
    finally:
        src.close()

    oracle = CpuExactBackend(cfg, init_now_s=0)
    rows = [
        (p, ts, k, v)
        for p in range(partitions)
        for (ts, k, v) in recs[p]
    ]
    for lo in range(0, len(rows), 256):
        oracle.update(records_to_batch(rows[lo:lo + 256]))
    want = oracle.finalize()

    assert np.array_equal(got.per_partition, want.per_partition)
    assert np.array_equal(got.per_partition_extremes,
                          want.per_partition_extremes)
    assert got.overall_count == want.overall_count
    assert got.overall_size == want.overall_size
    assert got.alive_keys == want.alive_keys
    assert got.earliest_ts_s == want.earliest_ts_s
    assert got.latest_ts_s == want.latest_ts_s
