"""Sharded (mesh) backend parity vs the CPU oracle on the virtual 8-device
CPU platform — SURVEY.md §4 'multi-core without a cluster'."""

import jax
import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

SPEC = SyntheticSpec(
    num_partitions=7,  # deliberately not divisible by the shard count
    messages_per_partition=3_000,
    keys_per_partition=300,
    key_null_permille=60,
    tombstone_permille=180,
    value_len_min=20,
    value_len_max=220,
    seed=99,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def run_cpu(config):
    be = CpuExactBackend(config, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    return run_scan("t", src, be, config.batch_size).metrics


def run_sharded(config):
    be = ShardedTpuBackend(config, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    return run_scan("t", src, be, config.batch_size).metrics


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_parity(mesh_shape):
    cfg = AnalyzerConfig(
        num_partitions=7,
        batch_size=1024,
        count_alive_keys=True,
        alive_bitmap_bits=20,
        enable_hll=True,
        enable_quantiles=True,
        mesh_shape=mesh_shape,
    )
    m_cpu = run_cpu(cfg)
    m_tpu = run_sharded(cfg)
    assert np.array_equal(m_cpu.per_partition, m_tpu.per_partition)
    assert m_cpu.earliest_ts_s == m_tpu.earliest_ts_s
    assert m_cpu.latest_ts_s == m_tpu.latest_ts_s
    assert m_cpu.smallest_message == m_tpu.smallest_message
    assert m_cpu.largest_message == m_tpu.largest_message
    assert m_cpu.overall_size == m_tpu.overall_size
    assert m_cpu.overall_count == m_tpu.overall_count
    assert m_cpu.alive_keys == m_tpu.alive_keys
    # Sketches merged across shards stay inside their error budget.
    assert m_tpu.distinct_keys_hll == pytest.approx(
        m_cpu.distinct_keys_exact, rel=0.05
    )
    for q_exact, q_sketch in zip(m_cpu.quantiles.values, m_tpu.quantiles.values):
        assert q_sketch == pytest.approx(q_exact, rel=0.011)


def test_mixed_batch_update_splits_by_partition():
    cfg = AnalyzerConfig(num_partitions=7, batch_size=512, mesh_shape=(4, 1))
    be = ShardedTpuBackend(cfg, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    for batch in src.batches(512):
        be.update(batch)  # mixed-partition path
    m = be.finalize()
    assert int(m.overall_count) == 7 * 3_000


def test_cross_chunk_last_writer_wins():
    """A key whose alive-bitmap updates straddle the space-chunk boundary
    must resolve by RECORD order, not by which space shard saw it: the
    device-side ordered application (backends/step.py) is what makes the
    chunked input sharding exact."""
    from kafka_topic_analyzer_tpu.records import RecordBatch

    def batch_of(rows):
        b = RecordBatch.empty(len(rows))
        for i, (h32, value_len) in enumerate(rows):
            b.partition[i] = 0
            b.key_len[i] = 4
            b.value_null[i] = value_len is None
            b.value_len[i] = 0 if value_len is None else value_len
            b.ts_s[i] = 100 + i
            b.key_hash32[i] = h32
            b.key_hash64[i] = h32
            b.valid[i] = True
        return b

    # batch_size 8 over (1, 2) → chunks of 4.  Key A: alive in chunk 0,
    # tombstoned in chunk 1 → dead.  Key B: tombstoned in chunk 0, alive
    # in chunk 1 → alive.  Key C alive twice in chunk 0 → alive.  Key D
    # only in chunk 1, alive → alive.
    rows = [
        (0xA, 10), (0xB, None), (0xC, 5), (0xC, 6),   # chunk 0
        (0xA, None), (0xB, 7), (0xD, 8), (0xD, 9),    # chunk 1
    ]
    cfg = AnalyzerConfig(
        num_partitions=1,
        batch_size=8,
        mesh_shape=(1, 2),
        count_alive_keys=True,
        alive_bitmap_bits=8,
    )
    be = ShardedTpuBackend(cfg, init_now_s=10**10)
    be.update_shards([batch_of(rows)])
    m = be.finalize()
    assert int(m.alive_keys) == 3  # B, C, D alive; A dead

    # Same records through the CPU oracle (sequential replay).
    oracle = CpuExactBackend(
        AnalyzerConfig(
            num_partitions=1, batch_size=8,
            count_alive_keys=True, alive_bitmap_bits=8,
        ),
        init_now_s=10**10,
    )
    oracle.update(batch_of(rows))
    assert int(oracle.finalize().alive_keys) == 3


def test_prepare_shard_staged_step_matches_direct():
    """update_shards fed PackedShards (the engine's prefetch-worker
    staging) must be byte-identical to feeding decoded batches."""
    import numpy as np

    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    cfg = AnalyzerConfig(
        num_partitions=4, batch_size=512, count_alive_keys=True,
        alive_bitmap_bits=16, enable_hll=True, hll_p=10,
        enable_quantiles=True, mesh_shape=(2, 2),
    )
    spec = SyntheticSpec(
        num_partitions=4, messages_per_partition=900,
        keys_per_partition=70, tombstone_permille=90, seed=31,
    )
    batches = list(SyntheticSource(spec).batches(cfg.batch_size))
    halves = [batches[i::2] for i in range(2)]  # row r gets every 2nd batch
    direct = ShardedTpuBackend(cfg, init_now_s=0)
    staged = ShardedTpuBackend(cfg, init_now_s=0)
    rounds = max(len(h) for h in halves)
    for i in range(rounds):
        row = [h[i] if i < len(h) else None for h in halves]
        direct.update_shards(list(row))
        staged.update_shards([
            staged.prepare_shard(b) if b is not None else None for b in row
        ])
    md, ms = direct.finalize(), staged.finalize()
    assert np.array_equal(md.per_partition, ms.per_partition)
    assert np.array_equal(md.per_partition_extremes, ms.per_partition_extremes)
    assert md.overall_count == ms.overall_count
    assert md.alive_keys == ms.alive_keys
    assert md.distinct_keys_hll == ms.distinct_keys_hll
    assert list(md.quantiles.values) == list(ms.quantiles.values)


def test_non_dense_partitions_sharded_engine_scan(tmp_path):
    """Sharded engine scan over true partition ids {5,7,9}: the staged
    packing must use dense rows while snapshots keep true ids (same
    regression class as the single-device staging)."""
    import numpy as np

    from fake_broker import FakeBroker

    from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
    from kafka_topic_analyzer_tpu.checkpoint import load_snapshot
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.kafka_wire import (
        KafkaWireSource,
        records_to_batch,
    )
    from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

    ids = (5, 7, 9)
    records = {
        p: [
            (off, 1_600_000_000_000 + off * 400,
             f"p{p}-k{off % 23}".encode() if off % 6 else None,
             None if off % 11 == 4 else bytes(8 + (off * 5 + p) % 50))
            for off in range(500)
        ]
        for p in ids
    }
    cfg = AnalyzerConfig(
        num_partitions=3, batch_size=256, count_alive_keys=True,
        alive_bitmap_bits=16, mesh_shape=(2, 2),
    )
    with FakeBroker("gap.sharded", records) as b:
        src = KafkaWireSource(f"127.0.0.1:{b.port}", "gap.sharded")
        try:
            result = run_scan(
                "gap.sharded", src, ShardedTpuBackend(cfg, init_now_s=0),
                256, snapshot_dir=str(tmp_path), snapshot_every_s=0.0,
            )
        finally:
            src.close()
    snap = load_snapshot(
        str(tmp_path), "gap.sharded", cfg,
        template=ShardedTpuBackend(cfg, init_now_s=0).get_state(),
    )
    assert snap is not None
    _, next_offsets, records_seen, _ = snap
    assert next_offsets == {5: 500, 7: 500, 9: 500}
    assert records_seen == 1500

    m = result.metrics
    assert m.partitions == [5, 7, 9]
    oracle = CpuExactBackend(cfg, init_now_s=0)
    rows = [
        (dense, ts, k, v)
        for dense, p in enumerate(ids)
        for (_off, ts, k, v) in records[p]
    ]
    for lo in range(0, len(rows), 256):
        oracle.update(records_to_batch(rows[lo:lo + 256]))
    want = oracle.finalize()
    assert np.array_equal(m.per_partition, want.per_partition)
    assert m.overall_count == want.overall_count
    assert m.alive_keys == want.alive_keys
