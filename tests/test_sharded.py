"""Sharded (mesh) backend parity vs the CPU oracle on the virtual 8-device
CPU platform — SURVEY.md §4 'multi-core without a cluster'."""

import jax
import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.parallel.sharded import ShardedTpuBackend

SPEC = SyntheticSpec(
    num_partitions=7,  # deliberately not divisible by the shard count
    messages_per_partition=3_000,
    keys_per_partition=300,
    key_null_permille=60,
    tombstone_permille=180,
    value_len_min=20,
    value_len_max=220,
    seed=99,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def run_cpu(config):
    be = CpuExactBackend(config, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    return run_scan("t", src, be, config.batch_size).metrics


def run_sharded(config):
    be = ShardedTpuBackend(config, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    return run_scan("t", src, be, config.batch_size).metrics


@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4)])
def test_sharded_parity(mesh_shape):
    cfg = AnalyzerConfig(
        num_partitions=7,
        batch_size=1024,
        count_alive_keys=True,
        alive_bitmap_bits=20,
        enable_hll=True,
        enable_quantiles=True,
        mesh_shape=mesh_shape,
    )
    m_cpu = run_cpu(cfg)
    m_tpu = run_sharded(cfg)
    assert np.array_equal(m_cpu.per_partition, m_tpu.per_partition)
    assert m_cpu.earliest_ts_s == m_tpu.earliest_ts_s
    assert m_cpu.latest_ts_s == m_tpu.latest_ts_s
    assert m_cpu.smallest_message == m_tpu.smallest_message
    assert m_cpu.largest_message == m_tpu.largest_message
    assert m_cpu.overall_size == m_tpu.overall_size
    assert m_cpu.overall_count == m_tpu.overall_count
    assert m_cpu.alive_keys == m_tpu.alive_keys
    # Sketches merged across shards stay inside their error budget.
    assert m_tpu.distinct_keys_hll == pytest.approx(
        m_cpu.distinct_keys_exact, rel=0.05
    )
    for q_exact, q_sketch in zip(m_cpu.quantiles.values, m_tpu.quantiles.values):
        assert q_sketch == pytest.approx(q_exact, rel=0.011)


def test_mixed_batch_update_splits_by_partition():
    cfg = AnalyzerConfig(num_partitions=7, batch_size=512, mesh_shape=(4, 1))
    be = ShardedTpuBackend(cfg, init_now_s=10**10)
    src = SyntheticSource(SPEC)
    for batch in src.batches(512):
        be.update(batch)  # mixed-partition path
    m = be.finalize()
    assert int(m.overall_count) == 7 * 3_000


def test_cross_chunk_last_writer_wins():
    """A key whose alive-bitmap updates straddle the space-chunk boundary
    must resolve by RECORD order, not by which space shard saw it: the
    device-side ordered application (backends/step.py) is what makes the
    chunked input sharding exact."""
    from kafka_topic_analyzer_tpu.records import RecordBatch

    def batch_of(rows):
        b = RecordBatch.empty(len(rows))
        for i, (h32, value_len) in enumerate(rows):
            b.partition[i] = 0
            b.key_len[i] = 4
            b.value_null[i] = value_len is None
            b.value_len[i] = 0 if value_len is None else value_len
            b.ts_s[i] = 100 + i
            b.key_hash32[i] = h32
            b.key_hash64[i] = h32
            b.valid[i] = True
        return b

    # batch_size 8 over (1, 2) → chunks of 4.  Key A: alive in chunk 0,
    # tombstoned in chunk 1 → dead.  Key B: tombstoned in chunk 0, alive
    # in chunk 1 → alive.  Key C alive twice in chunk 0 → alive.  Key D
    # only in chunk 1, alive → alive.
    rows = [
        (0xA, 10), (0xB, None), (0xC, 5), (0xC, 6),   # chunk 0
        (0xA, None), (0xB, 7), (0xD, 8), (0xD, 9),    # chunk 1
    ]
    cfg = AnalyzerConfig(
        num_partitions=1,
        batch_size=8,
        mesh_shape=(1, 2),
        count_alive_keys=True,
        alive_bitmap_bits=8,
    )
    be = ShardedTpuBackend(cfg, init_now_s=10**10)
    be.update_shards([batch_of(rows)])
    m = be.finalize()
    assert int(m.alive_keys) == 3  # B, C, D alive; A dead

    # Same records through the CPU oracle (sequential replay).
    oracle = CpuExactBackend(
        AnalyzerConfig(
            num_partitions=1, batch_size=8,
            count_alive_keys=True, alive_bitmap_bits=8,
        ),
        init_now_s=10**10,
    )
    oracle.update(batch_of(rows))
    assert int(oracle.finalize().alive_keys) == 3
