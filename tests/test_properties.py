"""Property-based tests (hypothesis): backend parity and codec roundtrips
must hold for *arbitrary* record streams, not just the synthetic generator's
distribution (SURVEY.md §4 backend-contract strategy, adversarial edition)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.records import RecordBatch

P = 3

record = st.tuples(
    st.integers(0, P - 1),                       # partition
    st.one_of(st.none(), st.integers(0, 300)),   # key_len (None = null key)
    st.one_of(st.none(), st.integers(0, 5000)),  # value_len (None = tombstone)
    st.integers(-1, 2**33),                      # ts seconds (incl. epoch edge)
    st.integers(0, 2**32 - 1),                   # key hash32
)


def _batch_from(rows):
    n = len(rows)
    b = RecordBatch.empty(n)
    for i, (p, kl, vl, ts, h32) in enumerate(rows):
        b.partition[i] = p
        b.key_null[i] = kl is None
        b.key_len[i] = 0 if kl is None else kl
        b.value_null[i] = vl is None
        b.value_len[i] = 0 if vl is None else vl
        b.ts_s[i] = ts
        b.key_hash32[i] = h32
        b.key_hash64[i] = h32 * 2654435761 % 2**64
        b.valid[i] = True
    return b


@settings(max_examples=30, deadline=None)
@given(st.lists(record, min_size=1, max_size=200), st.integers(1, 4))
def test_cpu_tpu_parity_arbitrary_streams(rows, nbatches):
    cfg = AnalyzerConfig(
        num_partitions=P, batch_size=64, count_alive_keys=True,
        alive_bitmap_bits=16,
    )
    cpu = CpuExactBackend(cfg, init_now_s=10**10)
    tpu = TpuBackend(cfg, init_now_s=10**10)
    chunks = np.array_split(np.arange(len(rows)), nbatches)
    for chunk in chunks:
        if len(chunk) == 0:
            continue
        batch = _batch_from([rows[i] for i in chunk])
        for lo in range(0, len(batch), 64):
            sub = batch.take(np.arange(lo, min(lo + 64, len(batch))))
            cpu.update(sub)
            tpu.update(sub)
    a, b = cpu.finalize(), tpu.finalize()
    assert np.array_equal(a.per_partition, b.per_partition)
    assert a.earliest_ts_s == b.earliest_ts_s
    assert a.latest_ts_s == b.latest_ts_s
    assert a.smallest_message == b.smallest_message
    assert a.largest_message == b.largest_message
    assert a.alive_keys == b.alive_keys
    assert np.array_equal(a.per_partition_extremes, b.per_partition_extremes)


@settings(max_examples=50, deadline=None)
@given(st.lists(st.integers(-(2**62), 2**62), max_size=30))
def test_varint_roundtrip_property(values):
    w = kc.ByteWriter()
    for v in values:
        w.varint(v)
    r = kc.ByteReader(w.done())
    assert [r.varint() for _ in values] == values


kafka_record = st.tuples(
    st.integers(0, 2**40),                      # ts_ms
    st.one_of(st.none(), st.binary(max_size=40)),
    st.one_of(st.none(), st.binary(max_size=200)),
)


@settings(max_examples=30, deadline=None)
@given(
    st.integers(0, 2**40),                      # base offset
    st.lists(kafka_record, min_size=1, max_size=30),
    st.sampled_from([
        kc.COMPRESSION_NONE, kc.COMPRESSION_GZIP,
        kc.COMPRESSION_SNAPPY, kc.COMPRESSION_LZ4,
    ]),
)
def test_record_batch_roundtrip_property(base, recs, codec):
    rows = [(base + 2 * i, ts, k, v) for i, (ts, k, v) in enumerate(recs)]
    buf = kc.encode_record_batch(rows, codec)
    got = [
        (off, ts, k, v)
        for off, (ts, k, v) in kc.decode_record_batches(buf, verify_crc=True)
    ]
    assert got == rows


@settings(max_examples=200, deadline=None)
@given(st.binary(max_size=400), st.booleans())
def test_record_batch_decoder_total_on_garbage(buf, verify_crc):
    """Feeding arbitrary bytes to the record-batch decoder must either
    yield records or raise KafkaProtocolError — never leak IndexError/
    struct.error/etc.  Fuzzed with verify_crc BOTH ways: random bytes never
    pass CRC32C, so only the False arm reaches the record-body parser."""
    try:
        list(kc.decode_record_batches(buf, verify_crc=verify_crc))
    except kc.KafkaProtocolError:
        pass


@settings(max_examples=150, deadline=None)
@given(
    st.lists(kafka_record, min_size=1, max_size=5),
    st.integers(0, 60),   # mutation position within the record payload
    st.integers(1, 255),  # xor mask
)
def test_record_body_parser_total_on_mutated_batches(recs, mpos, mask):
    """Mutate the *body* of an otherwise valid batch (CRC off) so the
    record/varint parser itself gets fuzzed, not just the header checks."""
    rows = [(i, ts, k, v) for i, (ts, k, v) in enumerate(recs)]
    buf = bytearray(kc.encode_record_batch(rows))
    body_start = 61  # fixed v2 batch header size
    if len(buf) > body_start:
        buf[body_start + mpos % (len(buf) - body_start)] ^= mask
    try:
        list(kc.decode_record_batches(bytes(buf), verify_crc=False))
    except kc.KafkaProtocolError:
        pass


@settings(max_examples=150, deadline=None)
@given(
    # Bare garbage essentially never starts with the framing magics, so the
    # framed code paths must be fuzzed explicitly via prefixes.
    st.sampled_from([b"", b"\x82SNAPPY\x00", b"\x04\x22\x4d\x18"]),
    st.binary(max_size=300),
    st.sampled_from([1, 2, 3]),
)
def test_decompressors_total_on_garbage(prefix, data, codec):
    """Arbitrary bytes through any decompressor: success or ValueError/
    zlib.error — no unbounded allocation, no hangs, no other exceptions."""
    import zlib

    from kafka_topic_analyzer_tpu.io.compression import (
        decompress,
        lz4_decompress_py,
        snappy_decompress_py,
    )

    payload = prefix + data
    try:
        decompress(codec, payload)
    except (ValueError, zlib.error):
        pass
    # The pure-Python decoders must be total on their own, not only behind
    # decompress()'s pre-validation.
    for py_decoder in (snappy_decompress_py, lz4_decompress_py):
        try:
            py_decoder(payload)
        except ValueError:
            pass


@settings(max_examples=150, deadline=None)
@given(st.binary(max_size=200))
def test_response_decoders_total_on_garbage(buf):
    """Broker responses are untrusted input: every response decoder must
    raise only KafkaProtocolError on garbage framing (the fetch loop's
    error handling depends on it)."""
    for decoder in (
        kc.decode_metadata_response,
        kc.decode_list_offsets_response,
        kc.decode_fetch_response,
        kc.decode_api_versions_response,
        kc.decode_offset_for_leader_epoch_response,
    ):
        # Classic AND flexible wire formats: both read untrusted bytes.
        for version in (1, 4, 7, 12):
            try:
                decoder(kc.ByteReader(buf), version)
            except kc.KafkaProtocolError:
                # The ONLY acceptable rejection. AssertionError is a
                # decoder bug (and vanishes under python -O) — the
                # single-topic request invariants raise KafkaProtocolError
                # since ADVICE r2.
                pass
            except MemoryError:
                raise AssertionError("decoder allocated unbounded memory")


@settings(max_examples=200, deadline=None)
@given(
    st.lists(st.integers(0, 2**63 - 1), max_size=8),
    st.lists(st.one_of(st.none(), st.text(max_size=40)), max_size=5),
    st.lists(st.one_of(st.none(), st.binary(max_size=64)), max_size=5),
)
def test_flexible_primitives_roundtrip_property(uints, strings, blobs):
    """KIP-482 compact primitives: write→read is identity for arbitrary
    values (uvarint boundaries, empty vs null strings/bytes)."""
    w = kc.ByteWriter()
    for v in uints:
        w.uvarint(v)
    for s in strings:
        w.compact_string(s)
    for b in blobs:
        w.compact_bytes(b)
    r = kc.ByteReader(w.done())
    assert [r.uvarint() for _ in uints] == uints
    assert [r.compact_string() for _ in strings] == strings
    assert [r.compact_bytes() for _ in blobs] == blobs
    assert r.remaining() == 0


@settings(max_examples=150, deadline=None)
@given(
    st.lists(
        st.tuples(st.integers(0, 2**31 - 1), st.binary(max_size=32)),
        max_size=6,
    ),
    st.binary(max_size=32),
)
def test_skip_tags_skips_arbitrary_tag_buffers(tag_fields, tail):
    """Unknown tagged fields of any shape are skipped exactly (forward
    compatibility contract), leaving the reader at the following field."""
    w = kc.ByteWriter()
    w.uvarint(len(tag_fields))
    for tag, data in tag_fields:
        w.uvarint(tag).uvarint(len(data)).raw(data)
    w.raw(tail)
    r = kc.ByteReader(w.done())
    r.skip_tags()
    assert bytes(r._take(r.remaining())) == tail


def test_invalid_utf8_string_is_protocol_error():
    """Regression: a broker host string with invalid UTF-8 must surface as
    KafkaProtocolError, not UnicodeDecodeError (found by a directed probe
    the random fuzz missed)."""
    import pytest

    w = kc.ByteWriter()
    w.i32(1).i32(0)            # one broker, node_id 0
    w.i16(2).raw(b"\xff\xfe")  # host: invalid UTF-8
    w.i32(9092).string(None)   # port, rack
    w.i32(0).i32(0)            # controller, topics
    with pytest.raises(kc.KafkaProtocolError, match="UTF-8"):
        kc.decode_metadata_response(kc.ByteReader(w.done()))


@settings(max_examples=30, deadline=None)
@given(st.lists(
    st.tuples(st.integers(0, 255), st.booleans(), st.booleans()),
    min_size=1, max_size=300,
))
def test_dedupe_matches_sequential_replay(updates):
    """Host dedupe (numpy + native) vs a literal insert/remove replay of
    src/metric.rs:273-280."""
    from kafka_topic_analyzer_tpu.packing import dedupe_slots_numpy

    h32 = np.array([u[0] for u in updates], dtype=np.uint32)
    active = np.array([u[1] for u in updates], dtype=bool)
    alive = np.array([u[2] for u in updates], dtype=bool)
    replay = {}
    for h, act, al in updates:
        if act:
            replay[h & 0xFF] = al
    slots, flags = dedupe_slots_numpy(h32, active, alive, bits=8)
    assert dict(zip(slots.tolist(), flags.tolist())) == {
        k: int(v) for k, v in replay.items()
    }


@settings(max_examples=200, deadline=None)
@given(
    st.lists(kafka_record, min_size=0, max_size=4),
    st.integers(0, 200),  # mutation position
    st.integers(0, 255),  # xor mask (0 = no mutation)
    st.integers(0, 80),   # tail truncation
    st.booleans(),        # insert a control batch
)
def test_native_record_set_walk_total_and_prefix_consistent(
    recs, mpos, mask, cut, with_control
):
    """The native record-set walker (kta_scan/kta_decode_record_set) is new
    untrusted-input surface: arbitrary mutations/truncations must never
    crash, over-read, or disagree between scan and decode — and whatever
    prefix it accepts must match the reference Python frame iterator."""
    from kafka_topic_analyzer_tpu.io.native import (
        decode_record_set_native,
        native_available,
        scan_record_set_native,
    )

    if not native_available():
        import pytest

        pytest.skip("native shim unavailable")
    rows = [(i, ts, k, v) for i, (ts, k, v) in enumerate(recs)]
    buf = bytearray()
    if rows:
        buf += kc.encode_record_batch(rows)
    if with_control:
        base = len(rows)
        buf += kc.encode_control_batch(base, 1000)
    if mask and buf:
        buf[mpos % len(buf)] ^= mask
    if cut:
        buf = buf[: max(0, len(buf) - cut)]
    data = bytes(buf)

    n, consumed, covered = scan_record_set_native(data)
    soa, used, covered2 = decode_record_set_native(data)
    # scan and decode must agree on the accepted prefix...
    assert 0 <= consumed <= len(data)
    if used:  # decode returning used=0 means "malformed inside prefix"
        assert (used, covered2) == (consumed, covered)
        assert len(soa["offsets"]) == n
        # ...and the accepted-and-decoded prefix must decode identically
        # via the Python reference path (same record count and offsets).
        # Only under `used`: the header-only scan can accept a prefix
        # whose record BODIES are mutated — the record-level decoders
        # (native and Python alike) are the ones that reject those.
        py_offsets = [
            off
            for f in kc.iter_batch_frames(data[:consumed])
            for off, _ in kc.decode_frame_records(f)
        ]
        assert soa["offsets"].tolist() == py_offsets
