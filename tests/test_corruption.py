"""Corrupt-data resilience: poison-frame isolation, quarantine, salvage.

Three layers of proof:

1. Codec units: the `CorruptFrameError` taxonomy classifies every damage
   class; `salvage_batch_frames` skips exactly the poisoned frame and
   keeps decoding the rest of the record set; a *negative* batch length
   mid-buffer classifies instead of silently dropping the rest of the
   fetch response (the old ``partial trailing batch`` confusion).
2. Chaos end-to-end: a `CorruptionInjector`-poisoned FakeBroker topic
   scanned under ``--on-corruption=skip``/``quarantine`` completes with
   metrics BYTE-IDENTICAL to a clean scan of the same topic minus exactly
   the poisoned frames' records; the CORRUPT report block,
   ``kta_corrupt_*`` counters, quarantine spool round-trip, EXIT_CORRUPT,
   and ``--resume`` idempotence (no re-scan, no double-quarantine) all
   hold.  Default ``fail`` still aborts.
3. Fuzz: ≥200 seeded random mutations (byte flips, truncations,
   length-field rewrites) over ``encode_record_batch`` output never hang,
   never raise an unclassified exception, and never let salvage invent
   records — plus hypothesis variants when available.
"""

import json
import os
import struct

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.config import AnalyzerConfig, CorruptionConfig
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.quarantine import QuarantineStore
from kafka_topic_analyzer_tpu.obs.registry import default_registry

from fake_broker import CorruptionInjector, FakeBroker

pytestmark = pytest.mark.chaos

TOPIC = "corrupt.topic"

FAST_RETRY = {
    "retry.backoff.ms": "5",
    "reconnect.backoff.max.ms": "40",
}


@pytest.fixture(autouse=True)
def _reset_registry():
    default_registry().reset()
    yield
    default_registry().reset()


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 37}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# 1. codec units: taxonomy + salvage + the negative-length bugfix


def _three_frames():
    recs = [(i, 1000 + i, f"k{i}".encode(), f"v{i}".encode()) for i in range(9)]
    return (
        kc.encode_record_batch(recs[:3]),
        kc.encode_record_batch(recs[3:6]),
        kc.encode_record_batch(recs[6:]),
    )


def _drain(items):
    good, spans = [], []
    for item in items:
        if isinstance(item, kc.CorruptSpan):
            spans.append(item)
        else:
            good.extend(off for off, _ in kc.decode_frame_records(item))
    return good, spans


def test_crc_mismatch_classifies_with_context():
    f1, f2, f3 = _three_frames()
    buf = bytearray(f1 + f2 + f3)
    buf[len(f1) + len(f2) - 1] ^= 0xFF  # last payload byte of frame 2
    with pytest.raises(kc.CrcMismatchError) as ei:
        list(kc.iter_batch_frames(bytes(buf), verify_crc=True))
    e = ei.value
    assert e.kind == "crc-mismatch"
    assert e.base_offset == 3
    assert e.span == (len(f1), len(f1) + len(f2))
    assert e.claimed_end == 6
    assert e.crc_expected != e.crc_actual
    assert isinstance(e, kc.KafkaProtocolError)  # existing handlers still fire


def test_salvage_skips_exactly_the_poisoned_frame():
    f1, f2, f3 = _three_frames()
    buf = bytearray(f1 + f2 + f3)
    buf[len(f1) + len(f2) - 1] ^= 0xFF
    good, spans = _drain(kc.salvage_batch_frames(bytes(buf), verify_crc=True))
    assert good == [0, 1, 2, 6, 7, 8]  # frames after the poison still decode
    assert len(spans) == 1
    s = spans[0]
    assert (s.start, s.end) == (len(f1), len(f1) + len(f2))
    assert s.error.kind == "crc-mismatch"
    assert s.skip_offset(3) == 6  # resume exactly past the poisoned range


def test_negative_batch_length_mid_buffer_classifies():
    """The satellite bugfix: a negative batch_length used to be treated as
    a partial trailing batch, silently ending iteration and dropping every
    frame after it in the fetch response."""
    f1, f2, f3 = _three_frames()
    buf = bytearray(f1 + f2 + f3)
    struct.pack_into(">i", buf, len(f1) + 8, -5)
    with pytest.raises(kc.MalformedHeaderError, match="non-positive"):
        list(kc.iter_batch_frames(bytes(buf)))
    good, spans = _drain(kc.salvage_batch_frames(bytes(buf), verify_crc=True))
    assert good == [0, 1, 2, 6, 7, 8]  # resync recovered the third frame
    assert spans[0].error.kind == "malformed-header"
    assert spans[0].resume_offset == 6


def test_undersized_batch_length_classifies_not_overruns():
    """A positive batch_length too small to hold the v2 header must
    classify BEFORE parsing: at the buffer tail the header reader would
    otherwise overrun with an unclassified error; mid-buffer it would
    silently read the next frame's bytes as header fields."""
    f1, f2, f3 = _three_frames()
    # Tail: lone frame claiming a 20-byte batch.
    tail = bytearray(f1)
    struct.pack_into(">i", tail, 8, 20)
    with pytest.raises(kc.MalformedHeaderError, match="below the magic-2"):
        list(kc.iter_batch_frames(bytes(tail)))
    good, spans = _drain(kc.salvage_batch_frames(bytes(tail), verify_crc=True))
    assert good == [] and spans[0].error.kind == "malformed-header"
    # Mid-buffer: the frames after the mangled length must salvage.
    mid = bytearray(f1 + f2 + f3)
    struct.pack_into(">i", mid, len(f1) + 8, 20)
    good, spans = _drain(kc.salvage_batch_frames(bytes(mid), verify_crc=True))
    assert good == [0, 1, 2, 6, 7, 8]
    assert spans[0].error.kind == "malformed-header"


def test_source_wrappers_forward_corruption_surface():
    """TeeSource (--dump-segments) and MultiTopicSource (fan-in) must
    forward the corruption accounting the engine discovers by hasattr —
    otherwise a corrupt scan through them exits 0 with silent undercounts."""
    from kafka_topic_analyzer_tpu.io.multi import MultiTopicSource
    from kafka_topic_analyzer_tpu.io.segfile import TeeSource
    from kafka_topic_analyzer_tpu.io.source import RecordSource

    class Stub(RecordSource):
        def __init__(self, parts, spans):
            self._parts = parts
            self._spans = spans
            self.seeded = None

        def partitions(self):
            return self._parts

        def watermarks(self):
            return ({p: 0 for p in self._parts}, {p: 10 for p in self._parts})

        def batches(self, batch_size, partitions=None, start_at=None):
            return iter(())

        def corruption_spans(self):
            return list(self._spans)

        def corruption_stats(self):
            out = {}
            for s in self._spans:
                d = out.setdefault(
                    s["partition"],
                    {"frames": 0, "records": 0, "bytes": 0,
                     "quarantined": 0, "kinds": {}, "spans": []},
                )
                d["frames"] += 1
                d["spans"].append(dict(s))
            return out

        def seed_corrupt_spans(self, spans):
            self.seeded = list(spans)

    span = {"partition": 1, "anchor": 4, "skip_to": 6,
            "kind": "crc-mismatch", "frames": 1, "records": 2, "bytes": 9}
    inner = Stub([0, 1], [span])

    class W:
        def append(self, b): pass
        def close(self): pass
        def set_base_offsets(self, o): pass

    tee = TeeSource(inner, W())
    assert tee.corruption_stats() == inner.corruption_stats()
    assert tee.corruption_spans() == [span]
    tee.seed_corrupt_spans([span])
    assert inner.seeded == [span]

    # Fan-in: topic b's partitions follow topic a's in dense row space,
    # so b/partition-1 is row 3; spans round-trip through the remap.
    a, b = Stub([0, 1], []), Stub([0, 1], [span])
    multi = MultiTopicSource([("a", a), ("b", b)])
    stats = multi.corruption_stats()
    assert set(stats) == {3} and stats[3]["topic"] == "b"
    spans_out = multi.corruption_spans()
    assert spans_out[0]["partition"] == 3
    assert spans_out[0]["topic_partition"] == 1
    multi.seed_corrupt_spans(spans_out)
    assert a.seeded is None or a.seeded == []
    assert b.seeded == [dict(spans_out[0], partition=1)]


def test_skip_prefers_validated_resume_over_corrupt_claimed_end():
    """A bit flip in last_offset_delta makes the corrupt frame's own
    claimed_end garbage-high; the skip bound must prefer the NEXT salvaged
    frame's validated base offset or the rest of the partition would be
    silently swallowed."""
    f1, f2, f3 = _three_frames()
    buf = bytearray(f1 + f2 + f3)
    # last_offset_delta is the i32 at frame byte 23 (after leader_epoch,
    # magic, crc, attributes) — inside the CRC-covered region.
    struct.pack_into(">i", buf, len(f1) + 23, 1 << 29)
    good, spans = _drain(kc.salvage_batch_frames(bytes(buf), verify_crc=True))
    assert good == [0, 1, 2, 6, 7, 8]
    s = spans[0]
    assert s.error.kind == "crc-mismatch"
    assert s.claimed_end == 3 + (1 << 29) + 1  # the poisoned field
    assert s.resume_offset == 6                # the validated boundary
    assert s.skip_offset(3) == 6               # ...which must win


def test_oscillating_corruption_kind_is_bounded():
    """A link that corrupts every re-fetch DIFFERENTLY at the same anchor
    must not cycle suspect re-fetches forever: after _MAX_SUSPECT_ROUNDS
    the verdict is forced with the latest classification."""
    import threading

    from kafka_topic_analyzer_tpu.io import kafka_wire as kw

    src = KafkaWireSource.__new__(KafkaWireSource)
    src.topic = "t"
    src.corruption = CorruptionConfig(policy="skip")
    src._quarantine = None
    src._corrupt_spans = {}
    src._corrupt_suspects = {}
    src._corrupt_lock = threading.Lock()
    kinds = [kc.TruncatedFrameError, kc.CrcMismatchError,
             kc.MalformedHeaderError, kc.BadCompressionError,
             kc.TruncatedFrameError, kc.CrcMismatchError]
    outcomes = []
    for cls in kinds:
        out = src._note_corrupt(
            0, 100, cls("x", base_offset=100), 150, -1, 50, b"raw"
        )
        outcomes.append(out)
        if out is not None:
            break
    # Re-fetched at most the bound, then forced the skip verdict.
    assert outcomes[-1] == 150
    assert len(outcomes) <= kw._MAX_SUSPECT_ROUNDS + 1
    assert (0, 100) in src._corrupt_spans


def test_explicit_config_wins_over_discarded_overrides():
    """--on-corruption=skip plus a stray --librdkafka quarantine.dir must
    not raise the quarantine-dir validation error for a config that is
    discarded anyway (the explicit flag wins; the override is ignored)."""
    records = {0: _mk_records(0, 60)}
    with FakeBroker(TOPIC, records, max_records_per_fetch=30) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC,
            overrides=dict(FAST_RETRY, **{"quarantine.dir": "/spool"}),
            corruption=CorruptionConfig(policy="skip"),
        )
        assert src.corruption.policy == "skip"
        assert src.corruption.quarantine_dir is None
        src.close()


def test_genuine_tail_truncation_still_tolerated():
    """A partial TRAILING batch is the broker's max_bytes cut, not
    corruption: iteration (and salvage) end cleanly, no span."""
    f1, f2, _ = _three_frames()
    buf = f1 + f2[: len(f2) // 2]
    frames = list(kc.iter_batch_frames(buf, verify_crc=True))
    assert [f.base_offset for f in frames] == [0]
    good, spans = _drain(kc.salvage_batch_frames(buf, verify_crc=True))
    assert good == [0, 1, 2] and spans == []


def test_bad_compression_classifies():
    recs = [(0, 1000, b"k", b"v"), (1, 1001, b"k2", b"v2")]
    buf = bytearray(kc.encode_record_batch(recs, kc.COMPRESSION_GZIP))
    # Scramble the compressed payload but repair the CRC: only the codec
    # stream is damaged, which must classify as bad-compression (not crc).
    for i in range(61, len(buf)):
        buf[i] = (buf[i] * 31 + 7) & 0xFF
    buf[17:21] = struct.pack(">I", kc._crc32c(bytes(buf[21:])))
    with pytest.raises(kc.BadCompressionError):
        list(kc.iter_batch_frames(bytes(buf), verify_crc=True))


def test_record_body_corruption_classifies():
    """Payload damage below the CRC's reach (verify off) surfaces in the
    record parser as a classified error carrying the frame span."""
    recs = [(i, 1000, b"key", b"value") for i in range(4)]
    buf = bytearray(kc.encode_record_batch(recs))
    buf[61] = 0x7E  # first record's length varint now claims 63 bytes
    frames = list(kc.iter_batch_frames(bytes(buf), verify_crc=False))
    with pytest.raises(kc.CorruptFrameError) as ei:
        for f in frames:
            list(kc.decode_frame_records(f))
    assert ei.value.kind in ("truncated", "malformed-header")
    assert ei.value.span == (0, len(buf))


def test_legacy_messageset_crc_classifies_and_salvages():
    recs = [(i, 1_600_000_000_000 + i, f"k{i}".encode(), b"v") for i in range(4)]
    entries = [
        kc.encode_message_set(recs[i : i + 1], magic=1) for i in range(4)
    ]
    buf = bytearray(b"".join(entries))
    pos = len(entries[0]) + len(entries[1])
    buf[pos + 20] ^= 0xFF  # inside entry 2's body -> CRC mismatch
    with pytest.raises(kc.CrcMismatchError):
        list(kc.iter_batch_frames(bytes(buf), verify_crc=True))
    good, spans = _drain(kc.salvage_batch_frames(bytes(buf), verify_crc=True))
    assert good == [0, 1, 3]
    assert spans[0].error.kind == "crc-mismatch"


def test_quarantine_store_round_trip(tmp_path):
    store = QuarantineStore(str(tmp_path))
    raw = b"\xde\xad\xbe\xef" * 10
    sidecar = store.spool(
        topic="t/../x", partition=3, anchor=17, raw=raw,
        classification="crc-mismatch", base_offset=17, offset_start=17,
        offset_end=20, crc_expected=1, crc_actual=2, error="boom",
    )
    assert sidecar is not None and os.path.dirname(sidecar) == str(tmp_path)
    meta, loaded = QuarantineStore.load(sidecar)
    assert loaded == raw
    assert meta["classification"] == "crc-mismatch"
    assert meta["partition"] == 3 and meta["anchor"] == 17
    assert meta["offset_end"] == 20
    # Idempotent: the same span never spools twice (resume contract).
    assert store.spool(
        topic="t/../x", partition=3, anchor=17, raw=raw,
        classification="crc-mismatch",
    ) is None
    assert len(store.entries()) == 1


# ---------------------------------------------------------------------------
# 2. chaos end-to-end through the wire source + engine + CLI

#: 6 chunks of 50 records per partition; poison plan: 3 frames, 2 partitions.
N_REC = 300
CHUNK = 50
POISON = {0: [2, 4], 1: [1]}  # partition -> poisoned chunk indices


def _poisoned_broker(**kwargs):
    inj = (
        CorruptionInjector()
        .flip_byte(0, chunk=2, offset=-1)       # crc-mismatch
        .garbage_compression(0, chunk=4)        # bad-compression
        .flip_byte(1, chunk=1, offset=-3)       # crc-mismatch
    )
    records = {p: _mk_records(p, N_REC) for p in range(2)}
    return FakeBroker(
        TOPIC, records, max_records_per_fetch=CHUNK, corruption=inj,
        honor_partition_max_bytes=True, **kwargs,
    ), inj


def _clean_minus_poison_doc():
    """Referee: a clean scan of the same topic with the poisoned chunks'
    records REMOVED (offsets/watermarks preserved) — what a corrupt scan
    under skip/quarantine must reproduce byte-for-byte."""
    records = {
        p: [
            r for i, r in enumerate(_mk_records(p, N_REC))
            if i // CHUNK not in POISON.get(p, [])
        ]
        for p in range(2)
    }
    with FakeBroker(
        TOPIC, records,
        max_records_per_fetch=CHUNK,
        start_offsets={0: 0, 1: 0},
        end_offsets={0: N_REC, 1: N_REC},
        honor_partition_max_bytes=True,
    ) as broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC,
            overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
        )
        result = _scan(src)
    assert not result.degraded_partitions and not result.corrupt_partitions
    return _doc(result)


def _scan(source, batch_size=128):
    cfg = AnalyzerConfig(
        num_partitions=2, batch_size=batch_size,
        count_alive_keys=True, alive_bitmap_bits=16,
    )
    backend = CpuExactBackend(cfg, init_now_s=10**10)
    result = run_scan(TOPIC, source, backend, batch_size)
    source.close()
    return result


def _doc(result):
    return result.metrics.to_dict(result.start_offsets, result.end_offsets)


def _corrupt_source(port, policy, qdir=None):
    return KafkaWireSource(
        f"127.0.0.1:{port}", TOPIC,
        overrides=dict(FAST_RETRY, **{"check.crcs": "true"}),
        corruption=CorruptionConfig(policy=policy, quarantine_dir=qdir),
    )


def test_default_fail_policy_aborts_like_today():
    broker, _ = _poisoned_broker()
    with broker:
        src = _corrupt_source(broker.port, "fail")
        with pytest.raises(kc.CorruptFrameError):
            _scan(src)


def test_skip_policy_completes_with_exact_metrics():
    baseline = _clean_minus_poison_doc()
    broker, inj = _poisoned_broker()
    with broker:
        src = _corrupt_source(broker.port, "skip")
        result = _scan(src)
    assert not result.degraded_partitions
    assert _doc(result) == baseline  # byte-identical minus the poison
    corrupt = result.corrupt_partitions
    assert set(corrupt) == {0, 1}
    assert sum(d["frames"] for d in corrupt.values()) == inj.poisoned_frames
    assert corrupt[0]["frames"] == 2 and corrupt[1]["frames"] == 1
    assert corrupt[0]["records"] == 2 * CHUNK and corrupt[1]["records"] == CHUNK
    kinds = {}
    for d in corrupt.values():
        for k, n in d["kinds"].items():
            kinds[k] = kinds.get(k, 0) + n
    assert kinds == {"crc-mismatch": 2, "bad-compression": 1}
    # Registry counters agree with the injected plan.
    snap = default_registry().snapshot()
    frames_total = sum(
        s["value"] for s in snap["kta_corrupt_frames_total"]["samples"]
    )
    assert frames_total == inj.poisoned_frames
    # Each poisoned span was re-fetched once before the verdict.
    refetches = sum(
        s["value"] for s in snap["kta_corrupt_refetches_total"]["samples"]
    )
    assert refetches == inj.poisoned_frames


def test_quarantine_policy_spools_evidence(tmp_path):
    baseline = _clean_minus_poison_doc()
    qdir = str(tmp_path / "quarantine")
    broker, inj = _poisoned_broker()
    with broker:
        src = _corrupt_source(broker.port, "quarantine", qdir)
        result = _scan(src)
    assert _doc(result) == baseline
    store = QuarantineStore(qdir)
    entries = store.entries()
    assert len(entries) == inj.poisoned_frames
    seen = set()
    for sidecar in entries:
        meta, raw = QuarantineStore.load(sidecar)  # sha256-verified
        assert meta["topic"] == TOPIC
        assert meta["classification"] in kc.CORRUPTION_KINDS
        assert len(raw) == meta["length"] > 0
        seen.add((meta["partition"], meta["anchor"]))
    # One spool per poisoned chunk, at the chunk's first offset.
    assert seen == {
        (p, ci * CHUNK) for p, cis in POISON.items() for ci in cis
    }
    assert all(d["quarantined"] for d in result.corrupt_partitions.values())


def test_cli_end_to_end_exit_corrupt_and_report(tmp_path, capsys):
    from kafka_topic_analyzer_tpu import cli

    qdir = str(tmp_path / "q")
    broker, inj = _poisoned_broker()
    with broker:
        rc = cli.main([
            "-t", TOPIC, "-b", f"127.0.0.1:{broker.port}",
            "--quiet", "--check-crcs",
            "--on-corruption", "quarantine", "--quarantine-dir", qdir,
            "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
        ])
    assert rc == cli.EXIT_CORRUPT
    out = capsys.readouterr().out
    assert "CORRUPT" in out
    assert f"{inj.poisoned_frames} unreadable frame(s)" in out
    assert "partition 0:" in out and "partition 1:" in out
    assert len(QuarantineStore(qdir).entries()) == inj.poisoned_frames


def test_cli_json_carries_corrupt_block(capsys):
    from kafka_topic_analyzer_tpu import cli

    broker, inj = _poisoned_broker()
    with broker:
        rc = cli.main([
            "-t", TOPIC, "-b", f"127.0.0.1:{broker.port}",
            "--quiet", "--check-crcs", "--json",
            "--on-corruption", "skip",
            "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
        ])
    assert rc == cli.EXIT_CORRUPT
    doc = json.loads(capsys.readouterr().out)
    got = doc["corrupt_partitions"]
    assert set(got) == {"0", "1"}
    assert sum(d["frames"] for d in got.values()) == inj.poisoned_frames
    # The telemetry block carries the kta_corrupt_* catalog too.
    assert "kta_corrupt_frames_total" in doc["telemetry"]


def test_cli_flag_validation():
    from kafka_topic_analyzer_tpu import cli

    # quarantine without a directory
    rc = cli.main([
        "-t", "t", "-b", "127.0.0.1:1", "--on-corruption", "quarantine",
    ])
    assert rc == 1
    # quarantine dir without the policy
    rc = cli.main([
        "-t", "t", "-b", "127.0.0.1:1", "--quarantine-dir", "/tmp/x",
    ])
    assert rc == 1
    # corruption policy needs the wire source
    rc = cli.main([
        "-t", "t", "--source", "synthetic", "--synthetic", "messages=10",
        "--on-corruption", "skip",
    ])
    assert rc == 1


def test_librdkafka_override_path_sets_policy():
    """on.corruption/quarantine.dir are also reachable through the usual
    --librdkafka overrides table (the CLI flags win when both are given)."""
    broker, inj = _poisoned_broker()
    with broker:
        src = KafkaWireSource(
            f"127.0.0.1:{broker.port}", TOPIC,
            overrides=dict(
                FAST_RETRY,
                **{"check.crcs": "true", "on.corruption": "skip"},
            ),
        )
        result = _scan(src)
    assert sum(
        d["frames"] for d in result.corrupt_partitions.values()
    ) == inj.poisoned_frames


def test_resume_neither_rescans_nor_double_quarantines(tmp_path):
    """Tail poison: the last chunk of partition 1 is corrupt, so the
    engine's offset tracker (which only sees records) stops short of the
    skipped span.  A --resume must re-seed the span from the snapshot:
    same totals, no new quarantine files, no double counting."""
    from kafka_topic_analyzer_tpu import cli

    qdir = str(tmp_path / "q")
    snapdir = str(tmp_path / "snap")
    inj = CorruptionInjector().flip_byte(1, chunk=5, offset=-1)
    records = {p: _mk_records(p, N_REC) for p in range(2)}
    argv = [
        "-t", TOPIC, "--quiet", "--check-crcs", "--backend", "tpu",
        "--on-corruption", "quarantine", "--quarantine-dir", qdir,
        "--snapshot-dir", snapdir, "--resume",
        "--librdkafka", "retry.backoff.ms=5,reconnect.backoff.max.ms=40",
    ]
    with FakeBroker(
        TOPIC, records, max_records_per_fetch=CHUNK, corruption=inj,
        honor_partition_max_bytes=True,
    ) as broker:
        rc1 = cli.main(argv + ["-b", f"127.0.0.1:{broker.port}"])
        assert rc1 == cli.EXIT_CORRUPT
        entries_after_first = QuarantineStore(qdir).entries()
        assert len(entries_after_first) == 1
        snap = os.path.join(snapdir, "scan_snapshot.npz")
        with np.load(snap, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
        assert len(meta["corrupt_spans"]) == 1
        assert meta["corrupt_spans"][0]["partition"] == 1
        fetches_before = broker.fetch_count
        rc2 = cli.main(argv + ["-b", f"127.0.0.1:{broker.port}"])
    assert rc2 == cli.EXIT_CORRUPT  # still reported (seeded), still exit 4
    assert QuarantineStore(qdir).entries() == entries_after_first
    # The resumed run re-walked at most the seeded span's neighborhood —
    # nowhere near the ~a-dozen-plus fetch rounds of a full rescan.
    assert broker.fetch_count - fetches_before <= 6


# ---------------------------------------------------------------------------
# 3. fuzz: classified-or-silent over ≥200 seeded mutations, salvage total

pytestmark_fuzz = pytest.mark.fuzz


def _fuzz_record_set(rng):
    recs = [
        (
            i,
            1000 + i,
            bytes(rng.integers(0, 256, rng.integers(0, 8), dtype=np.uint8)),
            bytes(rng.integers(0, 256, rng.integers(0, 12), dtype=np.uint8)),
        )
        for i in range(int(rng.integers(1, 6)))
    ]
    codec = int(rng.choice([0, 0, 1]))  # mostly uncompressed, some gzip
    return kc.encode_record_batch(recs, codec), len(recs)


def _mutate(buf, rng):
    b = bytearray(buf)
    mode = int(rng.integers(0, 3))
    if mode == 0 and len(b):  # single-byte flip
        b[int(rng.integers(0, len(b)))] ^= int(rng.integers(1, 256))
    elif mode == 1 and len(b) > 1:  # truncation
        del b[int(rng.integers(1, len(b))):]
    else:  # length-field rewrite (includes negatives)
        struct.pack_into(
            ">i", b, 8, int(rng.integers(-(1 << 31), 1 << 31))
        )
    return bytes(b)


@pytest.mark.fuzz
@pytest.mark.parametrize("verify_crc", [True, False])
def test_fuzz_mutations_classify_and_never_miscount(verify_crc):
    rng = np.random.default_rng(20260802 if verify_crc else 20260803)
    classified = 0
    for trial in range(220):
        sets = []
        total = 0
        for _ in range(int(rng.integers(1, 4))):
            s, n = _fuzz_record_set(rng)
            sets.append(s)
            total += n
        buf = _mutate(b"".join(sets), rng)
        # fail mode: records or a classified error, nothing else.
        try:
            list(kc.decode_record_batches(buf, verify_crc=verify_crc))
        except kc.CorruptFrameError:
            classified += 1
        # salvage mode: must terminate, raise nothing from the frame walk,
        # and never yield more records than were encoded (with CRC on, a
        # salvaged frame is either untouched or astronomically unlucky).
        salvaged = 0
        for item in kc.salvage_batch_frames(buf, verify_crc=verify_crc):
            if isinstance(item, kc.CorruptSpan):
                assert item.error.kind in kc.CORRUPTION_KINDS
                assert item.end > item.start or item.end == len(buf)
                continue
            try:
                salvaged += sum(1 for _ in kc.decode_frame_records(item))
            except kc.CorruptFrameError:
                pass  # record-body damage: classified, handled by policy
        if verify_crc:
            assert salvaged <= total
    assert classified > 20  # the mutations genuinely exercised the taxonomy


def test_fuzz_hypothesis_single_byte_flips():
    hyp = pytest.importorskip("hypothesis")
    st = pytest.importorskip("hypothesis.strategies")

    recs = [(i, 1000 + i, f"key{i}".encode(), bytes(range(i, i + 16)))
            for i in range(6)]
    base = (
        kc.encode_record_batch(recs[:3])
        + kc.encode_record_batch(recs[3:], kc.COMPRESSION_GZIP)
    )

    @hyp.settings(max_examples=120, deadline=None)
    @hyp.given(st.integers(0, len(base) - 1), st.integers(1, 255))
    def run(pos, mask):
        b = bytearray(base)
        b[pos] ^= mask
        try:
            list(kc.decode_record_batches(bytes(b), verify_crc=True))
        except kc.CorruptFrameError:
            pass
        got = []
        for item in kc.salvage_batch_frames(bytes(b), verify_crc=True):
            if isinstance(item, kc.CorruptSpan):
                assert item.error.kind in kc.CORRUPTION_KINDS
            else:
                got.extend(off for off, _ in kc.decode_frame_records(item))
        assert len(got) <= len(recs)
        assert all(0 <= off < len(recs) for off in got)

    run()
