"""Service health layer (ISSUE 15): disk-backed telemetry history,
trend doctor, and the SLO alert engine.

Five layers of coverage:

- alert-rule units: clock-injected state machine — fire, for-duration,
  resolve hysteresis, flap suppression — with the transitions counter,
  firing gauge, and typed events asserted per transition;
- history store: round-trip, downsample-tier exactness (cum=last,
  inst=mean), SIGTERM→restart series continuity (epoch bump, reset-aware
  deltas, pre-restart window served), byte-budget retention (RRD: coarse
  tiers keep the long view), truncated-tail tolerance;
- HTTP surfaces: /healthz routing (404 without an engine, 503
  pre-first-eval, 200 healthy, 503 with the firing-rule JSON) and
  /history (404 without a store, windowed queries, bad params);
- service integration: a scripted lag-divergence fault flips /healthz to
  503 within one poll and heals back to 200 after resolve hysteresis; a
  killed FakeBroker raises the watermark-refresh-outage alert;
- byte-identity: scans with recorder + history + alert evaluation all ON
  produce metrics documents identical to the stack OFF (solo wire,
  follow, and fleet) — the recorder's read-only discipline carries over.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
from kafka_topic_analyzer_tpu.backends.tpu import TpuBackend
from kafka_topic_analyzer_tpu.config import (
    AnalyzerConfig,
    DispatchConfig,
    FollowConfig,
    HealthConfig,
)
from kafka_topic_analyzer_tpu.engine import run_scan
from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource
from kafka_topic_analyzer_tpu.io.synthetic import SyntheticSource, SyntheticSpec
from kafka_topic_analyzer_tpu.obs import doctor, events as obs_events
from kafka_topic_analyzer_tpu.obs import flight as obs_flight
from kafka_topic_analyzer_tpu.obs import health as obs_health
from kafka_topic_analyzer_tpu.obs import history as obs_history
from kafka_topic_analyzer_tpu.obs import metrics as obs_metrics
from kafka_topic_analyzer_tpu.obs.flight import FlightRecorder
from kafka_topic_analyzer_tpu.obs.health import (
    FIRING,
    OK,
    PENDING,
    RESOLVING,
    AlertRule,
    HealthEngine,
    built_in_rules,
)
from kafka_topic_analyzer_tpu.obs.history import (
    HistoryStore,
    track_delta,
    track_rate,
)
from kafka_topic_analyzer_tpu.obs.registry import default_registry
from kafka_topic_analyzer_tpu.serve.follow import FollowService

from fake_broker import FakeBroker

pytestmark = pytest.mark.health


@pytest.fixture(autouse=True)
def _reset():
    default_registry().reset()
    yield
    default_registry().reset()
    obs_health.set_active(None)
    obs_history.set_active(None)
    obs_flight.set_active(None)


@pytest.fixture()
def event_log():
    events = []
    sink = lambda etype, fields: events.append((etype, fields))  # noqa: E731
    obs_events.add_sink(sink)
    yield events
    obs_events.remove_sink(sink)


# ---------------------------------------------------------------------------
# alert-rule state machine (clock-injected)


class _Cond:
    """A scriptable rule condition."""

    def __init__(self):
        self.value = None  # evidence dict or None

    def __call__(self, ctx):
        return self.value


def _engine(rule, clock):
    return HealthEngine(
        [rule], cfg=HealthConfig(eval_interval_s=0.001),
        clock=lambda: clock["t"], wall_clock=lambda: 1234.0,
    )


def _transitions(rule, state) -> float:
    return obs_metrics.ALERTS_TRANSITIONS.labels(rule=rule, state=state).value


def _firing(rule) -> float:
    return obs_metrics.ALERTS_FIRING.labels(rule=rule).value


def test_rule_fires_immediately_without_for_duration(event_log):
    cond = _Cond()
    clock = {"t": 0.0}
    eng = _engine(AlertRule("r", "test rule", cond), clock)
    doc = eng.evaluate({})
    assert doc["healthy"] and not doc["firing"]
    cond.value = {"n": 7}
    doc = eng.evaluate({})
    assert not doc["healthy"]
    assert doc["firing"][0]["rule"] == "r"
    assert doc["firing"][0]["evidence"] == {"n": 7}
    assert _transitions("r", FIRING) == 1
    assert _firing("r") == 1
    assert ("alert_firing", {"rule": "r", "state": "firing",
                             "evidence": {"n": 7}}) in event_log


def test_rule_for_duration_and_blip_suppression(event_log):
    cond = _Cond()
    clock = {"t": 0.0}
    eng = _engine(AlertRule("r", "s", cond, for_s=5.0), clock)
    cond.value = {"x": 1}
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == PENDING
    assert eng.doc()["healthy"]  # pending is not yet unhealthy
    # A blip: condition clears before for_s → back to ok, never fires.
    clock["t"] = 2.0
    cond.value = None
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == OK
    assert _transitions("r", FIRING) == 0
    assert not any(e[0] == "alert_firing" for e in event_log)
    assert any(e[0] == "alert_cleared" for e in event_log)
    # Sustained condition: pending at t=3, fires once t >= 3 + 5.
    clock["t"] = 3.0
    cond.value = {"x": 2}
    eng.evaluate({})
    clock["t"] = 7.9
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == PENDING
    clock["t"] = 8.0
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == FIRING
    assert _firing("r") == 1
    assert _transitions("r", PENDING) == 2
    assert _transitions("r", FIRING) == 1


def test_rule_resolve_hysteresis_and_flap_suppression(event_log):
    cond = _Cond()
    clock = {"t": 0.0}
    eng = _engine(AlertRule("r", "s", cond, resolve_s=10.0), clock)
    cond.value = {"x": 1}
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == FIRING
    # Condition clears → resolving, still ACTIVE (unhealthy).
    clock["t"] = 1.0
    cond.value = None
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == RESOLVING
    assert not eng.doc()["healthy"]
    assert _firing("r") == 1  # not resolved yet
    # Flap: condition returns mid-hysteresis → re-arms firing with NO
    # second alert_firing event and no gauge double-count.
    clock["t"] = 5.0
    cond.value = {"x": 2}
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == FIRING
    assert _firing("r") == 1
    assert sum(1 for e in event_log if e[0] == "alert_firing") == 1
    # Clear and hold past resolve_s → resolved.
    clock["t"] = 6.0
    cond.value = None
    eng.evaluate({})
    clock["t"] = 15.9
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == RESOLVING
    clock["t"] = 16.0
    eng.evaluate({})
    assert eng.doc()["rules"][0]["state"] == OK
    assert eng.doc()["healthy"]
    assert _firing("r") == 0
    assert sum(1 for e in event_log if e[0] == "alert_resolved") == 1
    # Every state change booked: firing x2 (initial + flap re-arm),
    # resolving x2, ok x1 — reconstructible from the counter alone.
    assert _transitions("r", FIRING) == 2
    assert _transitions("r", RESOLVING) == 2
    assert _transitions("r", OK) == 1


def test_broken_rule_predicate_never_raises():
    def boom(ctx):
        raise RuntimeError("rule bug")

    eng = _engine(AlertRule("r", "s", boom), {"t": 0.0})
    doc = eng.evaluate({})
    assert doc["healthy"]  # a broken rule reads as clear, not as a crash


def test_duplicate_rule_names_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        HealthEngine([
            AlertRule("r", "a", lambda ctx: None),
            AlertRule("r", "b", lambda ctx: None),
        ])


# ---------------------------------------------------------------------------
# built-in rules over scripted snapshots


def _lag_snap(lag: float) -> dict:
    return {
        "kta_follow_lag_records": {
            "type": "gauge",
            "samples": [{"labels": {}, "value": lag}],
        }
    }


def _cfg_fast(**kw) -> HealthConfig:
    base = dict(
        eval_interval_s=0.001, for_s=2.0, resolve_s=2.0,
        lag_window_s=2.0, lag_min_growth=10,
    )
    base.update(kw)
    return HealthConfig(**base)


def test_lag_growth_fires_and_resolves():
    clock = {"t": 0.0}
    eng = HealthEngine(
        built_in_rules(_cfg_fast()), cfg=_cfg_fast(),
        clock=lambda: clock["t"],
    )
    for t, lag in [(0, 0), (1, 100), (2, 300), (3, 700), (4, 1500),
                   (5, 3000), (6, 6000)]:
        clock["t"] = float(t)
        doc = eng.evaluate(_lag_snap(lag))
    assert not doc["healthy"]
    row = [r for r in doc["firing"] if r["rule"] == "lag-growth"][0]
    assert row["evidence"]["eta"] == "inf"
    assert row["evidence"]["growth_per_s"] > 0
    # Heal: lag collapses to 0 and stays there past resolve_s.
    for t in range(7, 12):
        clock["t"] = float(t)
        doc = eng.evaluate(_lag_snap(0))
    assert doc["healthy"]
    assert _firing("lag-growth") == 0


def test_lag_shrinking_never_fires():
    clock = {"t": 0.0}
    eng = HealthEngine(
        built_in_rules(_cfg_fast()), cfg=_cfg_fast(),
        clock=lambda: clock["t"],
    )
    for t, lag in enumerate([10000, 8000, 6000, 4000, 2000, 500]):
        clock["t"] = float(t)
        doc = eng.evaluate(_lag_snap(lag))
    # Behind but catching up = healthy.
    assert doc["healthy"]


def test_degraded_partitions_rule():
    clock = {"t": 0.0}
    eng = HealthEngine(
        built_in_rules(_cfg_fast(resolve_s=1.0)),
        cfg=_cfg_fast(resolve_s=1.0), clock=lambda: clock["t"],
    )

    def snap(n):
        return {
            "kta_scan_degraded_partitions": {
                "type": "gauge",
                "samples": [{"labels": {}, "value": n}],
            }
        }

    doc = eng.evaluate(snap(2))
    row = [r for r in doc["firing"] if r["rule"] == "degraded-partitions"]
    assert row and row[0]["evidence"] == {"degraded_partitions": 2}
    # Healed partitions (follow heals them at the head) resolve it.
    clock["t"] = 1.0
    eng.evaluate(snap(0))
    clock["t"] = 2.5
    doc = eng.evaluate(snap(0))
    assert doc["healthy"]


def test_fleet_topic_failure_rule_and_per_topic_scopes():
    clock = {"t": 0.0}
    cfg = _cfg_fast(for_s=0.0, lag_min_growth=1)
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )
    extras = {"topics": {"a": 0, "b": 0}, "failed_topics": ["b"]}
    doc = eng.evaluate({}, extras=extras)
    row = [r for r in doc["firing"] if r["rule"] == "fleet-topic-failure"]
    assert row and row[0]["evidence"]["failed_topics"] == ["b"]
    # Per-topic lag-growth: topic "a" diverges, topic "b" does not.
    for t, lag in [(1, 10), (2, 200), (3, 3000), (4, 30000), (5, 300000)]:
        clock["t"] = float(t)
        doc = eng.evaluate(
            {}, extras={"topics": {"a": lag, "b": 5}, "failed_topics": []},
        )
    scoped = [r for r in doc["firing"] if r["rule"] == "lag-growth"]
    assert [r["topic"] for r in scoped] == ["a"]
    # ?topic= filtering: b's block is healthy, a's is not.
    assert eng.alerts_block(topic="b")["healthy"]
    assert not eng.alerts_block(topic="a")["healthy"]


def test_per_topic_firing_survives_contextless_evaluation():
    """A heartbeat-cadence evaluation (no topic extras) must not drop a
    firing per-topic alert from the published document."""
    clock = {"t": 0.0}
    cfg = _cfg_fast(for_s=0.0, lag_min_growth=1)
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )
    for t, lag in [(0, 10), (1, 1000), (2, 100000), (3, 10000000)]:
        clock["t"] = float(t)
        doc = eng.evaluate(
            {}, extras={"topics": {"a": lag}, "failed_topics": []},
        )
    assert any(r["rule"] == "lag-growth" and r["topic"] == "a"
               for r in doc["firing"])
    # The engine-drive-loop hook evaluates with NO extras: the firing
    # scope must persist in the published document.
    clock["t"] = 4.0
    doc = eng.evaluate({})
    assert any(r["rule"] == "lag-growth" and r["topic"] == "a"
               for r in doc["firing"])
    assert not doc["healthy"]


def test_extras_derived_rule_survives_contextless_evaluation():
    """fleet-topic-failure derives its condition from extras; the
    engine-heartbeat path evaluates with none.  The last topic context
    must carry over, or the alert flaps ok↔firing between polls."""
    clock = {"t": 0.0}
    cfg = _cfg_fast(for_s=0.0)
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )
    doc = eng.evaluate(
        {}, extras={"topics": {"x": 0}, "failed_topics": ["x"]}
    )
    assert any(r["rule"] == "fleet-topic-failure" for r in doc["firing"])
    # Heartbeat evaluation mid-pass: no extras.  Still firing.
    clock["t"] = 1.0
    doc = eng.evaluate({})
    assert any(r["rule"] == "fleet-topic-failure" for r in doc["firing"])
    assert _transitions("fleet-topic-failure", OK) == 0  # no flap
    # The next poll boundary reports the topic recovered: resolves.
    clock["t"] = 2.0
    doc = eng.evaluate(
        {}, extras={"topics": {"x": 0}, "failed_topics": []}
    )
    assert doc["healthy"]


def test_throughput_regression_rate_uses_actual_span():
    """Sparse evaluation cadence: the 'recent' observation can be older
    than the nominal window, and the rate must divide by the real span
    — a service folding at exactly the drop threshold must fire."""
    clock = {"t": 0.0}
    # A 25s window against a 10s cadence: the nearest >=25s-old point is
    # 30s old, so dividing its delta by the nominal 25 would inflate a
    # true 450/s (0.45x the baseline — must fire at the 0.5x threshold)
    # to 540/s (0.54x — silently missed).
    cfg = _cfg_fast(
        for_s=0.0, throughput_window_s=25.0, throughput_baseline_s=120.0,
        min_baseline_rate=10.0,
    )
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )

    def snap(records):
        s = _lag_snap(500)
        s["kta_scan_records_total"] = {
            "type": "counter",
            "samples": [{"labels": {}, "value": records}],
        }
        return s

    t, records = 0.0, 0.0
    while t < 120.0:
        eng.evaluate(snap(records))
        t += 10.0
        clock["t"] = t
        records += 10_000.0
    for _ in range(4):
        eng.evaluate(snap(records))
        t += 10.0
        clock["t"] = t
        records += 4_500.0
    doc = eng.evaluate(snap(records))
    rows = [r for r in doc["firing"] if r["rule"] == "throughput-regression"]
    assert rows, doc["rules"]
    assert rows[0]["evidence"]["recent_per_s"] == pytest.approx(450.0)


def test_throughput_regression_requires_lag():
    clock = {"t": 0.0}
    cfg = _cfg_fast(
        for_s=0.0, throughput_window_s=2.0, throughput_baseline_s=8.0,
        min_baseline_rate=10.0,
    )
    eng = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )

    def snap(records, lag):
        s = _lag_snap(lag)
        s["kta_scan_records_total"] = {
            "type": "counter",
            "samples": [{"labels": {}, "value": records}],
        }
        return s

    # Healthy baseline: 1000 rec/s for 8s, then collapse to ~0 while
    # lag remains — regression.  (Lag held constant so lag-growth stays
    # quiet and this asserts the throughput rule alone.)
    records = 0
    for t in range(9):
        clock["t"] = float(t)
        records = t * 1000
        doc = eng.evaluate(snap(records, lag=500))
    for t in range(9, 12):
        clock["t"] = float(t)
        doc = eng.evaluate(snap(records, lag=500))
    rows = [r for r in doc["firing"] if r["rule"] == "throughput-regression"]
    assert rows and rows[0]["evidence"]["recent_per_s"] < 100
    # The same collapse at the HEAD (lag 0) is a healthy idle service.
    eng2 = HealthEngine(
        built_in_rules(cfg), cfg=cfg, clock=lambda: clock["t"],
    )
    for t in range(12):
        clock["t"] = float(t)
        doc = eng2.evaluate(snap(min(t, 8) * 1000, lag=0))
    assert doc["healthy"]


# ---------------------------------------------------------------------------
# history store


def _store(tmp_path, clk, **kw):
    kw.setdefault("max_bytes", 1 << 16)
    return HistoryStore(str(tmp_path / "hist"), clock=lambda: clk["t"], **kw)


def test_history_round_trip_and_downsample_exactness(tmp_path):
    clk = {"t": 1000.0}
    s = _store(tmp_path, clk)
    s.register_kinds({"records": "cum", "depth": "inst"})
    for i in range(8):
        clk["t"] = 1000.0 + i
        s.append({"records": i * 100.0, "depth": float(i)})
    w = s.window()
    assert w["t"] == [1000.0 + i for i in range(8)]
    assert w["tracks"]["records"] == [i * 100.0 for i in range(8)]
    assert w["kinds"] == {"depth": "inst", "records": "cum"}
    # Tier 1 = pairwise downsample: cumulative keeps the LAST value
    # (delta-exact), instantaneous averages.
    t1 = s.tier_rows(1)
    assert [r[2]["records"] for r in t1] == [100.0, 300.0, 500.0, 700.0]
    assert [r[2]["depth"] for r in t1] == [0.5, 2.5, 4.5, 6.5]
    # Windowed query bounds [t0, t1].
    sub = s.window(t0=1002.0, t1=1004.0)
    assert sub["t"] == [1002.0, 1003.0, 1004.0]
    # Delta/rate algebra over the window.
    assert track_delta(w, "records") == 700.0
    assert track_rate(w, "records") == pytest.approx(100.0)
    s.close()


def test_history_restart_continuity_and_epoch_reset(tmp_path):
    clk = {"t": 2000.0}
    s = _store(tmp_path, clk)
    s.register_kinds({"records": "cum"})
    for i in range(5):
        clk["t"] = 2000.0 + i
        s.append({"records": 1000.0 + i * 100.0})
    s.close()
    # Restart after a 60s outage: the pre-restart window is served, the
    # epoch bumps, and the process's counters restart from zero.
    clk["t"] = 2064.0
    s2 = _store(tmp_path, clk)
    assert s2.epoch == 2
    w = s2.window()
    assert len(w["t"]) == 5  # pre-restart rows survived the reopen
    for i in range(3):
        clk["t"] = 2064.0 + i
        s2.append({"records": i * 50.0})
    w = s2.window()
    assert len(w["t"]) == 8
    assert set(w["epoch"]) == {1, 2}
    # Reset-aware delta: 400 within epoch 1, 0 at the boundary row
    # (counter restarted at 0), 100 within epoch 2 = 500 — never a
    # negative delta from the reset.
    assert track_delta(w, "records") == 500.0
    # The outage gap stays IN the denominator: 500 records over the full
    # 66s wall span, not over the ~7s of sampled time.
    assert track_rate(w, "records") == pytest.approx(500.0 / 66.0)
    s2.close()


def test_history_crash_leaves_open_segment_recoverable(tmp_path):
    clk = {"t": 3000.0}
    s = _store(tmp_path, clk)
    s.register_kinds({"v": "cum"})
    for i in range(4):
        clk["t"] = 3000.0 + i
        s.append({"v": float(i)})
    # Simulate SIGKILL: no close().  Truncate the open segment mid-line
    # (the write in flight when the process died).
    open_path = os.path.join(str(tmp_path / "hist"), "tier0", "open.jsonl")
    data = open(open_path, "rb").read()
    with open(open_path, "wb") as f:
        f.write(data[:-7])  # sever the last line
    s2 = _store(tmp_path, clk)
    w = s2.window()
    # All complete rows recovered; the severed one skipped, not fatal.
    assert w["tracks"]["v"] == [0.0, 1.0, 2.0]
    s2.close()


def test_history_byte_budget_is_rrd_shaped(tmp_path):
    clk = {"t": 10_000.0}
    s = HistoryStore(
        str(tmp_path / "hist"), max_bytes=8192, tiers=3,
        clock=lambda: clk["t"],
    )
    s.register_kinds({"v": "cum"})
    for i in range(2000):
        clk["t"] = 10_000.0 + i
        s.append({"v": float(i)})
    # The store stayed within its bound (open segments included).
    hist_dir = str(tmp_path / "hist")
    total = sum(
        os.path.getsize(os.path.join(root, f))
        for root, _, files in os.walk(hist_dir)
        for f in files
        if f.endswith(".jsonl")
    )
    assert total <= 8192 * 1.3  # bound + at most one in-flight segment/tier
    # RRD retention: the coarse tier's window reaches further back than
    # tier 0's, and a whole-range query stitches both.
    t0_rows = s.tier_rows(0)
    t2_rows = s.tier_rows(2)
    assert t2_rows[0][0] < t0_rows[0][0]
    w = s.window()
    assert w["t"][0] == t2_rows[0][0]
    assert w["t"][-1] == t0_rows[-1][0]
    assert sorted(w["tiers_used"]) == w["tiers_used"]  # fine → coarse
    assert obs_metrics.HISTORY_ROTATIONS.value > 0
    s.close()


def test_telemetry_session_history_resumes_across_sessions(tmp_path):
    """The CLI wiring end to end: --history-bytes opens the store next
    to the checkpoints, implies the recorder, installs the alert
    engine, and a second session (the restarted service) serves the
    pre-restart window with a bumped epoch."""
    from kafka_topic_analyzer_tpu.obs import telemetry_session

    hist = str(tmp_path / "hist")
    with telemetry_session(history_dir=hist, history_bytes=65536):
        rec = obs_flight.active()
        assert rec is not None  # history implies the recorder
        assert obs_health.active() is not None  # serving surface exists
        obs_metrics.SCAN_RECORDS.inc(10)
        rec.sample_once()
        assert len(obs_history.active().window()["t"]) >= 1
    assert obs_history.active() is None
    assert obs_health.active() is None
    with telemetry_session(history_dir=hist, history_bytes=65536):
        store = obs_history.active()
        w = store.window()
        assert len(w["t"]) >= 1  # the pre-restart window is served
        assert store.epoch == 2
        assert 1 in w["epoch"]


def test_recorder_feeds_history(tmp_path):
    clk = {"t": 0.0}
    rec = FlightRecorder(interval_s=0.5, clock=lambda: clk["t"])
    s = HistoryStore(str(tmp_path / "hist"), clock=lambda: 500.0)
    rec.attach_history(s)
    obs_metrics.SCAN_RECORDS.inc(42)
    rec.sample_once()
    w = s.window()
    assert w["tracks"]["records"] == [42.0]
    # The recorder registered its kind map for downsample policy.
    assert w["kinds"]["records"] == "cum"
    assert w["kinds"]["dispatch_inflight"] == "inst"
    s.close()


def test_recorder_survives_history_sink_failure(tmp_path):
    """Telemetry is best-effort: a dying history sink (full disk,
    vanished directory) detaches — it must not kill the sampler thread
    or fail teardown's closing sample."""
    rec = FlightRecorder(interval_s=0.5, clock=lambda: 0.0)
    s = HistoryStore(str(tmp_path / "hist"))
    rec.attach_history(s)

    def boom(values, t=None):
        raise OSError("disk full")

    s.append = boom
    rec.sample_once()  # must not raise
    rec.sample_once()
    assert len(rec.series()["t"]) == 2  # the live ring kept recording
    assert rec._history is None  # sink detached after the first failure
    s.close()


def test_history_window_sorted_under_clock_regression(tmp_path):
    """An NTP step backwards across a restart: the mirror keeps write
    order (the eviction-prefix invariant) and window() sorts at query
    time, so served rows stay a monotone time axis."""
    clk = {"t": 5000.0}
    s = _store(tmp_path, clk)
    s.register_kinds({"v": "cum"})
    for i in range(3):
        clk["t"] = 5000.0 + i
        s.append({"v": float(i)})
    s.close()
    clk["t"] = 4990.0  # the clock stepped back before the restart
    s2 = _store(tmp_path, clk)
    for i in range(3):
        clk["t"] = 4990.0 + i
        s2.append({"v": float(i)})
    w = s2.window()
    assert w["t"] == sorted(w["t"])
    assert len(w["t"]) == 6
    s2.close()


# ---------------------------------------------------------------------------
# trend doctor


def _win(t, tracks, epoch=None):
    return {
        "t": t,
        "epoch": epoch or [1] * len(t),
        "tracks": tracks,
    }


def test_trend_throughput_droop():
    w = _win(
        [0.0, 10.0, 20.0, 30.0, 35.0, 40.0],
        {"records": [0.0, 10_000.0, 20_000.0, 30_000.0, 30_050.0, 30_100.0]},
    )
    kinds = [f["kind"] for f in doctor.diagnose_trends(w)]
    assert "throughput-droop" in kinds


def test_trend_lag_divergence():
    w = _win(
        [0.0, 10.0, 20.0, 30.0, 40.0],
        {"follow_lag": [100.0, 200.0, 400.0, 800.0, 1600.0]},
    )
    f = [x for x in doctor.diagnose_trends(w) if x["kind"] == "lag-divergence"]
    assert f and f[0]["evidence"]["eta"] == "inf"
    assert f[0]["evidence"]["growth_per_s"] == pytest.approx(1500 / 40.0)


def test_trend_retry_storm_and_quiet_window():
    quiet = _win(
        [0.0, 10.0, 20.0, 30.0, 40.0],
        {
            "records": [0, 1000, 2000, 3000, 4000],
            "backoff_sleeps": [0.0, 0.0, 0.0, 0.0, 0.0],
            "follow_lag": [0.0, 0.0, 0.0, 0.0, 0.0],
        },
    )
    assert doctor.diagnose_trends(quiet) == []
    storm = _win(
        [0.0, 10.0, 20.0, 30.0, 34.0, 40.0],
        {"backoff_sleeps": [0.0, 1.0, 1.0, 1.0, 30.0, 60.0]},
    )
    kinds = [f["kind"] for f in doctor.diagnose_trends(storm)]
    assert "retry-storm" in kinds


def test_trend_verify_bound_warm_reaudit():
    w = _win(
        [0.0, 10.0, 20.0, 30.0, 40.0],
        {
            "cache_verify_s": [0.0, 4.0, 8.0, 12.0, 16.0],
            "cache_hit_bytes": [0.0, 1e8, 2e8, 3e8, 4e8],
        },
    )
    f = [x for x in doctor.diagnose_trends(w) if x["kind"] == "verify-bound"]
    assert f and f[0]["evidence"]["verify_share"] == pytest.approx(0.4)


def test_trend_epoch_reset_not_a_droop():
    """A restart's counter reset must not read as negative throughput."""
    w = _win(
        [0.0, 10.0, 20.0, 30.0, 40.0],
        {"records": [10_000.0, 20_000.0, 30_000.0, 2_500.0, 5_000.0]},
        epoch=[1, 1, 1, 2, 2],
    )
    assert track_delta(w, "records") == pytest.approx(25_000.0)
    assert track_rate(w, "records") == pytest.approx(25_000.0 / 40.0)


# ---------------------------------------------------------------------------
# cache verify instrumentation (satellite)


def test_segment_cache_books_verify_seconds_and_hit_bytes(tmp_path):
    from kafka_topic_analyzer_tpu.io import objstore
    from kafka_topic_analyzer_tpu.io.objstore import SegmentCache

    cache = SegmentCache(str(tmp_path / "cache"), 1 << 20, "store-key")
    data = bytes(range(256)) * 512  # 128 KiB
    # The trust latch is process-wide; drop any residue from earlier
    # tests so first-touch verification is actually exercised here.
    objstore._PROCESS_TRUSTED.discard(cache._digest("chunk-0", len(data)))
    cache.put("chunk-0", len(data), data)
    assert obs_metrics.SEGSTORE_CACHE_VERIFY_SECONDS.value == 0.0
    got = cache.get("chunk-0", len(data))
    assert bytes(got) == data  # hits are zero-copy memmap views
    assert obs_metrics.SEGSTORE_CACHE_HIT_BYTES.value == len(data)
    assert obs_metrics.SEGSTORE_CACHE_VERIFY_SECONDS.value > 0.0
    # Second hit of a verified digest is latched: the hash is skipped
    # (verify-seconds stands still) and the latched counter books it.
    spent = obs_metrics.SEGSTORE_CACHE_VERIFY_SECONDS.value
    again = cache.get("chunk-0", len(data))
    assert bytes(again) == data
    assert obs_metrics.SEGSTORE_CACHE_VERIFY_SECONDS.value == spent
    assert obs_metrics.SEGSTORE_CACHE_VERIFY_LATCHED.value == 1


# ---------------------------------------------------------------------------
# HTTP surfaces: /healthz + /history


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    )


def test_healthz_and_history_routing(tmp_path):
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter

    exporter = PrometheusExporter(0)
    try:
        # 404: no engine, no store.
        for path in ("/healthz", "/history"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(exporter.port, path)
            assert ei.value.code == 404
        # 503 pre-first-eval: an unevaluated service must not claim
        # liveness.
        eng = HealthEngine([AlertRule("r", "s", lambda ctx: ctx.extras.get("on"))])
        obs_health.set_active(eng)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exporter.port, "/healthz")
        assert ei.value.code == 503
        # 200 healthy, with the document body.
        eng.evaluate({})
        with _get(exporter.port, "/healthz") as resp:
            doc = json.loads(resp.read().decode())
        assert doc["healthy"] and doc["evaluations"] == 1
        # 503 firing, with the firing-rule JSON as the body.
        eng.evaluate({}, extras={"on": {"why": "test"}})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exporter.port, "/healthz")
        assert ei.value.code == 503
        body = json.loads(ei.value.read().decode())
        assert body["firing"][0]["rule"] == "r"
        assert body["firing"][0]["evidence"] == {"why": "test"}
        # /history: windowed queries over the active store.
        clk = {"t": 100.0}
        store = HistoryStore(str(tmp_path / "hist"), clock=lambda: clk["t"])
        store.register_kinds({"records": "cum"})
        for i in range(6):
            clk["t"] = 100.0 + i
            store.append({"records": float(i)})
        obs_history.set_active(store)
        with _get(exporter.port, "/history") as resp:
            w = json.loads(resp.read().decode())
        assert w["tracks"]["records"] == [float(i) for i in range(6)]
        with _get(
            exporter.port, "/history?t0=102&t1=104&tracks=records"
        ) as resp:
            w = json.loads(resp.read().decode())
        assert w["t"] == [102.0, 103.0, 104.0]
        assert list(w["tracks"]) == ["records"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(exporter.port, "/history?t0=notanumber")
        assert ei.value.code == 400
        # The alert instruments ride the normal scrape.
        with _get(exporter.port, "/metrics") as resp:
            text = resp.read().decode()
        assert "kta_health_evaluations_total 2" in text
        assert 'kta_alerts_firing{rule="r"} 1' in text
        assert 'kta_alerts_transitions_total{rule="r",state="firing"} 1' in text
        store.close()
    finally:
        exporter.close()


# ---------------------------------------------------------------------------
# service integration: lag-divergence fault → /healthz flip → heal → 200


class _DivergingSource:
    """A scripted topic whose head runs away while 'stalled': watermark
    polls see a growing end offset but no records are servable, so the
    follow cursor cannot advance — the canonical lag-divergence fault.
    Healing serves the real (synthetic) records and pins the head."""

    def __init__(self, inner: SyntheticSource):
        self.inner = inner
        self.stalled = True
        self._fake_head = dict(inner.watermarks()[1])

    def partitions(self):
        return self.inner.partitions()

    def is_empty(self):
        return False

    def watermarks(self):
        start, end = self.inner.watermarks()
        return start, dict(self._fake_head)

    def refresh_watermarks(self):
        if self.stalled:
            for p in self._fake_head:
                self._fake_head[p] += 50  # the head keeps moving
        else:
            self._fake_head = dict(self.inner.watermarks()[1])
        return self.watermarks()

    def batches(self, batch_size, partitions=None, start_at=None):
        if self.stalled:
            return iter(())
        return self.inner.batches(
            batch_size, partitions=partitions, start_at=start_at
        )


def test_follow_lag_divergence_flips_healthz_and_heals(event_log):
    from kafka_topic_analyzer_tpu.obs.exporters import PrometheusExporter

    spec = SyntheticSpec(
        num_partitions=2, messages_per_partition=100, keys_per_partition=20
    )
    src = _DivergingSource(SyntheticSource(spec))
    cfg = _cfg_fast(
        for_s=0.05, resolve_s=0.05, lag_window_s=0.08, lag_min_growth=1
    )
    engine = HealthEngine(built_in_rules(cfg), cfg=cfg)
    follow = FollowConfig(
        poll_interval_s=0.02, idle_backoff_max_s=0.04, window_count=0
    )
    backend = CpuExactBackend(
        AnalyzerConfig(num_partitions=2, batch_size=64), init_now_s=10**10
    )
    svc = FollowService(
        "diverge.topic", src, backend, 64, follow, health=engine,
    )
    exporter = PrometheusExporter(0)

    def probe():
        try:
            with _get(exporter.port, "/healthz") as resp:
                return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            body = e.read().decode()
            try:
                return e.code, json.loads(body)
            except ValueError:
                # send_error HTML (pre-first-eval 503): no document yet.
                return e.code, {"firing": []}

    def _wait_for(pred, what, timeout_s=20.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.01)
        raise AssertionError(f"timed out waiting for {what}")

    errors = []

    def driver():
        try:
            # Fault injected from the start: /healthz must flip to 503
            # with lag-growth in the firing set.
            _wait_for(
                lambda: probe()[0] == 503
                and any(
                    r["rule"] == "lag-growth" for r in probe()[1]["firing"]
                ),
                "healthz 503 on lag divergence",
            )
            # Heal: serve the real records, pin the head, wait for 200.
            src.stalled = False
            _wait_for(
                lambda: probe()[0] == 200, "healthz 200 after heal+resolve"
            )
        except BaseException as e:
            errors.append(e)
        finally:
            svc.request_stop("test")

    t = threading.Thread(target=driver)
    t.start()
    result = svc.run()
    t.join()
    exporter.close()
    if errors:
        raise errors[0]
    # The service folded the real topic exactly once healed.
    assert result.metrics.overall_count == 200
    fired = [f for e, f in event_log if e == "alert_firing"]
    resolved = [f for e, f in event_log if e == "alert_resolved"]
    assert any(f["rule"] == "lag-growth" for f in fired)
    assert any(f["rule"] == "lag-growth" for f in resolved)
    # /report.json documents carry the health block.
    doc = svc.state.snapshot()
    assert doc is not None and "health" in doc and doc["health"]["healthy"]


def test_follow_watermark_outage_alert():
    """A killed broker: refresh give-ups accumulate and the
    watermark-refresh-outage alert fires (the service keeps polling the
    stale snapshot — PR 11's hardening — but /healthz says so)."""
    records = {p: [
        (i, 1_600_000_000_000 + i, f"k{i}".encode(), b"v" * 10)
        for i in range(40)
    ] for p in range(2)}
    cfg = _cfg_fast(for_s=0.05, resolve_s=0.05, outage_window_s=30.0)
    engine = HealthEngine(built_in_rules(cfg), cfg=cfg)
    follow = FollowConfig(
        poll_interval_s=0.02, idle_backoff_max_s=0.04, window_count=0
    )
    broker = FakeBroker("outage.topic", records).start()
    src = KafkaWireSource(
        f"127.0.0.1:{broker.port}", "outage.topic",
        overrides={
            "retry.backoff.ms": "2",
            "reconnect.backoff.max.ms": "8",
            "transport.retry.budget": "2",
        },
    )
    backend = CpuExactBackend(
        AnalyzerConfig(num_partitions=2, batch_size=64), init_now_s=10**10
    )
    svc = FollowService(
        "outage.topic", src, backend, 64, follow, health=engine,
    )
    errors = []

    def driver():
        try:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                doc = engine.doc()
                if doc is not None and svc.passes >= 1:
                    break
                time.sleep(0.01)
            broker.kill()  # every re-poll now exhausts its budget
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                doc = engine.doc()
                if doc and any(
                    r["rule"] == "watermark-refresh-outage"
                    for r in doc["firing"]
                ):
                    return
                time.sleep(0.01)
            raise AssertionError("watermark outage alert never fired")
        except BaseException as e:
            errors.append(e)
        finally:
            svc.request_stop("test")

    t = threading.Thread(target=driver)
    t.start()
    svc.run()
    t.join()
    src.close()
    broker.stop()
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------------
# byte-identity: full service-observability stack on vs off


N_PARTS, N_REC = 3, 240


def _mk_records(partition: int, n: int):
    return [
        (
            i,
            1_600_000_000_000 + i * 1000,
            f"k{partition}-{i % 29}".encode() if i % 5 else None,
            bytes(20 + (i % 13)) if i % 7 else None,
        )
        for i in range(n)
    ]


def _scan_cfg():
    return AnalyzerConfig(
        num_partitions=N_PARTS, batch_size=64,
        count_alive_keys=True, alive_bitmap_bits=16,
        enable_hll=True, hll_p=8,
    )


def _full_doc(result) -> dict:
    return {
        "metrics": result.metrics.to_dict(
            result.start_offsets, result.end_offsets
        ),
        "degraded": result.degraded_partitions,
        "corrupt": result.corrupt_partitions,
    }


def _with_stack(tmp_path, tag):
    """Context: recorder + history + alert engine, all active."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        rec = FlightRecorder(interval_s=0.002)
        store = HistoryStore(str(tmp_path / f"hist-{tag}"))
        rec.attach_history(store)
        cfg = _cfg_fast(eval_interval_s=0.005)
        engine = HealthEngine(built_in_rules(cfg), cfg=cfg)
        obs_flight.set_active(rec)
        obs_history.set_active(store)
        obs_health.set_active(engine)
        rec.start()
        try:
            yield engine
        finally:
            rec.stop()
            store.close()
            obs_flight.set_active(None)
            obs_history.set_active(None)
            obs_health.set_active(None)

    return ctx()


@pytest.mark.parametrize("workers,superbatch", [(1, 1), (4, 4)])
def test_scan_identity_full_stack_wire(tmp_path, workers, superbatch):
    records = {p: _mk_records(p, N_REC) for p in range(N_PARTS)}

    def scan(stack: bool):
        import contextlib

        cm = (
            _with_stack(tmp_path, f"w{workers}k{superbatch}-{stack}")
            if stack
            else contextlib.nullcontext()
        )
        with cm:
            with FakeBroker("health.topic", records,
                            max_records_per_fetch=60) as broker:
                src = KafkaWireSource(
                    f"127.0.0.1:{broker.port}", "health.topic",
                    overrides={"retry.backoff.ms": "5"},
                )
                result = run_scan(
                    "health.topic", src,
                    TpuBackend(
                        _scan_cfg(), init_now_s=10**10,
                        dispatch=DispatchConfig(superbatch=superbatch),
                    ),
                    64, ingest_workers=workers,
                )
                src.close()
        return _full_doc(result)

    assert scan(stack=True) == scan(stack=False)


def test_follow_identity_full_stack(tmp_path):
    """A follow service with the whole stack on folds byte-identically
    to the batch referee of the same records."""
    phase1 = {p: _mk_records(p, 120) for p in range(N_PARTS)}
    phase2 = {
        p: _mk_records(p, 180)[120:] for p in range(N_PARTS)
    }
    full = {p: phase1[p] + phase2[p] for p in range(N_PARTS)}

    with FakeBroker("health.follow", full, max_records_per_fetch=48) as b:
        src = KafkaWireSource(
            f"127.0.0.1:{b.port}", "health.follow",
            overrides={"retry.backoff.ms": "5"},
        )
        referee = _full_doc(run_scan(
            "health.follow", src,
            TpuBackend(_scan_cfg(), init_now_s=10**10), 64,
        ))
        src.close()
    default_registry().reset()

    with _with_stack(tmp_path, "follow"):
        follow = FollowConfig(
            poll_interval_s=0.02, idle_backoff_max_s=0.05,
            window_secs=5.0, window_count=4,
        )
        with FakeBroker("health.follow", phase1,
                        max_records_per_fetch=48) as broker:
            src = KafkaWireSource(
                f"127.0.0.1:{broker.port}", "health.follow",
                overrides={"retry.backoff.ms": "5"},
            )
            svc = FollowService(
                "health.follow", src,
                TpuBackend(_scan_cfg(), init_now_s=10**10), 64, follow,
            )
            errors = []

            def driver():
                try:
                    deadline = time.monotonic() + 20.0
                    while time.monotonic() < deadline:
                        doc = svc.state.snapshot()
                        if doc and doc["overall"]["count"] >= N_PARTS * 120:
                            break
                        time.sleep(0.01)
                    for p in range(N_PARTS):
                        broker.produce(p, phase2[p])
                    deadline = time.monotonic() + 20.0
                    while time.monotonic() < deadline:
                        doc = svc.state.snapshot()
                        if doc and doc["overall"]["count"] >= N_PARTS * 180:
                            break
                        time.sleep(0.01)
                except BaseException as e:
                    errors.append(e)
                finally:
                    svc.request_stop("test")

            t = threading.Thread(target=driver)
            t.start()
            result = svc.run()
            t.join()
            src.close()
            if errors:
                raise errors[0]
    assert _full_doc(result) == referee
    # The service used the session-installed engine (health block rode
    # the published reports).
    doc = svc.state.snapshot()
    assert "health" in doc


# ---------------------------------------------------------------------------
# fleet: per-topic verdicts in the rollup (satellite) + health context


def test_fleet_rollup_carries_verdicts_without_publishing(tmp_path):
    """The satellite fix: a fleet run that publishes NO reports (no
    --metrics-port) still attributes every topic's pass — the rollup's
    verdict column and verdict_counts fill in."""
    from kafka_topic_analyzer_tpu.fleet.scheduler import (
        FleetScheduler,
        TopicSeed,
    )
    from kafka_topic_analyzer_tpu.fleet.service import FleetService

    specs = {
        "fleet.a": SyntheticSpec(
            num_partitions=2, messages_per_partition=150,
            keys_per_partition=20, seed=1,
        ),
        "fleet.b": SyntheticSpec(
            num_partitions=2, messages_per_partition=90,
            keys_per_partition=10, seed=2,
        ),
    }

    cfg = _cfg_fast(for_s=0.0)
    engine = HealthEngine(built_in_rules(cfg), cfg=cfg)
    svc = FleetService(
        [TopicSeed(name=t, partitions=2) for t in specs],
        lambda t: SyntheticSource(specs[t]),
        lambda t, parts, grant: CpuExactBackend(
            AnalyzerConfig(num_partitions=parts, batch_size=64),
            init_now_s=10**10,
        ),
        64,
        FleetScheduler(2, 2, 2),
        publish_reports=False,
        health=engine,
    )
    fr = svc.run_batch()
    assert all(s.status == "ok" for s in fr.statuses.values())
    for s in fr.statuses.values():
        assert s.verdict  # every pass attributed, nothing published
    statuses = fr.rollup["fleet"]["statuses"]
    assert all(statuses[t]["verdict"] for t in specs)
    vc = fr.rollup["fleet"]["verdict_counts"]
    assert sum(vc.values()) == len(specs)
    # The health engine evaluated at the wave boundary and the rollup
    # carries its document.
    assert fr.rollup["health"]["healthy"]
    assert engine.evaluations >= 1
