"""Transaction control batches (commit/abort markers, batch attribute bit
5): consumers never see them as messages — librdkafka filters them at any
isolation level, so the reference's counters exclude them — but their
offsets are part of the log and the scan must advance past them.

Covers all three decode paths (Python iter_batch_frames, native
scan/decode of whole record sets) and the full wire scan."""

from __future__ import annotations

import numpy as np
import pytest

from kafka_topic_analyzer_tpu.io import kafka_codec as kc
from kafka_topic_analyzer_tpu.io.native import (
    decode_record_set_native,
    native_available,
    scan_record_set_native,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native shim unavailable"
)


def _record_set():
    """[2 data records][commit marker][abort marker][2 data records]."""
    return b"".join(
        [
            kc.encode_record_batch(
                [(0, 1000, b"k0", b"v0"), (1, 1001, b"k1", b"v1")]
            ),
            kc.encode_control_batch(2, 1002, commit=True),
            kc.encode_control_batch(3, 1003, commit=False),
            kc.encode_record_batch(
                [(4, 1004, b"k4", b"v4"), (5, 1005, b"k5", None)]
            ),
        ]
    )


def test_iter_batch_frames_skips_control_records():
    frames = list(kc.iter_batch_frames(_record_set(), verify_crc=True))
    assert [f.num_records for f in frames] == [2, 0, 0, 2]
    # Control frames still cover their offsets.
    assert [f.end_offset for f in frames] == [2, 3, 4, 6]
    recs = [
        off for f in frames for off, _ in kc.decode_frame_records(f)
    ]
    assert recs == [0, 1, 4, 5]


def test_native_scan_and_decode_skip_control_records():
    buf = _record_set()
    n, consumed, covered = scan_record_set_native(buf, verify_crc=True)
    assert (n, consumed, covered) == (4, len(buf), 6)
    soa, used, covered2 = decode_record_set_native(buf, verify_crc=True)
    assert used == len(buf) and covered2 == 6
    assert soa["offsets"].tolist() == [0, 1, 4, 5]
    assert soa["value_null"].tolist() == [0, 0, 0, 1]


def test_control_only_record_set_still_advances():
    buf = kc.encode_control_batch(7, 1000) + kc.encode_control_batch(8, 1001)
    n, consumed, covered = scan_record_set_native(buf)
    assert (n, consumed, covered) == (0, len(buf), 9)
    soa, used, covered2 = decode_record_set_native(buf)
    assert used == len(buf) and covered2 == 9
    assert len(soa["offsets"]) == 0


def test_wire_scan_excludes_markers_from_metrics(tmp_path):
    """End-to-end: a transactional topic's markers don't count as
    messages (reference parity: librdkafka's consumer hides them,
    src/kafka.rs:92-135 only ever sees real messages)."""
    from tests.fake_broker import FakeBroker
    from kafka_topic_analyzer_tpu.backends.cpu import CpuExactBackend
    from kafka_topic_analyzer_tpu.config import AnalyzerConfig
    from kafka_topic_analyzer_tpu.engine import run_scan
    from kafka_topic_analyzer_tpu.io.kafka_wire import KafkaWireSource

    records = {
        0: [(i, 1000 + i, b"k%d" % i, b"v%d" % i) for i in range(6)],
    }
    broker = FakeBroker("txn-topic", records, control_offsets={0: {2, 5}})
    with broker:
        src = KafkaWireSource(f"127.0.0.1:{broker.port}", "txn-topic")
        cfg = AnalyzerConfig(num_partitions=1, batch_size=64)
        result = run_scan(
            "txn-topic", src, CpuExactBackend(cfg, init_now_s=0), 64
        )
        src.close()
    m = result.metrics
    # 6 log slots, 2 are markers → 4 messages.
    assert m.overall_count == 4
    assert int(m.per_partition[0, 0]) == 4