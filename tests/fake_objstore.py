"""FakeObjectStore: the latency/fault-injectable S3-shaped test server.

A thin scripting layer over the real local server implementation
(kafka_topic_analyzer_tpu/tools/objstore_serve.py — the same code the
bench drives), so tests can enqueue per-object fault scripts:

    with FakeObjectStore(seg_dir) as store:
        store.script("t-0.ktaseg", "drop", ("status", 503))
        ...  # the next two BODY GETs of t-0 fail those ways, then serve

Scripts apply to whole-body GETs only by default (the fetch path under
test); header/list probes stay clean unless ``body_only=False``.

Conditional writes (the lease transport) script the same way through
``script_put`` — lost-rename/ambiguous PUTs, competing-writer races, and
clock-skewed lease bodies (see objstore_serve.PutFaultHook).
"""

from __future__ import annotations

import threading
from collections import Counter
from typing import Optional, Tuple

from kafka_topic_analyzer_tpu.tools.objstore_serve import (
    ObjectStoreHttpServer,
)


class FakeObjectStore(ObjectStoreHttpServer):
    def __init__(self, root, **kw):
        self._script_lock = threading.Lock()
        #: key -> list of (action, body_only) consumed FIFO per matching GET.
        self._scripts: "dict[str, list]" = {}
        #: key -> list of actions consumed FIFO per PUT of that key.
        self._put_scripts: "dict[str, list]" = {}
        #: Whole-body GETs observed per key (fault-scripted ones included).
        self.body_gets: "Counter[str]" = Counter()
        #: PUTs observed per key (fault-scripted ones included).
        self.puts: "Counter[str]" = Counter()
        super().__init__(
            root, fault_hook=self._hook, put_fault_hook=self._put_hook, **kw
        )

    def script(self, key: str, *actions, body_only: bool = True) -> None:
        """Enqueue fault actions for successive GETs of ``key`` (see
        objstore_serve.FaultHook for the action vocabulary)."""
        with self._script_lock:
            self._scripts.setdefault(key, []).extend(
                (a, body_only) for a in actions
            )

    def script_put(self, key: str, *actions) -> None:
        """Enqueue fault actions for successive PUTs of ``key`` (see
        objstore_serve.PutFaultHook for the action vocabulary)."""
        with self._script_lock:
            self._put_scripts.setdefault(key, []).extend(actions)

    def _put_hook(self, key: str, body: bytes, index: int):
        with self._script_lock:
            self.puts[key] += 1
            queue = self._put_scripts.get(key)
            if not queue:
                return None
            return queue.pop(0)

    def _hook(
        self,
        key: str,
        rng: "Optional[Tuple[Optional[int], int]]",
        index: int,
    ):
        with self._script_lock:
            if rng is None:
                self.body_gets[key] += 1
            queue = self._scripts.get(key)
            if not queue:
                return None
            action, body_only = queue[0]
            if body_only and rng is not None:
                return None
            queue.pop(0)
            return action
